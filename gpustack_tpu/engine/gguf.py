"""GGUF checkpoint loading: parse + dequantize into the HF tensor names
the existing loader already maps.

Reference parity: the reference serves GGUF checkpoints through
llama-box/llama.cpp and sizes them with gguf-parser (SURVEY §2.9; the
native C++ ``model-meta`` tool already covers the sizing half). This
module covers the SERVING half TPU-first: instead of a CPU/GPU GGML
runtime, GGUF tensors are dequantized to bf16 at load and run through
the same jitted transformer as safetensors checkpoints (optionally
re-quantized to int8 weight-only for the MXU path).

Format: GGUF v2/v3 (little-endian) — header, typed metadata KV section,
tensor info table, aligned data section. Quantizations supported:
F32/F16/BF16 passthrough, Q8_0, Q4_0/Q4_1, Q5_0/Q5_1, and the K-quant
super-block formats Q2_K/Q3_K/Q4_K/Q5_K/Q6_K (what real-world Q4_K_M /
Q5_K_M / Q6_K checkpoints ship). gguf-split multi-file checkpoints are
resolved via ``split.count`` metadata (gguf_shard_paths). MoE exports
(mixtral/qwen3moe-class fused ffn_*_exps tensors + ffn_gate_inp
router) load too; shared-expert (shexp) exports are rejected loudly.

Tokenizer: a ``tokenizer.json`` sidecar next to the .gguf wins (exact
HF tokenization). Without one, the GGUF's embedded vocab drives exact
DECODING (SentencePiece ``▁``/byte-token conventions) and greedy
longest-match ENCODING — a documented approximation: merges are not
replayed, so token boundaries can differ from the original BPE on rare
strings.
"""

from __future__ import annotations

import logging
import os
import re
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

GGUF_MAGIC = 0x46554747      # "GGUF" little-endian

# metadata value types
_T_U8, _T_I8, _T_U16, _T_I16, _T_U32, _T_I32, _T_F32, _T_BOOL = range(8)
_T_STRING, _T_ARRAY, _T_U64, _T_I64, _T_F64 = 8, 9, 10, 11, 12

_SCALAR_FMT = {
    _T_U8: "<B", _T_I8: "<b", _T_U16: "<H", _T_I16: "<h",
    _T_U32: "<I", _T_I32: "<i", _T_F32: "<f", _T_BOOL: "<?",
    _T_U64: "<Q", _T_I64: "<q", _T_F64: "<d",
}

# ggml tensor types (subset)
GGML_F32, GGML_F16 = 0, 1
GGML_Q4_0, GGML_Q4_1 = 2, 3
GGML_Q5_0, GGML_Q5_1 = 6, 7
GGML_Q8_0 = 8
GGML_Q2_K, GGML_Q3_K, GGML_Q4_K, GGML_Q5_K, GGML_Q6_K = 10, 11, 12, 13, 14
GGML_BF16 = 30

_TYPE_NAMES = {
    0: "F32", 1: "F16", 2: "Q4_0", 3: "Q4_1", 6: "Q5_0", 7: "Q5_1",
    8: "Q8_0", 9: "Q8_1", 10: "Q2_K", 11: "Q3_K", 12: "Q4_K",
    13: "Q5_K", 14: "Q6_K", 15: "Q8_K", 30: "BF16",
}

# bytes per block for each supported quantized type (block = 32 elements
# for the _0/_1 formats, 256 for K-quant super-blocks)
_BLOCK_BYTES = {
    GGML_Q4_0: (32, 18), GGML_Q4_1: (32, 20),
    GGML_Q5_0: (32, 22), GGML_Q5_1: (32, 24),
    GGML_Q8_0: (32, 34),
    GGML_Q2_K: (256, 84), GGML_Q3_K: (256, 110), GGML_Q4_K: (256, 144),
    GGML_Q5_K: (256, 176), GGML_Q6_K: (256, 210),
}


class _Reader:
    def __init__(self, data: memoryview):
        self.data = data
        self.pos = 0

    def scalar(self, vtype: int):
        fmt = _SCALAR_FMT[vtype]
        size = struct.calcsize(fmt)
        (value,) = struct.unpack_from(fmt, self.data, self.pos)
        self.pos += size
        return value

    def string(self) -> str:
        n = self.scalar(_T_U64)
        raw = bytes(self.data[self.pos: self.pos + n])
        self.pos += n
        return raw.decode("utf-8", errors="replace")

    def value(self, vtype: int):
        if vtype == _T_STRING:
            return self.string()
        if vtype == _T_ARRAY:
            etype = self.scalar(_T_U32)
            count = self.scalar(_T_U64)
            return [self.value(etype) for _ in range(count)]
        return self.scalar(vtype)


def read_gguf(
    path: str,
) -> Tuple[Dict[str, Any], List[Tuple[str, tuple, int, int]], int, Any]:
    """Parse a GGUF file → (metadata, tensor_infos, data_start, raw).

    tensor_infos entries are (name, numpy_shape, ggml_type, offset);
    GGUF stores dims fastest-varying-first, so the numpy shape is the
    reverse. ``raw`` is an mmap-backed buffer: metadata-only callers
    (config, tokenizer) touch header pages only, and weight loads page
    tensor data in lazily instead of slurping a multi-GB file three
    times at startup.
    """
    import mmap

    with open(path, "rb") as f:
        try:
            raw = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except (ValueError, OSError):
            raw = f.read()           # empty/special files: plain read
    mv = memoryview(raw)
    try:
        magic, version = struct.unpack_from("<II", mv, 0)
        if magic != GGUF_MAGIC:
            raise ValueError(f"{path!r} is not a GGUF file")
        if version not in (2, 3):
            raise ValueError(f"unsupported GGUF version {version}")
        n_tensors, n_kv = struct.unpack_from("<QQ", mv, 8)
        r = _Reader(mv)
        r.pos = 24
        metadata: Dict[str, Any] = {}
        for _ in range(n_kv):
            key = r.string()
            vtype = r.scalar(_T_U32)
            metadata[key] = r.value(vtype)
        infos = []
        for _ in range(n_tensors):
            name = r.string()
            n_dims = r.scalar(_T_U32)
            dims = [r.scalar(_T_U64) for _ in range(n_dims)]
            ggml_type = r.scalar(_T_U32)
            offset = r.scalar(_T_U64)
            infos.append(
                (name, tuple(reversed(dims)), ggml_type, offset)
            )
    except struct.error as e:
        # truncated/corrupt file: surface as ValueError so every caller's
        # fallback path (ByteTokenizer, EvaluationError) engages
        raise ValueError(f"corrupt GGUF file {path!r}: {e}") from e
    align = int(metadata.get("general.alignment", 32))
    data_start = (r.pos + align - 1) // align * align
    return metadata, infos, data_start, raw


def _f16(blocks: np.ndarray, a: int) -> np.ndarray:
    """Column-pair [a:a+2] of a [N, bytes] uint8 block array as f32
    scales, shape [N, 1]."""
    return blocks[:, a: a + 2].copy().view(np.float16).astype(np.float32)


def _k_scale_min(scales: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Unpack the 12-byte 6-bit scale/min table shared by Q4_K/Q5_K
    (ggml get_scale_min_k4): 8 sub-block (scale, min) pairs, the high 2
    bits of entries 4..7 spilled into the top bits of bytes 0..7."""
    n = scales.shape[0]
    sc = np.empty((n, 8), np.float32)
    mn = np.empty((n, 8), np.float32)
    for j in range(4):
        sc[:, j] = scales[:, j] & 63
        mn[:, j] = scales[:, j + 4] & 63
    for j in range(4, 8):
        sc[:, j] = (scales[:, j + 4] & 0x0F) | ((scales[:, j - 4] >> 6) << 4)
        mn[:, j] = (scales[:, j + 4] >> 4) | ((scales[:, j] >> 6) << 4)
    return sc, mn


def _dequant_q2_k(blocks: np.ndarray) -> np.ndarray:
    n = blocks.shape[0]
    scales = blocks[:, 0:16]
    qs = blocks[:, 16:80]
    d, dmin = _f16(blocks, 80), _f16(blocks, 82)
    out = np.empty((n, 256), np.float32)
    for half in range(2):                       # 128-element halves
        q = qs[:, 32 * half: 32 * half + 32]
        for j in range(4):                      # 2-bit planes
            for sub in range(2):                # 16-element sub-blocks
                s = scales[:, 8 * half + 2 * j + sub: 8 * half + 2 * j + sub + 1]
                dl = d * (s & 0x0F)
                ml = dmin * (s >> 4)
                vals = (q[:, 16 * sub: 16 * sub + 16] >> (2 * j)) & 3
                out[:, 128 * half + 32 * j + 16 * sub:
                     128 * half + 32 * j + 16 * sub + 16] = dl * vals - ml
    return out


def _dequant_q3_k(blocks: np.ndarray) -> np.ndarray:
    n = blocks.shape[0]
    hmask = blocks[:, 0:32]
    qs = blocks[:, 32:96]
    raw_sc = blocks[:, 96:108]
    d = _f16(blocks, 108)
    # 6-bit scales: low 4 bits in bytes 0..7, high 2 bits packed in 8..11
    sc = np.empty((n, 16), np.float32)
    for j in range(16):
        if j < 8:
            lo = raw_sc[:, j] & 0x0F
        else:
            lo = raw_sc[:, j - 8] >> 4
        hi = (raw_sc[:, 8 + j % 4] >> (2 * (j // 4))) & 3
        sc[:, j] = (lo | (hi << 4)).astype(np.int8) - 32
    out = np.empty((n, 256), np.float32)
    is_ = 0
    for half in range(2):
        q = qs[:, 32 * half: 32 * half + 32]
        for j in range(4):
            m = 1 << (4 * half + j)
            for sub in range(2):
                dl = d[:, 0] * sc[:, is_]
                is_ += 1
                qsub = ((q[:, 16 * sub: 16 * sub + 16] >> (2 * j)) & 3
                        ).astype(np.int8)
                hm = hmask[:, 16 * sub: 16 * sub + 16]
                qsub = qsub - np.where((hm & m) != 0, 0, 4).astype(np.int8)
                out[:, 128 * half + 32 * j + 16 * sub:
                     128 * half + 32 * j + 16 * sub + 16] = (
                    dl[:, None] * qsub
                )
    return out


def _dequant_q4_k(blocks: np.ndarray) -> np.ndarray:
    d, dmin = _f16(blocks, 0), _f16(blocks, 2)
    sc, mn = _k_scale_min(blocks[:, 4:16])
    qs = blocks[:, 16:144]
    out = np.empty((blocks.shape[0], 256), np.float32)
    for c in range(4):                          # 64-element chunks
        q = qs[:, 32 * c: 32 * c + 32]
        out[:, 64 * c: 64 * c + 32] = (
            d * sc[:, [2 * c]] * (q & 0x0F) - dmin * mn[:, [2 * c]]
        )
        out[:, 64 * c + 32: 64 * c + 64] = (
            d * sc[:, [2 * c + 1]] * (q >> 4) - dmin * mn[:, [2 * c + 1]]
        )
    return out


def _dequant_q5_k(blocks: np.ndarray) -> np.ndarray:
    d, dmin = _f16(blocks, 0), _f16(blocks, 2)
    sc, mn = _k_scale_min(blocks[:, 4:16])
    qh = blocks[:, 16:48]
    qs = blocks[:, 48:176]
    out = np.empty((blocks.shape[0], 256), np.float32)
    for c in range(4):
        ql = qs[:, 32 * c: 32 * c + 32]
        h1 = ((qh >> (2 * c)) & 1) * 16
        h2 = ((qh >> (2 * c + 1)) & 1) * 16
        out[:, 64 * c: 64 * c + 32] = (
            d * sc[:, [2 * c]] * ((ql & 0x0F) + h1)
            - dmin * mn[:, [2 * c]]
        )
        out[:, 64 * c + 32: 64 * c + 64] = (
            d * sc[:, [2 * c + 1]] * ((ql >> 4) + h2)
            - dmin * mn[:, [2 * c + 1]]
        )
    return out


def _dequant_q6_k(blocks: np.ndarray) -> np.ndarray:
    n = blocks.shape[0]
    ql = blocks[:, 0:128]
    qh = blocks[:, 128:192]
    sc = blocks[:, 192:208].view(np.int8).astype(np.float32)
    d = _f16(blocks, 208)
    out = np.empty((n, 256), np.float32)

    def rep(s, i0):
        return np.repeat(s[:, i0: i0 + 2], 16, axis=1)   # sc[l//16 + i0]

    for half in range(2):                       # 128-element halves
        qlh = ql[:, 64 * half: 64 * half + 64]
        qhh = qh[:, 32 * half: 32 * half + 32]
        s = sc[:, 8 * half: 8 * half + 8]
        q1 = ((qlh[:, :32] & 0x0F) | (((qhh >> 0) & 3) << 4)).astype(
            np.int8
        ) - 32
        q2 = ((qlh[:, 32:] & 0x0F) | (((qhh >> 2) & 3) << 4)).astype(
            np.int8
        ) - 32
        q3 = ((qlh[:, :32] >> 4) | (((qhh >> 4) & 3) << 4)).astype(
            np.int8
        ) - 32
        q4 = ((qlh[:, 32:] >> 4) | (((qhh >> 6) & 3) << 4)).astype(
            np.int8
        ) - 32
        base = 128 * half
        out[:, base: base + 32] = d * rep(s, 0) * q1
        out[:, base + 32: base + 64] = d * rep(s, 2) * q2
        out[:, base + 64: base + 96] = d * rep(s, 4) * q3
        out[:, base + 96: base + 128] = d * rep(s, 6) * q4
    return out


def _dequantize(
    name: str, blob: np.ndarray, shape: tuple, ggml_type: int
) -> np.ndarray:
    """Dequantize one tensor's raw bytes → f32 array of ``shape``.

    K-quant super-block layouts follow ggml-quants.c (dequantize_row_*):
    256-element super-blocks with 6-bit (Q4_K/Q5_K), 4+2-bit packed
    (Q3_K), 4-bit (Q2_K), or int8 (Q6_K) sub-block scales. These are the
    formats real-world GGUF checkpoints actually ship (Q4_K_M et al.) —
    reference role: llama-box serves them via ggml, here they load into
    the same jitted TPU transformer as safetensors."""
    n = int(np.prod(shape))
    if ggml_type == GGML_F32:
        return blob.view(np.float32)[:n].reshape(shape)
    if ggml_type == GGML_F16:
        return blob.view(np.float16)[:n].astype(np.float32).reshape(shape)
    if ggml_type == GGML_BF16:
        u32 = blob.view(np.uint16)[:n].astype(np.uint32) << 16
        return u32.view(np.float32).reshape(shape)
    if ggml_type == GGML_Q8_0:
        # blocks of 32: f16 scale + 32×int8
        blocks = blob.reshape(-1, 34)
        d = _f16(blocks, 0)
        q = blocks[:, 2:].view(np.int8).astype(np.float32)
        return (q * d).reshape(-1)[:n].reshape(shape)
    if ggml_type in (GGML_Q4_0, GGML_Q4_1):
        bs = 18 if ggml_type == GGML_Q4_0 else 20
        blocks = blob.reshape(-1, bs)
        d = _f16(blocks, 0)
        qs = blocks[:, bs - 16:]
        lo = (qs & 0x0F).astype(np.float32)
        hi = (qs >> 4).astype(np.float32)
        q = np.concatenate([lo, hi], axis=1)          # [blocks, 32]
        if ggml_type == GGML_Q4_0:
            vals = (q - 8.0) * d
        else:
            m = _f16(blocks, 2)
            vals = q * d + m
        return vals.reshape(-1)[:n].reshape(shape)
    if ggml_type in (GGML_Q5_0, GGML_Q5_1):
        bs = 22 if ggml_type == GGML_Q5_0 else 24
        blocks = blob.reshape(-1, bs)
        d = _f16(blocks, 0)
        qh = (
            blocks[:, bs - 20: bs - 16].copy().view(np.uint32)
            .astype(np.uint64)
        )
        qs = blocks[:, bs - 16:]
        bits = (qh[:, 0:1] >> np.arange(32, dtype=np.uint64)) & 1
        lo = (qs & 0x0F) | (bits[:, :16] << 4).astype(np.uint8)
        hi = (qs >> 4) | (bits[:, 16:] << 4).astype(np.uint8)
        q = np.concatenate([lo, hi], axis=1).astype(np.float32)
        if ggml_type == GGML_Q5_0:
            vals = (q - 16.0) * d
        else:
            vals = q * d + _f16(blocks, 2)
        return vals.reshape(-1)[:n].reshape(shape)
    kdeq = {
        GGML_Q2_K: _dequant_q2_k,
        GGML_Q3_K: _dequant_q3_k,
        GGML_Q4_K: _dequant_q4_k,
        GGML_Q5_K: _dequant_q5_k,
        GGML_Q6_K: _dequant_q6_k,
    }.get(ggml_type)
    if kdeq is not None:
        _, bs = _BLOCK_BYTES[ggml_type]
        vals = kdeq(blob.reshape(-1, bs))
        return vals.reshape(-1)[:n].reshape(shape)
    raise ValueError(
        f"GGUF tensor {name!r} uses unsupported quantization "
        f"{_TYPE_NAMES.get(ggml_type, ggml_type)}; supported: F32/F16/"
        "BF16/Q8_0/Q4_0/Q4_1/Q5_0/Q5_1/Q2_K/Q3_K/Q4_K/Q5_K/Q6_K"
    )


def _type_bytes(shape: tuple, ggml_type: int) -> int:
    n = int(np.prod(shape))
    if ggml_type == GGML_F32:
        return n * 4
    if ggml_type in (GGML_F16, GGML_BF16):
        return n * 2
    if ggml_type in _BLOCK_BYTES:
        elems, nbytes = _BLOCK_BYTES[ggml_type]
        return (n + elems - 1) // elems * nbytes
    raise ValueError(f"unsupported ggml type {ggml_type}")


# llama.cpp tensor names → the HF names the existing loader maps
# (engine/weights.py load_hf_checkpoint)
_NAME_MAP = {
    "token_embd.weight": "model.embed_tokens.weight",
    "output_norm.weight": "model.norm.weight",
    "output.weight": "lm_head.weight",
}
_BLK_MAP = {
    "attn_norm.weight": "input_layernorm.weight",
    "attn_q.weight": "self_attn.q_proj.weight",
    "attn_k.weight": "self_attn.k_proj.weight",
    "attn_v.weight": "self_attn.v_proj.weight",
    "attn_output.weight": "self_attn.o_proj.weight",
    "attn_q.bias": "self_attn.q_proj.bias",
    "attn_k.bias": "self_attn.k_proj.bias",
    "attn_v.bias": "self_attn.v_proj.bias",
    "attn_q_norm.weight": "self_attn.q_norm.weight",
    "attn_k_norm.weight": "self_attn.k_norm.weight",
    "ffn_norm.weight": "post_attention_layernorm.weight",
    "ffn_gate.weight": "mlp.gate_proj.weight",
    "ffn_up.weight": "mlp.up_proj.weight",
    "ffn_down.weight": "mlp.down_proj.weight",
}
_SKIP = ("rope_freqs.weight", "rope_factors.weight")


def _map_name(name: str) -> Optional[str]:
    if name in _NAME_MAP:
        return _NAME_MAP[name]
    if name in _SKIP:
        return None
    if name.startswith("blk."):
        _, layer, rest = name.split(".", 2)
        if rest in _BLK_MAP:
            return f"model.layers.{layer}.{_BLK_MAP[rest]}"
        if "shexp" in rest:
            # Qwen2-MoE-class shared experts: fused shexp tensors are
            # not mapped yet — loud, not silently dropped
            raise ValueError(
                "GGUF shared-expert (shexp) checkpoints are not "
                f"supported yet (tensor {name!r}); use the safetensors "
                "export"
            )
        if re.match(r"ffn_(gate|up|down)\.\d+\.weight$", rest):
            # legacy per-expert MoE layout (pre-fused llama.cpp
            # exports, e.g. early Mixtral GGUFs): silently warn-dropping
            # these would surface as a cryptic KeyError after minutes
            # of dequantizing a multi-GB file
            raise ValueError(
                "legacy per-expert MoE GGUF layout is not supported "
                f"(tensor {name!r}); re-export with a current "
                "llama.cpp (fused ffn_*_exps tensors) or use the "
                "safetensors checkpoint"
            )
        if rest == "ffn_gate_inp.weight":
            return f"model.layers.{layer}.mlp.gate.weight"
    logger.warning("ignoring unrecognized GGUF tensor %r", name)
    return None


# fused MoE expert tensors (llama.cpp exports one 3-D tensor per
# projection, experts stacked on the slowest axis after dim reversal:
# gate/up [E, F, D], down [E, D, F]) → the per-expert HF names
# build_lm_params already maps
_EXPS_MAP = {
    "ffn_gate_exps.weight": "gate_proj",
    "ffn_up_exps.weight": "up_proj",
    "ffn_down_exps.weight": "down_proj",
}


def _reverse_llama_permute(w: np.ndarray, n_head: int) -> np.ndarray:
    """Undo convert_hf_to_gguf's rotary permutation of q/k weights.

    llama-arch exports interleave head rows for GGML's rotary layout;
    this engine applies HF rotate_half RoPE, so the permutation must be
    reversed on load (the same fix transformers' own GGUF loader
    applies) — without it every real llama/mistral .gguf serves
    garbage attention."""
    out = w.shape[0]
    dim = out // n_head // 2
    return (
        w.reshape(n_head, dim, 2, *w.shape[1:])
        .swapaxes(1, 2)
        .reshape(w.shape)
    )


def gguf_shard_paths(
    path: str, first_parse=None
) -> List[str]:
    """All files of a (possibly split) GGUF checkpoint, first shard
    first.

    gguf-split writes ``split.count``/``split.no`` metadata and names
    shards ``<base>-00001-of-0000N.gguf``; every shard must be present
    (reference role: gguf-parser resolves splits the same way for
    sizing). A checkpoint without split metadata is its own single
    shard. ``first_parse`` lets callers that already read ``path``
    (read_gguf result tuple) avoid a second full metadata parse — the
    KV section can embed a 100k+-entry tokenizer vocab."""
    metadata = (first_parse or read_gguf(path))[0]
    count = int(metadata.get("split.count", 1) or 1)
    if count <= 1:
        return [path]
    m = re.search(r"-(\d{5})-of-(\d{5})\.gguf$", path)
    if not m:
        raise ValueError(
            f"{path!r} declares split.count={count} but is not named "
            "like gguf-split output (<base>-00001-of-0000N.gguf)"
        )
    base = path[: m.start()]
    total = int(m.group(2))
    if total != count:
        raise ValueError(
            f"{path!r}: filename says {total} shards, metadata says "
            f"{count}"
        )
    shards = [
        f"{base}-{i + 1:05d}-of-{total:05d}.gguf" for i in range(total)
    ]
    missing = [s for s in shards if not os.path.exists(s)]
    if missing:
        raise ValueError(
            f"split GGUF is missing shard(s): {missing}"
        )
    return shards


def _tensor_data(
    name: str, shape: tuple, ggml_type: int, offset: int,
    data_start: int, raw,
) -> np.ndarray:
    """One tensor's dequantized f32 data from a parsed shard."""
    buf = np.frombuffer(raw, np.uint8)
    start = data_start + offset
    blob = buf[start: start + _type_bytes(shape, ggml_type)]
    return _dequantize(name, blob, shape, ggml_type)


def load_gguf_tensors(path: str) -> Dict[str, Any]:
    """GGUF file (or first shard of a split checkpoint) → {hf_name:
    torch tensor} for load_hf_checkpoint's mapping machinery. llama.cpp
    2-D weights are [out, in] after dim reversal — the same layout as
    torch linear weights, so the existing transpose-on-load convention
    applies unchanged.

    Model metadata (arch, head counts) comes from shard 1 ONLY:
    gguf-split writes the model KV section just there, so per-shard
    metadata reads would silently skip the llama q/k un-permute for
    tensors living in later shards."""
    import torch

    first = read_gguf(path)
    shards = gguf_shard_paths(path, first_parse=first)
    metadata = first[0]
    arch = metadata.get("general.architecture", "llama")
    n_head = int(metadata.get(f"{arch}.attention.head_count", 0))
    n_kv = int(
        metadata.get(f"{arch}.attention.head_count_kv", n_head)
    )
    tensors: Dict[str, Any] = {}
    for shard in shards:
        _, infos, data_start, raw = (
            first if shard == path else read_gguf(shard)
        )
        for name, shape, ggml_type, offset in infos:
            # single parse point: fused-exps dispatch and _map_name see
            # the same (layer, rest) split
            layer = rest = ""
            if name.startswith("blk."):
                _, layer, rest = name.split(".", 2)
            if rest in _EXPS_MAP:
                fused = _tensor_data(
                    name, shape, ggml_type, offset, data_start, raw
                )
                proj = _EXPS_MAP[rest]
                for e in range(fused.shape[0]):
                    tensors[
                        f"model.layers.{layer}.mlp.experts.{e}"
                        f".{proj}.weight"
                    ] = torch.from_numpy(fused[e].copy())
                continue
            hf_name = _map_name(name)
            if hf_name is None:
                continue
            arr = _tensor_data(
                name, shape, ggml_type, offset, data_start, raw
            ).copy()
            if arch == "llama" and n_head:
                # only llama-arch exports permute q/k (qwen2/gemma don't)
                if name.endswith("attn_q.weight"):
                    arr = _reverse_llama_permute(arr, n_head)
                elif name.endswith("attn_k.weight"):
                    arr = _reverse_llama_permute(arr, n_kv)
            tensors[hf_name] = torch.from_numpy(arr)
    return tensors


def gguf_file_in(model_dir: str) -> Optional[str]:
    """The .gguf file for a model source: the path itself, or the first
    .gguf in the directory (for gguf-split checkpoints the sorted order
    puts the -00001-of-N shard first; gguf_shard_paths resolves the
    rest)."""
    if model_dir and model_dir.endswith(".gguf"):
        return model_dir if os.path.exists(model_dir) else None
    if model_dir and os.path.isdir(model_dir):
        files = sorted(
            f for f in os.listdir(model_dir) if f.endswith(".gguf")
        )
        if files:
            return os.path.join(model_dir, files[0])
    return None


def config_from_gguf(path: str, name: str = ""):
    """GGUF metadata → ModelConfig (reference role: gguf-parser's
    architecture extraction feeding the scheduler).

    Rope scaling is carried two ways by llama.cpp exports and both are
    honored: ``{arch}.rope.scaling.*`` metadata (linear/yarn), and the
    ``rope_freqs.weight`` tensor (Llama-3.1-class llama3 scaling, shipped
    as precomputed per-frequency divisors instead of metadata). Ignoring
    either would serve long prompts with unscaled RoPE — silently wrong
    beyond the original context window — while advertising the scaled
    ``context_length``."""
    from gpustack_tpu.models.config import ModelConfig

    first = read_gguf(path)
    shards = gguf_shard_paths(path, first_parse=first)
    metadata, infos, data_start, raw = first
    # tensor presence checks (tied embeddings, bias, rope_freqs) must see
    # the WHOLE checkpoint; gguf-split puts full metadata in shard 1 but
    # spreads tensors across every shard
    shard_infos = {shards[0]: (infos, data_start, raw)}
    for extra in shards[1:]:
        m2, i2, ds2, raw2 = read_gguf(extra)
        shard_infos[extra] = (i2, ds2, raw2)
        infos = infos + i2
    arch = metadata.get("general.architecture", "llama")
    if arch.startswith("deepseek"):
        # llama.cpp's deepseek2 export uses MLA-specific tensor names
        # and its own cache layout; the mapping here doesn't cover it
        raise ValueError(
            f"GGUF arch {arch!r} is not supported; serve DeepSeek from "
            "the safetensors checkpoint (MLA is natively supported "
            "there)"
        )

    def md(key: str, default=None):
        return metadata.get(f"{arch}.{key}", default)

    hidden = int(md("embedding_length", 0))
    heads = int(md("attention.head_count", 0))
    if not hidden or not heads:
        raise ValueError(
            f"GGUF {path!r} lacks {arch}.embedding_length/"
            "attention.head_count metadata"
        )
    kv_heads = int(md("attention.head_count_kv", heads))
    vocab = int(md("vocab_size", 0)) or len(
        metadata.get("tokenizer.ggml.tokens", [])
    )
    if not vocab:
        vocab = next(
            (
                int(shape[0]) for tname, shape, _t, _o in infos
                if tname == "token_embd.weight"
            ),
            32000,
        )
    tensor_names = {t[0] for t in infos}

    # MoE metadata (mixtral exports under arch "llama" with
    # expert_count set; qwen3moe under its own arch). Weight routing:
    # softmax over the selected experts with renormalization — the
    # semantics both mixtral and qwen3moe use.
    num_experts = int(md("expert_count", 0) or 0)
    num_experts_per_tok = int(md("expert_used_count", 0) or 0)
    moe_inter = int(
        md("expert_feed_forward_length", 0)
        or md("feed_forward_length", 0)
    )

    rope_scaling = None
    rs_type = md("rope.scaling.type")
    if rs_type == "linear":
        rope_scaling = {
            "rope_type": "linear",
            "factor": float(md("rope.scaling.factor", 1.0)),
        }
    elif rs_type == "yarn":
        rope_scaling = {
            "rope_type": "yarn",
            "factor": float(md("rope.scaling.factor", 1.0)),
            "original_max_position_embeddings": int(
                md("rope.scaling.original_context_length", 0)
                or md("context_length", 4096)
            ),
        }
    elif rs_type not in (None, "none"):
        raise ValueError(
            f"GGUF {path!r} declares unsupported rope scaling type "
            f"{rs_type!r}"
        )
    if "rope_freqs.weight" in tensor_names:
        # Llama-3.1-style exports: the blended llama3 divisors ship as a
        # tensor; load them now (always F32, head_dim/2 floats) so the
        # rope tables divide by them (transformer.rope_params)
        for spath, (s_infos, s_start, s_raw) in shard_infos.items():
            hit = next(
                (t for t in s_infos if t[0] == "rope_freqs.weight"), None
            )
            if hit is None:
                continue
            tname, shape, ggml_type, offset = hit
            factors = _tensor_data(
                tname, shape, ggml_type, offset, s_start, s_raw
            )
            rope_scaling = dict(rope_scaling or {})
            rope_scaling.setdefault("rope_type", "llama3")
            rope_scaling["factors"] = [
                float(x) for x in np.asarray(factors).reshape(-1)
            ]
            break

    return ModelConfig(
        name=name or os.path.basename(path),
        vocab_size=vocab,
        hidden_size=hidden,
        intermediate_size=int(md("feed_forward_length", 4 * hidden)),
        num_layers=int(md("block_count", 1)),
        num_heads=heads,
        num_kv_heads=kv_heads,
        head_dim=int(md("attention.key_length", hidden // heads)),
        rope_theta=float(md("rope.freq_base", 10000.0)),
        rope_scaling=rope_scaling,
        rms_norm_eps=float(md("attention.layer_norm_rms_epsilon", 1e-5)),
        max_position_embeddings=int(md("context_length", 8192)),
        tie_word_embeddings="output.weight" not in tensor_names,
        qkv_bias="blk.0.attn_q.bias" in tensor_names,
        qk_norm="blk.0.attn_q_norm.weight" in tensor_names,
        num_experts=num_experts,
        num_experts_per_tok=num_experts_per_tok,
        moe_intermediate_size=moe_inter if num_experts else 0,
        norm_topk_prob=True,
    )


def _gpt2_byte_tables():
    """OpenAI's bytes↔unicode bijection (gpt2 BPE vocab encoding)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(0xA1, 0xAD))
        + list(range(0xAE, 0x100))
    )
    cs = list(bs)
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    byte_to_uni = {b: chr(c) for b, c in zip(bs, cs)}
    uni_to_byte = {chr(c): b for b, c in zip(bs, cs)}
    return byte_to_uni, uni_to_byte


class GGUFVocabTokenizer:
    """Tokenizer from the GGUF's embedded vocab.

    Two vocab conventions are handled per ``tokenizer.ggml.model``:
    SentencePiece (``llama``: ``▁`` word boundary, ``<0xNN>`` byte
    tokens) and gpt2-style BPE (``gpt2``: byte↔unicode mapped pieces,
    ``Ġ`` spaces — Llama-3/Qwen exports). Decoding is exact for both.
    Encoding is greedy longest-match over the vocab — NOT a merge-order
    BPE replay, so boundaries can differ from the original tokenizer on
    rare strings (a tokenizer.json sidecar gives exact encoding;
    engine/tokenizer.py prefers it)."""

    def __init__(self, metadata: Dict[str, Any]):
        self.tokens: List[str] = metadata["tokenizer.ggml.tokens"]
        self.model = metadata.get("tokenizer.ggml.model", "llama")
        self.vocab_size = len(self.tokens)
        eos = int(metadata.get("tokenizer.ggml.eos_token_id", 2))
        bos = metadata.get("tokenizer.ggml.bos_token_id")
        self.bos_id = int(bos) if bos is not None else None
        self.eos_ids = (eos,)
        self._index = {t: i for i, t in enumerate(self.tokens)}
        self._max_len = max((len(t) for t in self.tokens), default=1)
        self._b2u, self._u2b = _gpt2_byte_tables()

    @classmethod
    def from_file(cls, path: str) -> "GGUFVocabTokenizer":
        metadata, _, _, _ = read_gguf(path)
        if "tokenizer.ggml.tokens" not in metadata:
            raise ValueError(f"GGUF {path!r} embeds no tokenizer vocab")
        return cls(metadata)

    def encode(self, text: str) -> List[int]:
        if self.model == "gpt2":
            # gpt2 vocabs store pieces in the byte→unicode mapping;
            # transform the text the same way, then longest-match
            piece_text = "".join(
                self._b2u[b] for b in text.encode("utf-8")
            )
        else:
            piece_text = "▁" + text.replace(" ", "▁")
        ids: List[int] = []
        if self.bos_id is not None:
            ids.append(self.bos_id)
        i = 0
        while i < len(piece_text):
            match = None
            for ln in range(
                min(self._max_len, len(piece_text) - i), 0, -1
            ):
                cand = piece_text[i: i + ln]
                tid = self._index.get(cand)
                if tid is not None:
                    match = (tid, ln)
                    break
            if match is None:
                # fall back to byte tokens for unknown chars; the word
                # boundary marker is OUR insertion — as bytes it must be
                # the space it stands for, not literal '▁'
                ch = " " if piece_text[i] == "▁" else piece_text[i]
                for b in ch.encode("utf-8"):
                    tid = self._index.get(f"<0x{b:02X}>")
                    if tid is not None:
                        ids.append(tid)
                i += 1
                continue
            ids.append(match[0])
            i += match[1]
        return ids

    def apply_chat_template(
        self, messages: List[dict], tools: Optional[List[dict]] = None,
    ) -> List[int]:
        """Generic role-tag template (same shape as the hermetic byte
        tokenizer's): a GGUF file carries no jinja chat template, so
        serving uses the neutral format rather than guessing a family's."""
        from gpustack_tpu.engine.tokenizer import (
            _content_text,
            _inject_tools_fallback,
        )

        messages = _inject_tools_fallback(messages, tools)
        text = "".join(
            f"<{m['role']}>{_content_text(m)}</{m['role']}>"
            for m in messages
        ) + "<assistant>"
        return self.encode(text)

    def decode(self, ids) -> str:
        if self.model == "gpt2":
            # reverse the byte↔unicode bijection over concatenated pieces
            byte_out = bytearray()
            for tid in ids:
                if not 0 <= int(tid) < self.vocab_size:
                    continue
                tok = self.tokens[int(tid)]
                if tok.startswith("<|") and tok.endswith("|>"):
                    continue         # control tokens render as nothing
                for ch in tok:
                    b = self._u2b.get(ch)
                    if b is None:
                        byte_out.extend(ch.encode("utf-8"))
                    else:
                        byte_out.append(b)
            return byte_out.decode("utf-8", errors="replace")
        out: List[str] = []
        byte_buf: List[int] = []

        def flush_bytes():
            if byte_buf:
                out.append(
                    bytes(byte_buf).decode("utf-8", errors="replace")
                )
                byte_buf.clear()

        for tid in ids:
            if not 0 <= int(tid) < self.vocab_size:
                continue
            tok = self.tokens[int(tid)]
            if (
                len(tok) == 6
                and tok.startswith("<0x")
                and tok.endswith(">")
            ):
                byte_buf.append(int(tok[3:5], 16))
                continue
            flush_bytes()
            if tok.startswith("<") and tok.endswith(">"):
                continue             # control tokens render as nothing
            out.append(tok.replace("▁", " "))
        flush_bytes()
        text = "".join(out)
        return text[1:] if text.startswith(" ") else text
