"""Weight loading: HF safetensors checkpoints → stacked functional params.

Covers the LlamaForCausalLM / Qwen2ForCausalLM / MistralForCausalLM /
MixtralForCausalLM tensor naming. Torch stores linear weights as
``[out_features, in_features]``; our functional matmuls contract
``x @ W`` with ``W[in, out]``, so every projection transposes on load.

When no checkpoint directory is given (hermetic tests, synthetic
benchmarks under zero egress) params are randomly initialized from the
config instead.
"""

from __future__ import annotations

import glob
import json
import logging
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gpustack_tpu.models.config import ModelConfig
from gpustack_tpu.models.transformer import init_params

logger = logging.getLogger(__name__)


# MXFP4 e2m1 value table, nibble-indexed (sign bit high): the packing
# the GPT-OSS hub checkpoints use for expert weights (transformers
# integrations/mxfp4 FP4_VALUES)
_FP4_VALUES = (
    0.0, 0.5, 1.0, 1.5, 2.0, 3.0, 4.0, 6.0,
    -0.0, -0.5, -1.0, -1.5, -2.0, -3.0, -4.0, -6.0,
)


def _mxfp4_dequant(blocks, scales) -> jax.Array:
    """MXFP4 blocks/scales → bf16 weight, matching
    convert_moe_packed_tensors: ``blocks`` uint8 [..., G, B] holds fp4
    PAIRS (low nibble = even element), ``scales`` uint8 e8m0 [..., G]
    biased by 127; output interleaves, applies 2^scale, flattens the
    block axes and swaps the last two dims into the [E, in, out]
    layout the bf16 exports use."""
    import numpy as np

    lut = np.asarray(_FP4_VALUES, np.float32)
    lo = lut[blocks & 0x0F]
    hi = lut[blocks >> 4]
    out = np.empty(
        (*blocks.shape[:-1], blocks.shape[-1] * 2), np.float32
    )
    out[..., 0::2] = lo
    out[..., 1::2] = hi
    out *= np.exp2(
        scales.astype(np.int32) - 127
    )[..., None].astype(np.float32)
    out = out.reshape(*blocks.shape[:-2], -1)      # [E, X, D]
    return jnp.asarray(out.swapaxes(-1, -2)).astype(jnp.bfloat16)


def _to_jnp(t, dtype=jnp.bfloat16) -> jax.Array:
    """torch tensor (possibly bf16) → jnp array."""
    import torch

    if t.dtype == torch.bfloat16:
        return jnp.asarray(t.float().numpy()).astype(jnp.bfloat16)
    return jnp.asarray(t.numpy()).astype(dtype)


def _read_safetensors(model_dir: str) -> Dict[str, Any]:
    """All tensors from a local HF model dir, keyed by checkpoint name."""
    from safetensors import safe_open

    files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no .safetensors files in {model_dir}")
    tensors: Dict[str, Any] = {}
    for f in files:
        with safe_open(f, framework="pt") as st:
            for name in st.keys():
                tensors[name] = st.get_tensor(name)
    return tensors


def _taker(tensors: Dict[str, Any]):
    """take(name, transpose) popping from ``tensors`` — shared by the LM
    and Whisper loaders so dtype/transpose handling can't drift."""

    def take(name: str, transpose: bool = False) -> jax.Array:
        t = tensors.pop(name)
        if transpose:
            t = t.T
        return _to_jnp(t)

    return take


def load_hf_checkpoint(cfg: ModelConfig, model_dir: str) -> Dict[str, Any]:
    """Load *.safetensors from a local HF model dir into our param tree."""
    tensors = _read_safetensors(model_dir)
    return build_lm_params(cfg, tensors)


def load_gguf_checkpoint(cfg: ModelConfig, gguf_path: str) -> Dict[str, Any]:
    """Load a GGUF checkpoint: dequantize to the HF tensor names
    (engine/gguf.py), then reuse the exact same mapping as safetensors —
    one param-tree builder, two on-disk formats."""
    from gpustack_tpu.engine.gguf import load_gguf_tensors

    tensors = load_gguf_tensors(gguf_path)
    return build_lm_params(cfg, tensors)


def build_lm_params(
    cfg: ModelConfig, tensors: Dict[str, Any]
) -> Dict[str, Any]:
    """HF-named tensors → the stacked functional param tree.

    DeepSeek checkpoints split into a dense prefix stack
    (``first_k_dense`` layers) + a MoE remainder — forward scans them
    back-to-back (models/transformer.py)."""
    L = cfg.num_layers
    take = _taker(tensors)
    kd = cfg.first_k_dense if cfg.is_moe else 0

    def build_range(rng, moe: bool) -> Dict[str, Any]:
        def stack(fmt: str, transpose: bool = False) -> jax.Array:
            return jnp.stack(
                [take(fmt.format(i), transpose) for i in rng]
            )

        layers: Dict[str, Any] = {
            "attn_norm": stack("model.layers.{}.input_layernorm.weight"),
        }
        if cfg.is_mla:
            # DeepSeek MLA projections (decompressed serving)
            if cfg.q_lora_rank:
                layers["wq_a"] = stack(
                    "model.layers.{}.self_attn.q_a_proj.weight", True
                )
                layers["q_a_norm"] = stack(
                    "model.layers.{}.self_attn.q_a_layernorm.weight"
                )
                layers["wq_b"] = stack(
                    "model.layers.{}.self_attn.q_b_proj.weight", True
                )
            else:
                layers["wq"] = stack(
                    "model.layers.{}.self_attn.q_proj.weight", True
                )
            layers["wkv_a"] = stack(
                "model.layers.{}.self_attn.kv_a_proj_with_mqa.weight",
                True,
            )
            layers["kv_a_norm"] = stack(
                "model.layers.{}.self_attn.kv_a_layernorm.weight"
            )
            layers["wkv_b"] = stack(
                "model.layers.{}.self_attn.kv_b_proj.weight", True
            )
            layers["wo"] = stack(
                "model.layers.{}.self_attn.o_proj.weight", True
            )
        else:
            layers["wq"] = stack(
                "model.layers.{}.self_attn.q_proj.weight", True
            )
            layers["wk"] = stack(
                "model.layers.{}.self_attn.k_proj.weight", True
            )
            layers["wv"] = stack(
                "model.layers.{}.self_attn.v_proj.weight", True
            )
            layers["wo"] = stack(
                "model.layers.{}.self_attn.o_proj.weight", True
            )
        if cfg.post_norms:
            # gemma sandwich norms: HF post_attention_layernorm is the
            # POST-attention norm; the pre-MLP norm has its own name
            layers["post_attn_norm"] = stack(
                "model.layers.{}.post_attention_layernorm.weight"
            )
            layers["mlp_norm"] = stack(
                "model.layers.{}.pre_feedforward_layernorm.weight"
            )
            layers["post_mlp_norm"] = stack(
                "model.layers.{}.post_feedforward_layernorm.weight"
            )
        else:
            # llama-family: post_attention_layernorm IS the pre-MLP norm
            layers["mlp_norm"] = stack(
                "model.layers.{}.post_attention_layernorm.weight"
            )
        if cfg.qkv_bias:
            layers["bq"] = stack("model.layers.{}.self_attn.q_proj.bias")
            layers["bk"] = stack("model.layers.{}.self_attn.k_proj.bias")
            layers["bv"] = stack("model.layers.{}.self_attn.v_proj.bias")
        if cfg.o_bias:
            layers["bo"] = stack("model.layers.{}.self_attn.o_proj.bias")
        if cfg.attn_sinks:
            # fp32: sink logits join the softmax denominator directly
            layers["sinks"] = jnp.stack([
                _to_jnp(
                    tensors.pop(f"model.layers.{i}.self_attn.sinks"),
                    jnp.float32,
                )
                for i in rng
            ])
        if cfg.qk_norm:
            layers["q_norm"] = stack(
                "model.layers.{}.self_attn.q_norm.weight"
            )
            layers["k_norm"] = stack(
                "model.layers.{}.self_attn.k_norm.weight"
            )
        def pop_gptoss_expert(name: str, i: int):
            """GPT-OSS expert tensor, dequantizing the hub's MXFP4
            packing when present (openai/gpt-oss-* ship
            ``{name}_blocks`` uint8 fp4-pairs + ``{name}_scales`` e8m0
            per 32-value block — transformers integrations/mxfp4
            convert_moe_packed_tensors); dequantized bf16 re-exports
            carry the plain tensor."""
            base = f"model.layers.{i}.mlp.experts.{name}"
            if base in tensors:
                return _to_jnp(tensors.pop(base))
            blocks = tensors.pop(base + "_blocks").numpy()
            scales = tensors.pop(base + "_scales").numpy()
            return _mxfp4_dequant(blocks, scales)

        if moe and cfg.moe_act == "gptoss":
            # GPT-OSS fused expert tensors (modeling_gpt_oss
            # GptOssExperts/GptOssTopKRouter): gate_up_proj [E, D, 2F]
            # with gate/up INTERLEAVED on the last axis, biased
            # everywhere, router as a true affine map
            layers["router"] = stack(
                "model.layers.{}.mlp.router.weight", True
            )
            layers["router_bias"] = jnp.stack([
                _to_jnp(
                    tensors.pop(f"model.layers.{i}.mlp.router.bias"),
                    jnp.float32,
                )
                for i in rng
            ])

            def popb(name: str, i: int):
                return _to_jnp(
                    tensors.pop(f"model.layers.{i}.mlp.experts.{name}")
                )

            gu = [
                pop_gptoss_expert("gate_up_proj", i) for i in rng
            ]                                                # [E, D, 2F]
            gub = [popb("gate_up_proj_bias", i) for i in rng]  # [E, 2F]
            layers["we_gate"] = jnp.stack([t[..., 0::2] for t in gu])
            layers["we_up"] = jnp.stack([t[..., 1::2] for t in gu])
            layers["we_gate_b"] = jnp.stack([t[..., 0::2] for t in gub])
            layers["we_up_b"] = jnp.stack([t[..., 1::2] for t in gub])
            layers["we_down"] = jnp.stack(
                [pop_gptoss_expert("down_proj", i) for i in rng]
            )                                                # [E, F, D]
            layers["we_down_b"] = jnp.stack(
                [popb("down_proj_bias", i) for i in rng]     # [E, D]
            )
        elif moe:
            # Three HF MoE naming schemes: Mixtral (block_sparse_moe /
            # w1|w2|w3), Qwen-MoE and DeepSeek (mlp.gate /
            # experts.{e}.gate_proj|down_proj|up_proj)
            if "model.layers.0.block_sparse_moe.gate.weight" in tensors:
                block, wg, wd, wu = (
                    "block_sparse_moe", "w1", "w2", "w3"
                )
            else:
                block, wg, wd, wu = (
                    "mlp", "gate_proj", "down_proj", "up_proj"
                )
                if not cfg.shared_expert_intermediate_size and any(
                    "shared_expert" in name for name in tensors
                ):
                    # shared-expert tensors with no config support would
                    # be silently dropped -> wrong logits; fail loudly
                    raise ValueError(
                        "checkpoint has shared-expert weights but the "
                        "config declares no shared expert width"
                    )
            layers["router"] = stack(
                "model.layers.{}." + block + ".gate.weight", True
            )
            if cfg.moe_scoring == "sigmoid":
                # fp32 on purpose: the correction bias tie-breaks expert
                # SELECTION (checkpoints store it fp32); bf16 rounding
                # could flip top-k picks on finely-balanced experts
                layers["router_bias"] = jnp.stack([
                    _to_jnp(
                        tensors.pop(
                            f"model.layers.{i}.{block}"
                            ".gate.e_score_correction_bias"
                        ),
                        jnp.float32,
                    )
                    for i in rng
                ])
            E = cfg.num_experts

            def stack_experts(w: str, transpose: bool) -> jax.Array:
                return jnp.stack([
                    jnp.stack([
                        _to_jnp(
                            tensors.pop(
                                f"model.layers.{i}.{block}"
                                f".experts.{e}.{w}.weight"
                            ).T if transpose else tensors.pop(
                                f"model.layers.{i}.{block}"
                                f".experts.{e}.{w}.weight"
                            )
                        )
                        for e in range(E)
                    ])
                    for i in rng
                ])

            layers["we_gate"] = stack_experts(wg, True)
            layers["we_down"] = stack_experts(wd, True)
            layers["we_up"] = stack_experts(wu, True)
            if cfg.shared_expert_intermediate_size:
                # DeepSeek: mlp.shared_experts.* (plural, ungated);
                # Qwen2-MoE: mlp.shared_expert.* + shared_expert_gate
                se = (
                    "shared_expert" if cfg.shared_expert_gated
                    else "shared_experts"
                )
                layers["ws_gate"] = stack(
                    "model.layers.{}.mlp." + se + ".gate_proj.weight",
                    True,
                )
                layers["ws_up"] = stack(
                    "model.layers.{}.mlp." + se + ".up_proj.weight",
                    True,
                )
                layers["ws_down"] = stack(
                    "model.layers.{}.mlp." + se + ".down_proj.weight",
                    True,
                )
                if cfg.shared_expert_gated:
                    layers["shared_gate"] = stack(
                        "model.layers.{}.mlp.shared_expert_gate.weight",
                        True,
                    )
        else:
            layers["w_gate"] = stack(
                "model.layers.{}.mlp.gate_proj.weight", True
            )
            layers["w_up"] = stack(
                "model.layers.{}.mlp.up_proj.weight", True
            )
            layers["w_down"] = stack(
                "model.layers.{}.mlp.down_proj.weight", True
            )
        return layers

    params: Dict[str, Any] = {
        "embed": take("model.embed_tokens.weight"),
        "layers": build_range(range(kd, L), cfg.is_moe),
        "final_norm": take("model.norm.weight"),
    }
    if kd:
        params["dense_layers"] = build_range(range(kd), False)
    if not cfg.tie_word_embeddings:
        if "lm_head.weight" in tensors:
            params["lm_head"] = take("lm_head.weight", True)
        else:
            logger.warning("no lm_head.weight; tying to embeddings")
            params["lm_head"] = params["embed"].T
    if tensors:
        logger.warning("unused checkpoint tensors: %s", sorted(tensors)[:8])
    return params


def load_npz_params(path: str, init_fn):
    """Load a flat-or-nested param tree saved as .npz ('/'-joined keys),
    falling back to ``init_fn()`` when no file exists — the checkpoint
    format for in-repo models without an HF counterpart (e.g. TTS)."""
    import numpy as np

    try:
        with np.load(path) as z:
            flat = {k: jnp.asarray(z[k]) for k in z.files}
    except OSError:
        logger.warning("no checkpoint at %r — random init", path)
        return init_fn()
    tree: dict = {}
    for key, value in flat.items():
        node = tree
        parts = key.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return tree


def load_whisper_params(cfg, model_dir: str):
    """Load an HF Whisper safetensors checkpoint into the
    models/whisper.py param tree (falls back to random init when no
    checkpoint is present — same contract as load_or_init_params)."""
    from gpustack_tpu.models.whisper import init_whisper_params

    try:
        tensors = _read_safetensors(model_dir)
    except FileNotFoundError:
        logger.warning(
            "no whisper checkpoint at %r — random init", model_dir
        )
        return init_whisper_params(cfg, jax.random.key(0))
    take = _taker(tensors)

    def stack(side: str, L: int, fmt: str, transpose=False) -> jax.Array:
        return jnp.stack(
            [
                take(f"model.{side}.layers.{i}.{fmt}", transpose)
                for i in range(L)
            ]
        )

    def attn_block(side: str, L: int, prefix: str, out: dict, tag: str):
        out[f"{tag}wq"] = stack(side, L, f"{prefix}.q_proj.weight", True)
        out[f"{tag}bq"] = stack(side, L, f"{prefix}.q_proj.bias")
        out[f"{tag}wk"] = stack(side, L, f"{prefix}.k_proj.weight", True)
        out[f"{tag}wv"] = stack(side, L, f"{prefix}.v_proj.weight", True)
        out[f"{tag}bv"] = stack(side, L, f"{prefix}.v_proj.bias")
        out[f"{tag}wo"] = stack(side, L, f"{prefix}.out_proj.weight", True)
        out[f"{tag}bo"] = stack(side, L, f"{prefix}.out_proj.bias")

    def layer_group(side: str, L: int) -> dict:
        out = {
            "ln1": stack(side, L, "self_attn_layer_norm.weight"),
            "ln1_b": stack(side, L, "self_attn_layer_norm.bias"),
            "ln2": stack(side, L, "final_layer_norm.weight"),
            "ln2_b": stack(side, L, "final_layer_norm.bias"),
            "w_up": stack(side, L, "fc1.weight", True),
            "b_up": stack(side, L, "fc1.bias"),
            "w_down": stack(side, L, "fc2.weight", True),
            "b_down": stack(side, L, "fc2.bias"),
        }
        attn_block(side, L, "self_attn", out, "")
        if side == "decoder":
            out["lnx"] = stack(side, L, "encoder_attn_layer_norm.weight")
            out["lnx_b"] = stack(side, L, "encoder_attn_layer_norm.bias")
            attn_block(side, L, "encoder_attn", out, "x")
        return out

    params = {
        # HF conv weights are [out, in, k] — ours are [k, in, out]
        "conv1": jnp.transpose(
            _to_jnp(tensors.pop("model.encoder.conv1.weight")), (2, 1, 0)
        ),
        "conv1_b": take("model.encoder.conv1.bias"),
        "conv2": jnp.transpose(
            _to_jnp(tensors.pop("model.encoder.conv2.weight")), (2, 1, 0)
        ),
        "conv2_b": take("model.encoder.conv2.bias"),
        "enc_layers": layer_group("encoder", cfg.encoder_layers),
        "enc_ln": take("model.encoder.layer_norm.weight"),
        "enc_ln_b": take("model.encoder.layer_norm.bias"),
        "tok_embed": take("model.decoder.embed_tokens.weight"),
        "pos_embed": take("model.decoder.embed_positions.weight"),
        "dec_layers": layer_group("decoder", cfg.decoder_layers),
        "dec_ln": take("model.decoder.layer_norm.weight"),
        "dec_ln_b": take("model.decoder.layer_norm.bias"),
    }
    # encoder position embeddings are fixed sinusoids (recomputed)
    tensors.pop("model.encoder.embed_positions.weight", None)
    tensors.pop("proj_out.weight", None)  # tied to tok_embed
    if tensors:
        logger.warning(
            "unused whisper tensors: %s", sorted(tensors)[:8]
        )
    return params


# HF PEFT module name -> our stacked layer param (torch Linear weights
# are [out, in]; ours are transposed [in, out], so the merged delta is
# (B @ A).T == A.T @ B.T)
_LORA_MODULES = {
    "q_proj": "wq",
    "k_proj": "wk",
    "v_proj": "wv",
    "o_proj": "wo",
    "gate_proj": "w_gate",
    "up_proj": "w_up",
    "down_proj": "w_down",
}


def merge_lora_adapters(cfg, params: Dict[str, Any], adapter_dirs):
    """Merge PEFT LoRA adapters into the base weights: W' = W + s·BA.

    Merged-at-load serving (the TPU-friendly LoRA shape: zero runtime
    overhead, one instance per adapter set — reference serves LoRA via
    engine flags + per-adapter ModelRoutes, server/lora_model_routes.py).
    Must run BEFORE int8 quantization. Returns the mutated param tree.
    """
    import json as _json
    import re as _re

    for adapter_dir in adapter_dirs:
        cfg_path = os.path.join(adapter_dir, "adapter_config.json")
        scale = 1.0
        try:
            with open(cfg_path) as f:
                acfg = _json.load(f)
            r = int(acfg.get("r", 0)) or 1
            alpha = float(acfg.get("lora_alpha", r))
            if acfg.get("use_rslora"):
                scale = alpha / (r ** 0.5)   # rsLoRA: alpha / sqrt(r)
            else:
                scale = alpha / r
        except (OSError, ValueError):
            logger.warning(
                "no adapter_config.json in %s; using scale 1.0",
                adapter_dir,
            )
        tensors = _read_safetensors(adapter_dir)
        pat = _re.compile(
            r"layers\.(\d+)\.(?:self_attn|mlp)\.(\w+)\.lora_A\.weight$"
        )
        merged = 0
        for name in sorted(tensors):
            m = pat.search(name)
            if m is None:
                continue
            layer_idx = int(m.group(1))
            module = m.group(2)
            ours = _LORA_MODULES.get(module)
            if layer_idx >= cfg.num_layers:
                # JAX scatter would silently drop the OOB update — a
                # half-applied adapter must be an error, not a mystery
                raise ValueError(
                    f"adapter {adapter_dir} targets layer {layer_idx} "
                    f"but the model has {cfg.num_layers} layers"
                )
            # heterogeneous stacks (DeepSeek first_k_dense): absolute HF
            # layer i lives in the dense prefix when i < kd, else at
            # offset i - kd in the MoE stack — indexing the MoE stack
            # with the absolute i would merge into the WRONG layer
            kd = (
                len(next(iter(params["dense_layers"].values())))
                if "dense_layers" in params else 0
            )
            if layer_idx < kd:
                stack_key, stack_idx = "dense_layers", layer_idx
            else:
                stack_key, stack_idx = "layers", layer_idx - kd
            if ours is None or ours not in params[stack_key]:
                logger.warning(
                    "skipping LoRA target %s (unsupported module)", name
                )
                continue
            b_name = name.replace("lora_A", "lora_B")
            if b_name not in tensors:
                raise ValueError(
                    f"adapter {adapter_dir} is missing {b_name} "
                    f"(truncated checkpoint?)"
                )
            # keep fp32 through the delta matmul — routing through the
            # default bf16 load dtype would cost ~8 mantissa bits twice
            a = _to_jnp(tensors[name], jnp.float32)
            b = _to_jnp(tensors[b_name], jnp.float32)
            delta = (a.T @ b.T) * scale                 # [in, out]
            base = params[stack_key][ours]
            params[stack_key][ours] = base.at[stack_idx].add(
                delta.astype(base.dtype)
            )
            merged += 1
        logger.info(
            "merged %d LoRA deltas from %s (scale %.3f)",
            merged, adapter_dir, scale,
        )
        if merged == 0:
            raise ValueError(
                f"adapter {adapter_dir} matched no mergeable weights"
            )
    return params


def checkpoint_source(model_dir: Optional[str]):
    """(kind, path) for a model source: ("safetensors", dir),
    ("gguf", file) or ("none", None). The ONE place format precedence
    lives — config resolution and weight loading must always pick the
    same checkpoint in a mixed directory."""
    if model_dir and glob.glob(os.path.join(model_dir, "*.safetensors")):
        return "safetensors", model_dir
    if model_dir:
        from gpustack_tpu.engine.gguf import gguf_file_in

        gguf_path = gguf_file_in(model_dir)
        if gguf_path:
            return "gguf", gguf_path
    return "none", None


def load_or_init_params(
    cfg: ModelConfig, model_dir: Optional[str], seed: int = 0
) -> Dict[str, Any]:
    kind, path = checkpoint_source(model_dir)
    if kind == "safetensors":
        logger.info("loading checkpoint from %s", path)
        return load_hf_checkpoint(cfg, path)
    if kind == "gguf":
        logger.info("loading GGUF checkpoint from %s", path)
        return load_gguf_checkpoint(cfg, path)
    logger.warning(
        "no checkpoint at %r — initializing random weights for %s",
        model_dir, cfg.name,
    )
    return init_params(cfg, jax.random.key(seed))


def save_checkpoint(params: Dict[str, Any], path: str) -> None:
    """Save params in our native stacked layout (orbax-free, npz-based) —
    used for engine-local caching of (possibly int8-quantized) weights.
    ``QuantW`` leaves round-trip via explicit ``::q`` / ``::s`` suffixes."""
    from gpustack_tpu.models.quant import QuantW

    flat: Dict[str, np.ndarray] = {}

    def to_np(leaf) -> tuple:
        """npz has no bfloat16; store as float32 with a dtype tag."""
        arr = np.asarray(leaf)
        if arr.dtype == jnp.bfloat16:
            return arr.astype(np.float32), "#bf16"
        return arr, ""

    def walk(node, prefix: str) -> None:
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, f"{prefix}/{k}" if prefix else k)
        elif isinstance(node, QuantW):
            arr, tag = to_np(node.q)
            flat[prefix + "::q" + tag] = arr
            arr, tag = to_np(node.s)
            flat[prefix + "::s" + tag] = arr
        else:
            arr, tag = to_np(node)
            flat[prefix + tag] = arr

    walk(params, "")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez(path, **flat)


def load_checkpoint(path: str) -> Dict[str, Any]:
    from gpustack_tpu.models.quant import QuantW

    data = np.load(path)
    tree: Dict[str, Any] = {}
    pending_quant: Dict[str, Dict[str, Any]] = {}
    for name, arr in data.items():
        if name.endswith("#bf16"):
            name = name[: -len("#bf16")]
            arr = jnp.asarray(arr).astype(jnp.bfloat16)
        base, _, qs = name.partition("::")
        if qs:
            pending_quant.setdefault(base, {})[qs] = jnp.asarray(arr)
            continue
        parts = name.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(arr)
    for base, qs in pending_quant.items():
        parts = base.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = QuantW(q=qs["q"], s=qs["s"])
    return tree
