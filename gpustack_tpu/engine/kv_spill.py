"""Disk spill tier under the host-RAM KV cache: mmap'd block store.

Third tier of the KV fabric (docs/KV_CACHE.md "Fleet KV fabric"):
blocks evicted from host RAM spill to one file per block on local disk
instead of being dropped, keyed by the SAME content-addressed chain
keys the radix trie uses — so a later prompt sharing the prefix faults
the blocks back instead of re-prefilling. The on-disk format reuses
the KV-transfer wire frame (engine/kv_transfer.py): one file is
``MAGIC + one self-describing frame`` (meta JSON with tokens, dtype,
shapes and a payload crc32), written tmp-then-rename so a crash never
leaves a half-visible block.

Durability contract: this tier is a CACHE, not a store of record. Any
corruption — truncated file, bad magic, crc mismatch, unparseable
meta — degrades to a miss (the file is deleted and a counter bumps),
never a crash and never wrong bytes (the crc covers the payload, and
the radix attach recomputes chain keys from the tokens inside the
frame, so a file renamed to the wrong key can't poison the trie).

Thread contract: all disk I/O (``store``/``load``) runs on the engine's
kv-copy executor (spill happens after eviction returns victims outside
the trie lock; fault-back runs inside ``gather_prefix``, which the
engine already stages through its ``_KVStager``). The in-memory index
has its own lock so residency probes (``has``) from the scheduler
thread are dict lookups, never file I/O.
"""

from __future__ import annotations

import logging
import mmap
import os
import threading
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

SPILL_SUFFIX = ".kvb"


# concurrency contract (checked by `python -m gpustack_tpu.analysis`,
# rule guarded-by): the index and every counter are shared between the
# kv-copy executor (store/load) and scheduler/HTTP readers (snapshot,
# residency probes) — always under `_mu`, never held across file I/O.
GUARDED_BY = {
    "_index": "_mu",
    "_bytes": "_mu",
    "_tick": "_mu",
    "blocks_spilled": "_mu",
    "blocks_loaded": "_mu",
    "bytes_spilled": "_mu",
    "bytes_loaded": "_mu",
    "corrupt": "_mu",
    "evictions": "_mu",
}

# sync-in-dispatch: the scheduler may probe residency/size every step —
# these never touch the filesystem. store()/load() (open/os.replace/
# mmap) stay OFF this list: they run on the kv-copy executor only.
DISPATCH_SYNC_FREE = ("has", "size")


class DiskKVSpill:
    """Byte-bounded one-file-per-block spill store.

    ``scan()`` on construction re-indexes whatever blocks a previous
    engine life left behind (same directory ⇒ restart keeps the tier
    warm); index entries are trusted for residency only — every load
    re-verifies magic + crc and degrades to a miss on any mismatch.
    """

    def __init__(self, directory: str, max_bytes: int):
        self.directory = directory
        self.max_bytes = max(0, int(max_bytes))
        os.makedirs(directory, exist_ok=True)
        self._mu = threading.Lock()
        # key hex -> (file size, insertion tick); tick orders eviction
        self._index: Dict[str, Tuple[int, int]] = {}
        self._bytes = 0
        self._tick = 0
        self.blocks_spilled = 0
        self.blocks_loaded = 0          # fault-backs that verified clean
        self.bytes_spilled = 0
        self.bytes_loaded = 0
        self.corrupt = 0                # loads that degraded to a miss
        self.evictions = 0              # disk-budget evictions
        self._scan()

    # ---- residency ------------------------------------------------------

    def has(self, key_hex: str) -> bool:
        with self._mu:
            return key_hex in self._index

    def size(self, key_hex: str) -> int:
        """Spilled file size (≈ block nbytes + frame meta); 0 when not
        resident. Lets the cache bound a disk-extended match by what
        the RAM budget can actually hold after fault-back."""
        with self._mu:
            entry = self._index.get(key_hex)
            return entry[0] if entry else 0

    @property
    def entries(self) -> int:
        with self._mu:
            return len(self._index)

    @property
    def bytes_used(self) -> int:
        with self._mu:
            return self._bytes

    # ---- spill (RAM -> disk) -------------------------------------------

    def store(self, key_hex: str, frame_bytes: bytes) -> bool:
        """Write one encoded block frame under its chain key. Atomic
        (tmp + rename); any OS error degrades to "not spilled" —
        eviction already dropped the block, losing the spill copy only
        costs a future re-prefill."""
        if self.max_bytes <= 0:
            return False
        path = self._path(key_hex)
        tmp = path + ".tmp"
        try:
            from gpustack_tpu.engine.kv_transfer import MAGIC

            with open(tmp, "wb") as f:
                f.write(MAGIC)
                f.write(frame_bytes)
            os.replace(tmp, path)
        except OSError as e:
            logger.warning("kv spill write failed for %s: %s", key_hex, e)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        size = len(frame_bytes) + 6
        with self._mu:
            prev = self._index.pop(key_hex, None)
            if prev is not None:
                self._bytes -= prev[0]
            self._tick += 1
            self._index[key_hex] = (size, self._tick)
            self._bytes += size
            self.blocks_spilled += 1
            self.bytes_spilled += size
            doomed = self._collect_over_budget_locked()
        for victim in doomed:
            self._unlink(victim)
        return True

    # ---- fault-back (disk -> RAM) --------------------------------------

    def load(self, key_hex: str):
        """Read + verify one spilled block. Returns the decoded
        ``kv_transfer.Frame`` or None (miss). ANY defect — missing
        file, truncated stream, bad magic, crc mismatch, wrong frame
        count — deletes the file, bumps ``corrupt`` (unless simply
        absent) and reads as a miss."""
        with self._mu:
            entry = self._index.get(key_hex)
        if entry is None:
            return None
        path = self._path(key_hex)
        from gpustack_tpu.engine.kv_transfer import decode_stream

        try:
            with open(path, "rb") as f:
                try:
                    buf = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
                except (ValueError, OSError):
                    # empty or unmappable file: read() the (tiny) tail
                    buf = f.read()
                try:
                    frames = decode_stream(bytes(buf))
                finally:
                    if isinstance(buf, mmap.mmap):
                        buf.close()
        except FileNotFoundError:
            # raced an eviction: plain miss, not corruption
            with self._mu:
                self._drop_locked(key_hex)
            return None
        except (OSError, ValueError) as e:
            logger.warning(
                "kv spill block %s unreadable (%s); degrading to a miss",
                key_hex, e,
            )
            self._quarantine(key_hex)
            return None
        if len(frames) != 1 or frames[0].skipped or frames[0].k is None:
            # truncated mid-frame (decoder yields nothing) or a foreign
            # file under our suffix: either way not a usable block
            self._quarantine(key_hex)
            return None
        frame = frames[0]
        with self._mu:
            entry = self._index.get(key_hex)
            if entry is not None:
                self.blocks_loaded += 1
                self.bytes_loaded += entry[0]
        return frame

    def remove(self, key_hex: str) -> None:
        self._unlink(key_hex)

    # ---- internals ------------------------------------------------------

    def _path(self, key_hex: str) -> str:
        return os.path.join(self.directory, key_hex + SPILL_SUFFIX)

    def _scan(self) -> None:
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        # construction-time only, but taken under `_mu` anyway: the
        # index must never be observable half-built, and the uniform
        # discipline is what the guarded-by contract checks
        with self._mu:
            for name in sorted(names):
                if not name.endswith(SPILL_SUFFIX):
                    continue
                key_hex = name[: -len(SPILL_SUFFIX)]
                try:
                    size = os.path.getsize(
                        os.path.join(self.directory, name)
                    )
                except OSError:
                    continue
                self._tick += 1
                self._index[key_hex] = (size, self._tick)
                self._bytes += size
            doomed = self._collect_over_budget_locked()
        for victim in doomed:
            self._unlink(victim)

    def _collect_over_budget_locked(self) -> List[str]:
        """Oldest-spilled-first victims to fall back under budget.
        Caller holds (or is constructing under) the index lock; the
        actual unlinks happen after release."""
        doomed: List[str] = []
        if self.max_bytes <= 0:
            return doomed
        while self._bytes > self.max_bytes and self._index:
            # key the min on the materialized items — a closure over
            # self._index would escape the locked scope statically
            key = min(self._index.items(), key=lambda kv: kv[1][1])[0]
            size, _ = self._index.pop(key)
            self._bytes -= size
            self.evictions += 1
            doomed.append(key)
        return doomed

    def _drop_locked(self, key_hex: str) -> None:
        entry = self._index.pop(key_hex, None)
        if entry is not None:
            self._bytes -= entry[0]

    def note_corrupt(self) -> None:
        """Count a corruption detected by a caller (the host cache's
        fault-back decode path finds defects this tier's own verify
        can't see)."""
        with self._mu:
            self.corrupt += 1

    def _quarantine(self, key_hex: str) -> None:
        self.note_corrupt()
        self._unlink(key_hex)

    def _unlink(self, key_hex: str) -> None:
        with self._mu:
            self._drop_locked(key_hex)
        try:
            os.unlink(self._path(key_hex))
        except OSError:
            pass

    def snapshot(self) -> Dict[str, int]:
        with self._mu:
            return {
                "entries": len(self._index),
                "bytes": self._bytes,
                "blocks_spilled": self.blocks_spilled,
                "blocks_loaded": self.blocks_loaded,
                "bytes_spilled": self.bytes_spilled,
                "bytes_loaded": self.bytes_loaded,
                "corrupt": self.corrupt,
                "evictions": self.evictions,
            }


def encode_spill_frame(blk) -> Tuple[str, bytes]:
    """One host-cache ``_Block`` → ``(key hex, wire frame bytes)`` in
    the block's stored tier (int8 spills as int8 + scales)."""
    from gpustack_tpu.engine.kv_transfer import _dtype_name, encode_frame

    return blk.key.hex(), encode_frame(
        blk.key.hex(), blk.tokens,
        k=blk.k, v=blk.v,
        k_scale=blk.k_scale, v_scale=blk.v_scale,
        dtype=(
            "bfloat16" if str(blk.dtype) == "bfloat16"
            else _dtype_name(blk.dtype)
        ),
    )
