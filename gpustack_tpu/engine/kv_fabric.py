"""Engine-side conversation index: the cluster KV directory's feed.

The server's fleet block directory (server/kv_directory.py) routes on
*cached-prefix mass* — how many of a request's prefix blocks a replica
actually holds. The proxy keys conversations by message-prefix hashes
(server/resilience.conversation_chain); the engine keys KV blocks by
token-block chain hashes. This index is the bridge: at chat-request
finish the API layer records the conversation's message chain alongside
its token ids, and ``summary()`` re-checks block residency across both
cache tiers at scrape time — so the directory's view is an honest
(bounded, approximate) snapshot of what a fresh request would match,
not what was once stored.

Bounded: ``max_entries`` conversations LRU; a summary exposes at most
``max_keys`` chain hashes (most-recent conversations win). Thread-safe:
recorded from request handlers, summarized from the scrape path.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

DEFAULT_MAX_ENTRIES = 1024
DEFAULT_SUMMARY_KEYS = 512


class _Conv:
    __slots__ = ("chain", "tokens")

    def __init__(self, chain: Tuple[str, ...], tokens: np.ndarray):
        self.chain = chain
        self.tokens = tokens


class ConvIndex:
    """Bounded map: conversation chain head → (message chain, tokens)."""

    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES):
        self.max_entries = max(16, int(max_entries))
        self._entries: "collections.OrderedDict[str, _Conv]" = (
            collections.OrderedDict()
        )
        self._mu = threading.Lock()
        self.recorded = 0

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)

    def record(self, chain: Sequence[str], token_ids) -> None:
        """Remember a served conversation: its message-prefix hash
        chain and the token sequence whose KV blocks the cache holds
        (prompt + generated — what turn N+1 will prefix-match)."""
        if not chain or token_ids is None or not len(token_ids):
            return
        conv = _Conv(
            tuple(chain), np.asarray(list(token_ids), np.int32)
        )
        head = conv.chain[-1]
        with self._mu:
            self._entries.pop(head, None)
            self._entries[head] = conv
            self.recorded += 1
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)

    def _recent(self) -> List[Tuple[str, _Conv]]:
        with self._mu:
            return list(reversed(self._entries.items()))

    def summary(
        self, cache, max_keys: int = DEFAULT_SUMMARY_KEYS
    ) -> Dict:
        """The per-replica prefix-key summary the directory scrapes:
        ``keys`` maps each conversation-prefix hash this replica served
        to the block depth actually resident (RAM + disk, re-checked
        NOW) and the deepest RAM block's chain key (the prefetch export
        handle). Most-recent conversations win the ``max_keys`` bound;
        conversations whose blocks fully evicted contribute nothing —
        which is exactly what lets the proxy demote stale affinity
        entries."""
        keys: Dict[str, Dict] = {}
        conversations = 0
        for head, conv in self._recent():
            if len(keys) >= max_keys:
                break
            if cache is None:
                break
            ram, disk = cache.resident_keys(conv.tokens)
            blocks = len(ram) + len(disk)
            if blocks == 0:
                continue
            conversations += 1
            entry = {
                "blocks": blocks,
                "tail": ram[-1] if ram else "",
            }
            for h in conv.chain:
                prev = keys.get(h)
                if prev is None or blocks > prev["blocks"]:
                    keys[h] = entry
        return {"keys": keys, "conversations": conversations}

    def apply_sharing(
        self, cache, sharing: Optional[Dict[str, int]]
    ) -> int:
        """Fold the directory's fleet-wide sharing counts (conversation
        hash → number of replicas holding it) into the cache's eviction
        economics: every resident block of a shared conversation gets
        the sharing boost. Returns blocks updated."""
        if not sharing or cache is None:
            return 0
        updated = 0
        for head, conv in self._recent():
            count = 0
            for h in conv.chain:
                c = sharing.get(h)
                if c is not None and int(c) > count:
                    count = int(c)
            if count <= 1:
                continue
            ram, _ = cache.resident_keys(conv.tokens)
            if ram:
                updated += cache.boost_sharing(ram, count)
        return updated
