"""Host-RAM prefill KV cache: the extended-KV-cache role on TPU.

Reference parity: first-class ``ExtendedKVCacheConfig`` wired into vLLM's
LMCache env/args (schemas/models.py:111-122, worker/backends/vllm.py:
418-436,822-840). On TPU the analogous lever is spilling prefill KV over
PCIe into host RAM: a repeated prompt (system prompts, retried requests,
agent loops) skips its entire prefill — the dominant FLOPs cost for long
prompts — and re-uploads cached K/V instead.

v1 granularity is the whole padded prompt bucket (exact-match). Prefix-
granular reuse (continue prefill from a cached prefix) needs
prefill-from-offset in the runner and is the planned upgrade.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Optional, Tuple

import numpy as np


def _prompt_key(bucket: int, prompt_ids, true_len: int) -> str:
    h = hashlib.sha256()
    h.update(f"{bucket}:{true_len}:".encode())
    h.update(np.asarray(prompt_ids, np.int32).tobytes())
    return h.hexdigest()


class HostKVCache:
    """Byte-bounded LRU of host-resident prefill results.

    Each entry optionally records its true prompt tokens, enabling
    PREFIX reuse: a new prompt that extends a cached one re-uploads the
    cached K/V and prefills only the suffix (prefill-from-offset in the
    runner) — the LMCache-style long-context lever for shared system
    prompts and agent loops.
    """

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        # key -> (arrays, prompt_ids tuple or None)
        self._lru: "OrderedDict[str, Tuple[Tuple[Any, ...], Any]]" = (
            OrderedDict()
        )
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.prefix_hits = 0

    @staticmethod
    def key(bucket: int, prompt_ids, true_len: int) -> str:
        return _prompt_key(bucket, prompt_ids, true_len)

    def get(self, key: str) -> Optional[Tuple[Any, ...]]:
        with self._lock:
            entry = self._lru.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._lru.move_to_end(key)
            self.hits += 1
            return entry[0]

    def find_longest_prefix(
        self, prompt_ids, min_len: int = 32
    ) -> Optional[Tuple[Tuple[Any, ...], int]]:
        """Cached entry whose TRUE prompt is the longest proper prefix
        of ``prompt_ids`` (>= min_len tokens); returns (arrays, plen).
        The caller counts a prefix hit only when it actually USES the
        match (bounds guards may still reject it)."""
        prompt = tuple(prompt_ids)
        # snapshot under the lock, compare outside: the token-by-token
        # comparisons are O(entries x plen) and must not stall the
        # scheduler thread against the copy worker
        with self._lock:
            candidates = [
                (key, arrays, entry_prompt)
                for key, (arrays, entry_prompt) in self._lru.items()
                if entry_prompt is not None
                and min_len <= len(entry_prompt) < len(prompt)
            ]
        best = None
        best_key = None
        best_len = min_len - 1
        for key, arrays, entry_prompt in candidates:
            plen = len(entry_prompt)
            if plen > best_len and prompt[:plen] == entry_prompt:
                best, best_key, best_len = (arrays, plen), key, plen
        if best_key is not None:
            with self._lock:
                if best_key in self._lru:
                    # refresh recency: a hot shared prefix hit only via
                    # extension must not be the first eviction victim
                    self._lru.move_to_end(best_key)
        return best

    def put(
        self, key: str, arrays: Tuple[Any, ...], prompt_ids=None
    ) -> None:
        size = sum(a.nbytes for a in arrays)
        if size > self.max_bytes:
            return  # single entry larger than the whole budget
        with self._lock:
            if key in self._lru:
                self._lru.move_to_end(key)
                return
            self._lru[key] = (
                arrays,
                tuple(prompt_ids) if prompt_ids is not None else None,
            )
            self._bytes += size
            while self._bytes > self.max_bytes and self._lru:
                _, (evicted, _) = self._lru.popitem(last=False)
                self._bytes -= sum(a.nbytes for a in evicted)

    @property
    def entries(self) -> int:
        return len(self._lru)

    @property
    def bytes_used(self) -> int:
        return self._bytes
