"""Host-RAM KV cache: block-granular radix prefix reuse on TPU.

Reference parity: first-class ``ExtendedKVCacheConfig`` wired into vLLM's
LMCache env/args (schemas/models.py:111-122, worker/backends/vllm.py:
418-436,822-840). On TPU the analogous lever is spilling KV over PCIe
into host RAM: a prompt sharing a prefix with any previously served
sequence (system prompts, agent loops, multi-turn chat) re-uploads the
cached K/V for the shared run and prefills only its suffix — skipping
the dominant FLOPs cost for long prompts.

v2 granularity is a fixed-size token **block** (default 256, see
``kv_block_tokens``): KV is split into blocks deduplicated across
requests via a radix trie keyed on rolling token-block hashes —
``child_key = sha256(parent_key || block_token_bytes)`` — so lookup is
O(prompt_len / block) hash-map probes (each hashing one block's bytes,
O(prompt_len) total) instead of the v1 O(entries × prompt_len) linear
scan over whole-prompt entries. Eviction is block-level LRU over leaf
blocks only: an interior block is referenced by its children
(``refs``), so a hot shared system-prompt block survives while cold
per-conversation suffixes evict. Sequences are inserted at *request
finish* (prompt + generated tokens), which is what makes turn N+1 of a
conversation hit the blocks turn N decoded.

Opt-in ``int8`` host tiering quantizes each block with a per-block
scale (amax per layer × head within the block) and dequantizes on
upload, roughly doubling cache capacity per byte of host RAM at a KV
precision cost that greedy-parity tests bound.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

DEFAULT_BLOCK_TOKENS = 256


def _prompt_key(bucket: int, prompt_ids, true_len: int) -> str:
    h = hashlib.sha256()
    h.update(f"{bucket}:{true_len}:".encode())
    h.update(np.asarray(prompt_ids, np.int32).tobytes())
    return h.hexdigest()


def _quantize_block(x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-block int8: scale = amax over (tokens, head_dim) per
    layer × head, so one outlier token degrades only its own block."""
    x32 = np.asarray(x, np.float32)
    scale = np.max(np.abs(x32), axis=(1, 3), keepdims=True) / 127.0
    scale = np.maximum(scale, 1e-8).astype(np.float32)
    q = np.clip(np.rint(x32 / scale), -127, 127).astype(np.int8)
    return q, scale


def _dequantize_block(q: np.ndarray, scale: np.ndarray, dtype) -> np.ndarray:
    return (q.astype(np.float32) * scale).astype(dtype)


class _Block:
    """One cached KV block: ``block_tokens`` tokens of one sequence.

    ``refs`` counts child blocks whose prefix this block is — a block
    with live children can never evict (its children would dangle), the
    refcount behaviour the LRU needs so shared prefixes outlive cold
    suffixes.
    """

    __slots__ = (
        "key", "tokens", "parent", "children", "refs",
        "k", "v", "k_scale", "v_scale", "dtype",
        "nbytes", "last_used", "touches", "sharing",
    )

    def __init__(self, key: bytes, tokens: Tuple[int, ...], parent):
        self.key = key
        self.tokens = tokens
        self.parent = parent
        self.children: Dict[bytes, "_Block"] = {}
        self.refs = 0
        self.k = self.v = None
        self.k_scale = self.v_scale = None
        self.dtype = None
        self.nbytes = 0
        self.last_used = 0
        self.touches = 0     # local reuse count (walk touches)
        self.sharing = 0     # fleet sharing (directory-reported)


# concurrency contract (checked by `python -m gpustack_tpu.analysis`,
# rule guarded-by): the trie and its accounting are shared between the
# engine scheduler (match path) and the kv-copy executor (store/import/
# evict) — always under `_lock`; quantize/assemble/file I/O stay
# outside it (blocks are immutable once attached).
GUARDED_BY = {
    "_root": "_lock",
    "_blocks": "_lock",
    "_bytes": "_lock",
    "_tick": "_lock",
    "hits": "_lock",
    "misses": "_lock",
    "faultbacks": "_lock",
    "blocks_inserted": "_lock",
    "blocks_evicted": "_lock",
}

# sync-in-dispatch: the scheduler calls the match path every admit —
# trie probes and in-memory spill-index lookups only, no file I/O and
# no device syncs (fault-back and assembly run on the kv-copy
# executor via gather_prefix).
DISPATCH_SYNC_FREE = (
    "match_prefix_len", "peek_prefix_len", "_walk", "_disk_extension",
)


class HostKVCache:
    """Byte-bounded block-granular radix prefix cache in host RAM.

    Thread contract: ``match_prefix`` runs on the engine scheduler
    thread, ``put``/``insert_sequence`` on the kv-copy executor. The
    lock guards only the trie walk and accounting; quantization and
    the dequantize+concatenate assembly of a matched run happen outside
    it (block arrays are immutable once attached — eviction drops
    references, it never mutates)."""

    def __init__(
        self,
        max_bytes: int,
        block_tokens: int = DEFAULT_BLOCK_TOKENS,
        int8: bool = False,
    ):
        if block_tokens <= 0:
            raise ValueError(f"block_tokens must be > 0: {block_tokens}")
        self.max_bytes = max_bytes
        self.block_tokens = int(block_tokens)
        self.int8 = bool(int8)
        self._root = _Block(b"", (), None)
        self._blocks: Dict[bytes, _Block] = {}
        self._bytes = 0
        self._tick = 0
        self._lock = threading.Lock()
        # optional disk spill tier (engine/kv_spill.DiskKVSpill):
        # eviction victims spill instead of dropping; matches extend
        # into disk residency and fault back on gather
        self.spill = None
        self.hits = 0            # match_prefix calls that matched >= 1 block
        self.misses = 0          # match_prefix calls that matched nothing
        self.prefix_hits = 0     # matches the engine actually consumed
        self.prefix_tokens_reused = 0   # tokens the engine skipped prefilling
        self.blocks_inserted = 0
        self.blocks_evicted = 0
        self.faultbacks = 0      # disk blocks pulled back into RAM runs

    # ---- keys -----------------------------------------------------------

    @staticmethod
    def key(bucket: int, prompt_ids, true_len: int) -> str:
        return _prompt_key(bucket, prompt_ids, true_len)

    def _child_key(self, parent_key: bytes, tokens) -> bytes:
        h = hashlib.sha256()
        h.update(parent_key)
        h.update(np.asarray(tokens, np.int32).tobytes())
        return h.digest()

    # ---- lookup ---------------------------------------------------------

    def _walk(self, prompt, max_blocks: int, touch: bool) -> List[_Block]:
        """Locked trie walk: the longest cached block run prefixing
        ``prompt``, at most ``max_blocks`` long. O(len/block) probes;
        each hashes one block and verifies the stored tokens (collision
        guard) — total work O(len) in the prompt, never O(entries)."""
        bt = self.block_tokens
        run: List[_Block] = []
        with self._lock:
            node = self._root
            for b in range(max_blocks):
                block = prompt[b * bt : (b + 1) * bt]
                child = node.children.get(self._child_key(node.key, block))
                if child is None or child.tokens != block:
                    break
                run.append(child)
                node = child
            if touch and run:
                self._tick += 1
                for blk in run:
                    blk.last_used = self._tick
                    blk.touches += 1
        return run

    def _disk_extension(
        self, prompt, parent_key: bytes, start_b: int, max_blocks: int
    ) -> List[str]:
        """Hex chain keys of the contiguous DISK-resident continuation
        of a RAM run ending at ``parent_key``. Chain keys derive from
        the tokens alone (content addressing), so no trie state is
        needed — residency probes are in-memory index lookups on the
        spill tier, never file I/O."""
        keys: List[str] = []
        spill = self.spill
        if spill is None:
            return keys
        bt = self.block_tokens
        key = parent_key
        # bound the extension by what the RAM budget can actually hold
        # after fault-back (spill file size ≈ block nbytes): matching
        # deeper than RAM fits would make every gather fail and
        # cold-start — worse than consuming the fittable prefix
        budget = self.max_bytes - start_b * self._avg_block_bytes()
        for b in range(start_b, max_blocks):
            key = self._child_key(key, prompt[b * bt : (b + 1) * bt])
            key_hex = key.hex()
            size = spill.size(key_hex)
            if size <= 0 or size > budget:
                break
            budget -= size
            keys.append(key_hex)
        return keys

    def _avg_block_bytes(self) -> int:
        with self._lock:
            n = len(self._blocks)
            return (self._bytes // n) if n else 0

    def match_prefix_len(self, prompt_ids) -> int:
        """Length of the longest cached block run that is a proper
        prefix of ``prompt_ids`` — a multiple of ``block_tokens``,
        strictly less than ``len(prompt_ids)`` (at least one suffix
        token always remains to prefill, which regenerates the
        last-position logits). Counts one hit or miss per call and
        touches the matched path's recency; no KV bytes move — callers
        trim the length against their bounds guards first and then
        assemble only what they will use via :meth:`gather_prefix`."""
        prompt = tuple(int(t) for t in prompt_ids)
        max_blocks = (len(prompt) - 1) // self.block_tokens
        run = self._walk(prompt, max_blocks, touch=True) if max_blocks > 0 \
            else []
        disk = self._disk_extension(
            prompt, run[-1].key if run else b"", len(run), max_blocks
        )
        with self._lock:
            if run or disk:
                self.hits += 1
            else:
                self.misses += 1
        return (len(run) + len(disk)) * self.block_tokens

    def peek_prefix_len(self, prompt_ids) -> int:
        """Like :meth:`match_prefix_len` but side-effect free (no
        counters, no recency touch) — a probe for tests and benches
        waiting on async stores to land."""
        prompt = tuple(int(t) for t in prompt_ids)
        max_blocks = (len(prompt) - 1) // self.block_tokens
        if max_blocks <= 0:
            return 0
        run = self._walk(prompt, max_blocks, touch=False)
        disk = self._disk_extension(
            prompt, run[-1].key if run else b"", len(run), max_blocks
        )
        return (len(run) + len(disk)) * self.block_tokens

    def gather_prefix(
        self, prompt_ids, length: int
    ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Assemble (dequantize + concatenate) exactly ``length`` tokens
        of cached prefix KV — the post-trim amount the caller will
        actually upload, so no bytes are copied for blocks a bounds
        guard discarded. Returns None when the run is no longer fully
        resident (evicted since the length probe); callers fall back to
        a cold prefill."""
        bt = self.block_tokens
        if length <= 0 or length % bt:
            return None
        prompt = tuple(int(t) for t in prompt_ids[:length])
        run = self._walk(prompt, length // bt, touch=True)
        if len(run) * bt < length:
            # disk fault-back: the probe counted spilled blocks toward
            # the match; pull them into RAM (this method already runs
            # on the kv-copy executor via the engine's stager, so the
            # file reads never block dispatch). Any defect degrades to
            # None — the caller cold-starts.
            if not self._fault_back(prompt, length // bt):
                return None
            run = self._walk(prompt, length // bt, touch=True)
            if len(run) * bt < length:
                return None
        # assembly OUTSIDE the lock: block arrays are immutable once
        # attached (eviction only drops references)
        k = np.concatenate([self._block_k(b) for b in run], axis=1)
        v = np.concatenate([self._block_v(b) for b in run], axis=1)
        return k, v

    def _fault_back(self, prompt: Tuple[int, ...], n_blocks: int) -> bool:
        """Pull the first ``n_blocks`` of ``prompt`` that live only on
        the spill tier back into the RAM trie. Returns True when the
        whole run is RAM-resident afterwards. A missing, corrupt, or
        content-mismatched spill file reads as False (cold prefill) —
        never a crash, never wrong bytes (tokens inside the verified
        frame must equal the prompt's block)."""
        spill = self.spill
        if spill is None:
            return False
        from gpustack_tpu.engine.kv_transfer import _to_cache_tier

        bt = self.block_tokens
        with self._lock:
            resident = set(self._blocks.keys())
        prepared: Dict[int, Tuple] = {}
        key = b""
        complete = True
        for b in range(n_blocks):
            block = prompt[b * bt : (b + 1) * bt]
            key = self._child_key(key, block)
            if key in resident:
                continue
            frame = spill.load(key.hex())
            if frame is None:
                complete = False
                break
            if tuple(frame.tokens) != block:
                # file content does not match its key (rename, foreign
                # file): corruption — quarantine and read as a miss
                spill.note_corrupt()
                spill.remove(key.hex())
                complete = False
                break
            prepared[b] = _to_cache_tier(self, frame)
        if prepared:
            with self._lock:
                _, victims = self._attach_prepared_locked(
                    prompt[: n_blocks * bt], n_blocks, prepared
                )
                self.faultbacks += len(prepared)
            self._spill_victims(victims)
        # the caller's re-walk is the ground truth for whether the run
        # is fully resident now; ``complete`` short-circuits the walk
        # when a load already failed
        return complete

    def prefix_keys(self, prompt_ids) -> List[str]:
        """Hex chain keys of the longest cached block run prefixing
        ``prompt_ids`` (side-effect free). The KV-transfer dedup
        protocol: a puller declares these so the exporter elides blocks
        it already holds."""
        prompt = tuple(int(t) for t in prompt_ids)
        max_blocks = (len(prompt) - 1) // self.block_tokens
        if max_blocks <= 0:
            return []
        return [
            b.key.hex()
            for b in self._walk(prompt, max_blocks, touch=False)
        ]

    def resident_keys(
        self, prompt_ids
    ) -> Tuple[List[str], List[str]]:
        """``(ram_keys, disk_keys)`` of the longest resident block run
        prefixing ``prompt_ids`` across both tiers (side-effect free).
        ``prefix_keys`` stays RAM-only on purpose — it feeds the wire
        ``have`` dedup, and a skipped frame for a disk-resident block
        would end the import's attach run at the RAM trie gap."""
        prompt = tuple(int(t) for t in prompt_ids)
        max_blocks = (len(prompt) - 1) // self.block_tokens
        if max_blocks <= 0:
            return [], []
        run = self._walk(prompt, max_blocks, touch=False)
        disk = self._disk_extension(
            prompt, run[-1].key if run else b"", len(run), max_blocks
        )
        return [b.key.hex() for b in run], disk

    def boost_sharing(self, keys_hex, count: int) -> int:
        """Record the fleet-wide sharing count the cluster directory
        reports for these chain keys — the eviction score divides by
        it, so a block many replicas hold locally (a shared system
        prompt) outlives cold per-conversation suffixes. Returns how
        many resident blocks were updated."""
        count = max(0, int(count))
        updated = 0
        with self._lock:
            for key_hex in keys_hex:
                try:
                    blk = self._blocks.get(bytes.fromhex(key_hex))
                except ValueError:
                    continue
                if blk is not None and blk.sharing < count:
                    blk.sharing = count
                    updated += 1
        return updated

    def export_chain(self, tail_key_hex: str) -> List[dict]:
        """The RAM-resident block chain ending at ``tail_key_hex``
        (root → tail), in the same dict shape as :meth:`export_blocks`
        — the prefetch export path, which is keyed by chain key because
        the puller has no token ids, only the directory's summary."""
        try:
            tail = bytes.fromhex(tail_key_hex)
        except ValueError:
            return []
        chain: List[_Block] = []
        with self._lock:
            node = self._blocks.get(tail)
            self._tick += 1
            while node is not None and node is not self._root:
                node.last_used = self._tick
                chain.append(node)
                node = node.parent
        chain.reverse()
        return [
            {
                "key": b.key.hex(),
                "tokens": b.tokens,
                "k": b.k,
                "v": b.v,
                "k_scale": b.k_scale,
                "v_scale": b.v_scale,
                "dtype": (
                    "bfloat16"
                    if str(b.dtype) == "bfloat16"
                    else np.dtype(b.dtype).name
                ),
                "nbytes": b.nbytes,
            }
            for b in chain
        ]

    def export_blocks(
        self, prompt_ids, max_blocks: int = 0
    ) -> List[dict]:
        """The matched block run for ``prompt_ids`` AS STORED (int8
        tiers export quantized + scales — no dequantize work, half the
        wire bytes), for the KV-transfer exporter
        (engine/kv_transfer.py). The walk touches recency (an exported
        block is a hot block); array references are safe outside the
        lock because blocks are immutable once attached."""
        prompt = tuple(int(t) for t in prompt_ids)
        limit = (len(prompt) - 1) // self.block_tokens
        if max_blocks > 0:
            limit = min(limit, max_blocks)
        if limit <= 0:
            return []
        run = self._walk(prompt, limit, touch=True)
        return [
            {
                "key": b.key.hex(),
                "tokens": b.tokens,
                "k": b.k,
                "v": b.v,
                "k_scale": b.k_scale,
                "v_scale": b.v_scale,
                "dtype": (
                    "bfloat16"
                    if str(b.dtype) == "bfloat16"
                    else np.dtype(b.dtype).name
                ),
                "nbytes": b.nbytes,
            }
            for b in run
        ]

    def import_blocks(self, token_ids, prepared: Dict[int, Tuple]) -> int:
        """Attach pre-converted blocks received over the wire:
        ``prepared[b]`` is ``(k, v, scales|None, dtype, nbytes)`` for
        block index ``b`` of ``token_ids``. Keys are recomputed from
        the tokens (content addressing survives the wire); a gap —
        neither cached nor provided — ends the run, so a truncated
        transfer lands its complete prefix and nothing else."""
        tokens = tuple(int(t) for t in token_ids)
        n_blocks = len(tokens) // self.block_tokens
        if n_blocks <= 0:
            return 0
        with self._lock:
            inserted, victims = self._attach_prepared_locked(
                tokens, n_blocks, prepared
            )
        self._spill_victims(victims)
        return inserted

    def match_prefix(
        self, prompt_ids
    ) -> Optional[Tuple[np.ndarray, np.ndarray, int]]:
        """One-shot convenience (tests, small prompts): longest proper
        prefix run fully assembled. The engine uses the two-phase
        match_prefix_len → trim → gather_prefix flow instead, so it
        never assembles bytes its bounds guards then discard."""
        matched = self.match_prefix_len(prompt_ids)
        if matched <= 0:
            return None
        got = self.gather_prefix(prompt_ids, matched)
        if got is None:
            return None
        return got[0], got[1], matched

    def _block_k(self, blk: _Block) -> np.ndarray:
        if blk.k_scale is None:
            return blk.k
        return _dequantize_block(blk.k, blk.k_scale, blk.dtype)

    def _block_v(self, blk: _Block) -> np.ndarray:
        if blk.v_scale is None:
            return blk.v
        return _dequantize_block(blk.v, blk.v_scale, blk.dtype)

    # ---- insert ---------------------------------------------------------

    def insert_sequence(self, token_ids, k, v) -> int:
        """Split ``(k, v)`` (``[L, T, H, hd]`` with ``T >=
        len(token_ids)``; extra width is bucket padding) into full
        blocks and attach any that are not already cached. Existing
        blocks are touched (LRU recency), never re-stored — that is the
        cross-request dedup. Returns the number of new blocks."""
        tokens = tuple(int(t) for t in token_ids)
        bt = self.block_tokens
        n_blocks = len(tokens) // bt
        if n_blocks <= 0:
            return 0
        k = np.asarray(k)
        v = np.asarray(v)
        # Walk under the lock FIRST to find where new blocks start
        # (touching the shared prefix's recency on the way), so the
        # quantize/copy work below runs only for the genuinely new
        # suffix — a turn-N conversation store must not re-quantize
        # turn 1's blocks just to discard them at the dedup check.
        start = 0
        with self._lock:
            node = self._root
            for b in range(n_blocks):
                block = tokens[b * bt : (b + 1) * bt]
                child = node.children.get(self._child_key(node.key, block))
                if child is None or child.tokens != block:
                    break
                self._tick += 1
                child.last_used = self._tick
                node = child
                start += 1
        if start == n_blocks:
            return 0
        # quantize/copy OUTSIDE the lock, new suffix blocks only
        prepared: Dict[int, Tuple[Any, Any, Any, Any, int]] = {}
        for b in range(start, n_blocks):
            bk = k[:, b * bt : (b + 1) * bt]
            bv = v[:, b * bt : (b + 1) * bt]
            if self.int8:
                qk, sk = _quantize_block(bk)
                qv, sv = _quantize_block(bv)
                nbytes = qk.nbytes + qv.nbytes + sk.nbytes + sv.nbytes
                prepared[b] = (qk, qv, (sk, sv), k.dtype, nbytes)
            else:
                bk = np.ascontiguousarray(bk)
                bv = np.ascontiguousarray(bv)
                prepared[b] = (
                    bk, bv, None, k.dtype, bk.nbytes + bv.nbytes
                )
        with self._lock:
            inserted, victims = self._attach_prepared_locked(
                tokens, n_blocks, prepared
            )
        self._spill_victims(victims)
        return inserted

    def _attach_prepared_locked(
        self, tokens: Tuple[int, ...], n_blocks: int,
        prepared: Dict[int, Tuple],
    ) -> Tuple[int, List[_Block]]:
        """Attach phase shared by the local store (insert_sequence) and
        the wire import (import_blocks): re-walk from the root — the
        trie may have changed since any earlier walk (concurrent
        insert, eviction of the walked prefix) — touch existing blocks,
        attach prepared ones, and end the run at the first block that
        is neither (evicted prefix or transfer gap)."""
        bt = self.block_tokens
        inserted = 0
        node = self._root
        for b in range(n_blocks):
            block = tokens[b * bt : (b + 1) * bt]
            key = self._child_key(node.key, block)
            child = node.children.get(key)
            if child is not None and child.tokens == block:
                self._tick += 1
                child.last_used = self._tick
                node = child
                continue
            if b not in prepared:
                break
            bk, bv, scales, dtype, nbytes = prepared[b]
            if nbytes > self.max_bytes:
                break   # one block over the whole budget: stop here
            child = _Block(key, block, node)
            child.k, child.v = bk, bv
            if scales is not None:
                child.k_scale, child.v_scale = scales
            child.dtype = dtype
            child.nbytes = nbytes
            self._tick += 1
            child.last_used = self._tick
            node.children[key] = child
            node.refs += 1
            self._blocks[key] = child
            self._bytes += nbytes
            self.blocks_inserted += 1
            inserted += 1
            node = child
        return inserted, self._evict_locked()

    def _eviction_score_locked(self, blk: _Block) -> float:
        """Eviction economics (docs/KV_CACHE.md "Fleet KV fabric"):
        bytes × age / (1 + sharing) instead of plain LRU — a large
        stale block evicts before a small one, but a block many
        requests (``touches``) or many replicas (directory-reported
        ``sharing``) lean on survives past its raw recency. Highest
        score evicts first."""
        age = max(1, self._tick - blk.last_used + 1)
        reuse = blk.sharing + min(blk.touches, 8)
        return (blk.nbytes * age) / (1.0 + reuse)

    def _evict_locked(self) -> List[_Block]:
        """Detach worst-scoring leaf blocks until back under budget and
        return them — the caller spills them to the disk tier (file
        I/O must happen OUTSIDE the trie lock). Leaf-only: ``refs > 0``
        means children still extend this block. O(#leaves) per evicted
        block — fine at the hundreds-to-thousands of blocks a host-RAM
        budget holds."""
        victims: List[_Block] = []
        while self._bytes > self.max_bytes and self._blocks:
            victim = None
            score = -1.0
            for blk in self._blocks.values():
                if blk.refs:
                    continue
                s = self._eviction_score_locked(blk)
                if s > score:
                    victim, score = blk, s
            if victim is None:       # all blocks interior (can't happen
                break                # while leaves exist, but stay safe)
            parent = victim.parent
            del parent.children[victim.key]
            parent.refs -= 1
            del self._blocks[victim.key]
            self._bytes -= victim.nbytes
            self.blocks_evicted += 1
            victims.append(victim)
        return victims

    def _spill_victims(self, victims: List[_Block]) -> None:
        """Write evicted blocks to the disk tier (no-op without one).
        Runs outside the trie lock on whatever thread performed the
        attach (kv-copy executor for the engine's paths). Blocks whose
        spill file already exists (a faulted-back copy re-evicting)
        skip the rewrite."""
        spill = self.spill
        if spill is None or not victims:
            return
        from gpustack_tpu.engine.kv_spill import encode_spill_frame

        for blk in victims:
            key_hex = blk.key.hex()
            if spill.has(key_hex):
                continue
            spill.store(key_hex, encode_spill_frame(blk)[1])

    # ---- legacy store surface ------------------------------------------

    def put(
        self, key: str, arrays: Tuple[Any, ...], prompt_ids=None
    ) -> None:
        """Store a finished prefill's KV under its sequence tokens.

        ``arrays`` is ``(last_logits, k, v)`` (the v1 exact-entry
        shape) or ``(k, v)``; only the K/V blocks are retained — block
        granularity subsumes the exact-match tier (an identical prompt
        re-matches every full block and prefills a >= 1 token tail).
        A ``key`` whose first put lacked ``prompt_ids`` is upgraded in
        place when a later put supplies them, instead of early-returning
        with the tokens dropped (the v1 bug)."""
        if len(arrays) == 3:
            _, k, v = arrays
        else:
            k, v = arrays
        if prompt_ids is None:
            return  # nothing placeable in the trie without the tokens
        # ALWAYS insert: the trie walk dedups existing blocks cheaply,
        # a put whose first call lacked prompt_ids upgrades the moment
        # the tokens arrive (the v1 key-level early-return dropped
        # them), and a key whose blocks were evicted under pressure
        # rejoins the cache on its next prefill store
        self.insert_sequence(tuple(int(t) for t in prompt_ids), k, v)

    # ---- introspection --------------------------------------------------

    @property
    def entries(self) -> int:
        with self._lock:
            return len(self._blocks)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes
