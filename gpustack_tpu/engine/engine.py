"""Continuous-batching engine: request queue → slots → streamed tokens.

This is the TPU replacement for the engine containers the reference
launches (reference gpustack/worker/backends/vllm.py role): an in-process
orchestrator around :class:`~gpustack_tpu.engine.runner.ModelRunner`.

Scheduling loop (one thread, device never idles on the host):

1. admit: while a slot is free and requests wait → prefill (bucketed) +
   insert.
2. decode: one ``decode_step`` advances all active slots; sampled tokens are
   fetched with a small async lag so the device pipeline stays full.
3. retire: EOS / max_tokens / capacity → free slot, finish stream.

The reference's per-instance health probe contract (serve_manager health
checks) maps to :meth:`LLMEngine.health`.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from gpustack_tpu.engine.runner import DecodeState, ModelRunner
from gpustack_tpu.engine.tokenizer import load_tokenizer
from gpustack_tpu.models.config import ModelConfig

logger = logging.getLogger(__name__)

_FETCH_LAG = 2  # decode steps in flight before the host inspects tokens


@dataclasses.dataclass
class GenRequest:
    """One generation request (already tokenized)."""

    prompt_ids: List[int]
    max_tokens: int = 128
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    stop_ids: Tuple[int, ...] = ()
    stop_texts: Tuple[str, ...] = ()       # OpenAI 'stop' strings
    stream: Optional[queue.Queue] = None   # receives (token_id, text_piece)
    request_id: str = ""

    # filled by the engine
    output_ids: List[int] = dataclasses.field(default_factory=list)
    output_text: str = ""                  # stop-truncated decoded text
    finish_reason: str = ""
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    submitted_at: float = 0.0
    first_token_at: float = 0.0
    finished_at: float = 0.0

    @property
    def ttft_ms(self) -> float:
        return (self.first_token_at - self.submitted_at) * 1e3


@dataclasses.dataclass
class _SlotInfo:
    request: GenRequest
    # Incremental detokenization state: undecoded token ids are buffered
    # until they decode cleanly (no dangling multibyte sequence), then the
    # text accumulates here — the tokenizer only ever decodes the small
    # buffer, keeping streaming O(tokens) instead of O(tokens^2).
    buffer_ids: List[int] = dataclasses.field(default_factory=list)
    text: str = ""            # decoded text (post stop-truncation)
    emitted: int = 0          # chars of ``text`` already streamed


class LLMEngine:
    """Single-replica continuous-batching LLM engine."""

    def __init__(
        self,
        cfg: ModelConfig,
        params: Dict[str, Any],
        *,
        tokenizer=None,
        model_dir: Optional[str] = None,
        max_slots: int = 8,
        max_seq_len: int = 1024,
        plan=None,
        mesh=None,
        seed: int = 0,
    ):
        self.cfg = cfg
        self.tokenizer = tokenizer or load_tokenizer(model_dir)
        self.runner = ModelRunner(
            cfg, params, plan=plan, mesh=mesh,
            max_slots=max_slots, max_seq_len=max_seq_len,
        )
        self.max_slots = max_slots
        self.max_seq_len = max_seq_len
        self._state: DecodeState = self.runner.new_state()
        self._slots: Dict[int, _SlotInfo] = {}
        self._free = list(range(max_slots))
        self._waiting: "queue.Queue[GenRequest]" = queue.Queue()
        self._key = jax.random.key(seed)
        self._pending: List[Tuple[Any, Dict[int, int]]] = []
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._id_counter = itertools.count()
        self._step_count = 0
        self._tokens_generated = 0

    # ---- public API -----------------------------------------------------

    def submit(self, req: GenRequest) -> GenRequest:
        if not req.request_id:
            req.request_id = f"req-{next(self._id_counter)}"
        req.submitted_at = time.time()
        if len(req.prompt_ids) >= self.max_seq_len:
            raise ValueError(
                f"prompt of {len(req.prompt_ids)} tokens >= max_seq_len "
                f"{self.max_seq_len}"
            )
        self._waiting.put(req)
        return req

    def generate(self, req: GenRequest, timeout: float = 300.0) -> GenRequest:
        """Blocking helper: submit and wait for completion."""
        self.submit(req)
        if not req.done.wait(timeout):
            raise TimeoutError(f"request {req.request_id} timed out")
        return req

    def start(self) -> None:
        self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="llm-engine", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread:
            self._thread.join(timeout=30)

    def health(self) -> Dict[str, Any]:
        return {
            "status": "ok",
            "model": self.cfg.name,
            "slots_total": self.max_slots,
            "slots_used": self.max_slots - len(self._free),
            "waiting": self._waiting.qsize(),
            "steps": self._step_count,
            "tokens_generated": self._tokens_generated,
        }

    # ---- scheduling loop ------------------------------------------------

    def _loop(self) -> None:
        while self._running:
            busy = self.step()
            if not busy:
                time.sleep(0.002)

    def step(self) -> bool:
        """One scheduling iteration. Returns False when fully idle."""
        admitted = self._admit()
        if self._slots:
            self._decode_once()
            return True
        if admitted:
            return True
        # Nothing active: drain any lagging fetches so finished requests
        # complete deterministically.
        self._drain_pending()
        return not self._waiting.empty()

    # admit as many waiting requests as there are free slots
    def _admit(self) -> bool:
        admitted = False
        while self._free and not self._waiting.empty():
            try:
                req = self._waiting.get_nowait()
            except queue.Empty:
                break
            slot = self._free.pop(0)
            self._start_request(slot, req)
            admitted = True
        return admitted

    def _start_request(self, slot: int, req: GenRequest) -> None:
        import jax.numpy as jnp

        from gpustack_tpu.engine.sampling import SamplingState, sample

        ids = req.prompt_ids
        bucket = self.runner.bucket_for(max(1, len(ids)))
        padded = list(ids) + [0] * (bucket - len(ids))
        last_logits, k, v = self.runner.prefill(padded, len(ids))
        # First generated token: same device sampler as decode, one row —
        # one sampling semantics for the whole sequence, seeded by the
        # engine's key.
        self._key, first_key = jax.random.split(self._key)
        first = int(
            sample(
                last_logits[None, :],
                SamplingState(
                    temperature=jnp.asarray([req.temperature], jnp.float32),
                    top_k=jnp.asarray([req.top_k], jnp.int32),
                    top_p=jnp.asarray([req.top_p], jnp.float32),
                ),
                first_key,
            )[0]
        )
        req.first_token_at = time.time()
        self._state = self.runner.insert(
            self._state, k, v, slot, len(ids), first,
            req.temperature, req.top_k, req.top_p,
        )
        info = _SlotInfo(request=req)
        self._slots[slot] = info
        self._deliver(slot, info, [first])

    def _decode_once(self) -> None:
        self._key, step_key = jax.random.split(self._key)
        self._state, sampled = self.runner.decode_step(self._state, step_key)
        self._step_count += 1
        # Snapshot slot ownership at dispatch time: by the time this step's
        # tokens are fetched (lagged), a slot may have been retired and
        # re-used — the request_id check drops such stale tokens.
        owners = {
            s: info.request.request_id for s, info in self._slots.items()
        }
        self._pending.append((sampled, owners))
        if len(self._pending) > _FETCH_LAG:
            self._process_fetch(*self._pending.pop(0))

    def _drain_pending(self) -> None:
        while self._pending:
            self._process_fetch(*self._pending.pop(0))

    def _process_fetch(self, sampled, owners: Dict[int, str]) -> None:
        tokens = np.asarray(sampled)  # sync point (lagged)
        for slot, owner_id in owners.items():
            info = self._slots.get(slot)
            if info is None or info.request.request_id != owner_id:
                continue
            self._deliver(slot, info, [int(tokens[slot])])

    def _deliver(self, slot: int, info: _SlotInfo, toks: List[int]) -> None:
        req = info.request
        for tok in toks:
            is_eos = tok in self.tokenizer.eos_ids or tok in req.stop_ids
            if not is_eos:
                req.output_ids.append(tok)
                self._tokens_generated += 1
                info.buffer_ids.append(tok)
                if self._emit_text(info, final=False):
                    self._finish(slot, info, "stop")
                    return
            at_cap = (
                len(req.prompt_ids) + len(req.output_ids)
                >= self.max_seq_len - 1
            )
            if is_eos or at_cap or len(req.output_ids) >= req.max_tokens:
                self._finish(slot, info, "stop" if is_eos else "length")
                return

    def _emit_text(self, info: _SlotInfo, final: bool) -> bool:
        """Advance incremental detokenization; stream newly-safe text.

        Returns True when a stop string matched (text already truncated and
        flushed). Text that could still turn into a stop string — or a
        dangling multibyte sequence — is held back until resolved.
        """
        req = info.request
        if info.buffer_ids:
            piece = self.tokenizer.decode(info.buffer_ids)
            if final or not piece.endswith("�"):
                info.text += piece
                info.buffer_ids.clear()
        unemitted = info.text[info.emitted:]
        # Stop-string search: hold-back guarantees no stop can straddle the
        # emitted boundary, so searching the unemitted tail is complete.
        for s in req.stop_texts:
            idx = unemitted.find(s)
            if idx != -1:
                info.text = info.text[: info.emitted + idx]
                self._push(info, info.text[info.emitted:])
                return True
        hold = 0
        if not final:
            for s in req.stop_texts:
                for k in range(min(len(s) - 1, len(unemitted)), 0, -1):
                    if unemitted.endswith(s[:k]):
                        hold = max(hold, k)
                        break
        self._push(info, unemitted[: len(unemitted) - hold] if hold else unemitted)
        return False

    def _push(self, info: _SlotInfo, piece: str) -> None:
        if not piece:
            return
        info.emitted += len(piece)
        req = info.request
        if req.stream is not None:
            last = req.output_ids[-1] if req.output_ids else 0
            req.stream.put((last, piece))

    def _finish(self, slot: int, info: _SlotInfo, reason: str) -> None:
        req = info.request
        # A late stop-match during the final flush upgrades the reason.
        if self._emit_text(info, final=True):
            reason = "stop"
        req.finish_reason = reason
        req.output_text = info.text
        req.finished_at = time.time()
        self._state = self.runner.deactivate(self._state, slot)
        del self._slots[slot]
        self._free.append(slot)
        if req.stream is not None:
            req.stream.put(None)  # sentinel: stream end
        req.done.set()
