"""Continuous-batching engine: request queue → slots → streamed tokens.

This is the TPU replacement for the engine containers the reference
launches (reference gpustack/worker/backends/vllm.py role): an in-process
orchestrator around :class:`~gpustack_tpu.engine.runner.ModelRunner`.

Overlapped scheduling (one dispatch thread that never waits on the
device, ``pipeline_depth`` steps of work in flight — the
``--async-scheduling`` role the reference Performance Lab credits its
biggest serving wins to):

1. admit: while a slot is free and requests wait → prefill (bucketed) +
   insert. The first sampled token is fed on-device (a device scalar
   into ``insert``), so admission dispatches N+1's prefill while N's
   sample is still in flight.
2. decode: one ``decode_step`` advances all active slots; sampled tokens
   are fetched ``pipeline_depth`` steps behind dispatch. When a lagged
   fetch reveals a slot finished, the speculatively dispatched steps
   for it are rolled back host-side (dropped + counted) and the slot is
   re-tenanted cleanly.
3. retire: EOS / max_tokens / capacity → free slot; detokenization and
   SSE stream writes ride a dedicated worker thread so tokenizer calls
   and client queues never stall dispatch.

``pipeline_depth=0`` is the serial reference mode (fetch + inline
detok every step) — greedy outputs are bit-identical across modes; the
parity suite (tests/engine/test_overlap.py) enforces it.

The reference's per-instance health probe contract (serve_manager health
checks) maps to :meth:`LLMEngine.health`.
"""

from __future__ import annotations

import collections
import concurrent.futures
import dataclasses
import itertools
import logging
import os
import queue
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from gpustack_tpu.engine.runner import DecodeState, ModelRunner
from gpustack_tpu.engine.tokenizer import load_tokenizer
from gpustack_tpu.models.config import ModelConfig
from gpustack_tpu.observability import flight as _flight

logger = logging.getLogger(__name__)

# default decode-fetch pipeline depth: decode steps in flight before the
# host inspects tokens (ModelSpec.engine_pipeline_depth / Config
# engine_pipeline_depth override it per deployment; 0 = serial mode)
_FETCH_LAG = 2

# sync-in-dispatch contract (analysis/rules/sync_dispatch.py): these
# functions form the scheduler dispatch path and must never block on the
# device — the analyzer flags np.asarray / .item() /
# jax.block_until_ready / jax.device_get inside them (nested def bodies
# excluded: they run on worker threads). Host syncs belong in the
# designated fetch/drain helpers (_process_fetch, _drain_pending,
# _draft_propose, _upload_prefix, _resolve_staged_prefix) or off-thread.
DISPATCH_SYNC_FREE = (
    "_loop", "step", "_admit", "_start_request", "_finalize_start",
    "_new_slot_info", "_plan_chunk_job", "_advance_chunk",
    "_decode_once", "_note_spec_dispatch", "_spec_safe", "_deliver",
    "_emit_text", "_push", "_finish", "_flight_record",
    "_submit_kv_copy", "_store_finished_sequence", "_build_proposals",
    "_entry_ready", "_drain_ready", "_advance_one_shot",
    "_flush_detok",
)

# guarded-by contract (analysis/rules/guarded_by.py): lock-guarded
# shared state, plus the scheduler thread's single-owner state. An
# owner list means "only these methods — all of which run on the
# scheduler thread — may touch the attribute"; a lock there would be
# pure overhead on the dispatch path. Cross-thread observational reads
# (health gauges) carry explicit `# analysis: ignore[guarded-by]`.
_SCHEDULER_METHODS = (
    "step", "_loop", "_admit", "_advance_chunk", "_advance_one_shot",
    "_build_proposals", "_decode_once", "_draft_propose",
    "_fail_all_requests", "_finalize_start", "_finalize_start_sync",
    "_finish", "_flight_record", "_process_fetch", "_drain_pending",
    "_drain_ready", "_start_request", "_deliver", "_flush_detok",
    "_store_finished_sequence", "_upload_prefix",
    "_resolve_staged_prefix", "_plan_chunk_job", "_new_slot_info",
    "_emit_text", "_push", "_note_spec_dispatch", "_spec_safe",
    "_entry_ready", "_submit_kv_copy",
)

GUARDED_BY = {
    "_overlap_s": "_overlap_mu",
    "_profile": "_profile_mu",
    "_KVStager._inflight": "_mu",
    "_slots": _SCHEDULER_METHODS,
    "_free": _SCHEDULER_METHODS,
    "_pending": _SCHEDULER_METHODS,
    "_chunk_jobs": _SCHEDULER_METHODS,
    "_detok_batch": _SCHEDULER_METHODS,
    "_overlap_seen": _SCHEDULER_METHODS,
    "_state": _SCHEDULER_METHODS,
    "_key": _SCHEDULER_METHODS,
}

# thread-boundary contract (analysis/rules/thread_boundary.py): the
# scheduler's working state must never be reached from `async def`
# bodies — the HTTP layer talks to the engine through submit()/health()
# and the thread-safe queues only.
THREAD_OWNED = (
    "_slots", "_free", "_pending", "_chunk_jobs", "_detok_batch",
    "_state",
)


class LatencyHistogram:
    """Fixed-bucket Prometheus-style histogram (counts are cumulative
    per bucket at render time, kept simple here as per-bucket tallies).

    The reference normalizes vLLM's ttft/tpot histograms into its
    dashboard pipeline (metrics_config.yaml); the in-repo engine emits
    the same shapes natively."""

    def __init__(self, buckets):
        self.buckets = tuple(buckets)       # upper bounds, seconds
        self.counts = [0] * (len(self.buckets) + 1)   # +Inf tail
        self.total = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        # bucket BEFORE count: snapshot() reads count first, so a racing
        # scrape can under-report count but never show count > +Inf
        # bucket (which would corrupt histogram_quantile)
        self.total += value
        for i, ub in enumerate(self.buckets):
            if value <= ub:
                self.counts[i] += 1
                break
        else:
            self.counts[-1] += 1
        self.count += 1

    def snapshot(self):
        """[(le, cumulative_count)], sum, count — count read first (see
        observe) and clamped to the +Inf bucket so the exposition always
        satisfies count <= bucket{le=\"+Inf\"}."""
        count = self.count
        cum, out = 0, []
        for ub, c in zip(self.buckets, self.counts):
            cum += c
            out.append((ub, cum))
        inf = cum + self.counts[-1]
        out.append((float("inf"), inf))
        return out, self.total, min(count, inf)


TTFT_BUCKETS_S = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
TPOT_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5)
E2E_BUCKETS_S = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)


def _ngram_propose(ctx: List[int], k: int, n: int = 2) -> List[int]:
    """Propose up to k continuation tokens: find the latest earlier
    occurrence of the context's final n-gram and replay what followed
    (the reference exposes the same idea as vLLM's ngram speculative
    mode via engine args, vllm.py:531). O(len) reference version — the
    engine hot loop uses the incremental :class:`_NgramIndex`."""
    if k <= 0 or len(ctx) < n + 1:
        return []
    key = tuple(ctx[-n:])
    for i in range(len(ctx) - n - 1, -1, -1):
        if tuple(ctx[i : i + n]) == key:
            return list(ctx[i + n : i + n + k])
    return []


class _NgramIndex:
    """Incremental 2-gram index: O(1) proposal lookup per decode step.

    ``prev[g]`` is the end-index of the latest occurrence of 2-gram ``g``
    *before* its most recent one — exactly what the proposer needs, since
    the most recent occurrence of the context's final 2-gram is always the
    context tail itself.
    """

    def __init__(self, ctx: List[int], n: int = 2):
        self.n = n
        self.ctx = list(ctx)
        self.cur: Dict[tuple, int] = {}
        self.prev: Dict[tuple, int] = {}
        for end in range(n, len(self.ctx) + 1):
            self._register(tuple(self.ctx[end - n : end]), end)

    def _register(self, gram: tuple, end: int) -> None:
        if gram in self.cur:
            self.prev[gram] = self.cur[gram]
        self.cur[gram] = end

    def append(self, token: int) -> None:
        self.ctx.append(token)
        if len(self.ctx) >= self.n:
            self._register(tuple(self.ctx[-self.n:]), len(self.ctx))

    def propose(self, k: int) -> List[int]:
        if k <= 0 or len(self.ctx) < self.n + 1:
            return []
        end = self.prev.get(tuple(self.ctx[-self.n:]))
        if end is None:
            return []
        return self.ctx[end : end + k]


@dataclasses.dataclass
class GenRequest:
    """One generation request (already tokenized)."""

    prompt_ids: List[int]
    max_tokens: int = 128
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: Optional[int] = None             # OpenAI 'seed': deterministic replay
    logit_bias: Optional[Dict[int, float]] = None   # token id -> bias
    stop_ids: Tuple[int, ...] = ()
    stop_texts: Tuple[str, ...] = ()       # OpenAI 'stop' strings
    logprobs: bool = False                 # collect per-token logprobs
    top_logprobs: int = 0                  # alternatives per position (<= 20)
    json_mode: bool = False                # stop after one complete JSON value
    # VLM: (embeds [T, D] f32, mask [T] bool) overriding placeholder rows
    embeds_override: Optional[Tuple[Any, Any]] = None
    stream: Optional[queue.Queue] = None   # receives (token_id, text_piece)
    request_id: str = ""

    # filled by the engine
    output_ids: List[int] = dataclasses.field(default_factory=list)
    output_text: str = ""                  # stop-truncated decoded text
    # aligned with output_ids when logprobs: per-token logprob and
    # [(token_id, logprob)] alternatives
    output_logprobs: List[float] = dataclasses.field(default_factory=list)
    output_top_logprobs: List[List[Tuple[int, float]]] = dataclasses.field(
        default_factory=list
    )
    finish_reason: str = ""
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    # client gone (SSE disconnect, proxy timeout): the engine stops
    # generating for this request at its next delivery instead of
    # burning the slot to max_tokens (advisor r4)
    aborted: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )
    submitted_at: float = 0.0
    first_token_at: float = 0.0
    finished_at: float = 0.0
    # host KV cache accounting for this request: prefix tokens whose
    # prefill was skipped, and the host→device upload seconds spent
    # re-materializing them (surfaced as the trace's kv_upload phase)
    prefix_tokens_reused: int = 0
    kv_upload_s: float = 0.0

    def abort(self) -> None:
        self.aborted.set()

    @property
    def ttft_ms(self) -> float:
        return (self.first_token_at - self.submitted_at) * 1e3


@dataclasses.dataclass
class _ChunkJob:
    """An in-progress chunked prefill occupying a slot (not yet decoding)."""

    req: "GenRequest"
    ids: List[int]
    done: int = 0            # tokens prefilled so far
    last: Any = None         # last-position logits of the latest chunk
    k: Any = None            # accumulated KV [L, bucket, H, hd]
    v: Any = None
    # staged prefix upload in flight on the kv-copy executor (double
    # buffering): resolves to (k, v, prefix_len) or None on eviction —
    # the job cold-starts then. While pending, decode for running slots
    # proceeds; that concurrency is the overlap win.
    pending_kv: Any = None
    # deferred ONE-SHOT prefill (non-chunked prefix hit): the single
    # "chunk" is the entire suffix, run the step after the staged
    # upload lands — the job shape that un-blocks the scheduler from
    # the old inline gather+upload (PR 11 residual)
    one_shot: bool = False


@dataclasses.dataclass
class _SlotInfo:
    request: GenRequest
    ngram: Optional["_NgramIndex"] = None
    # draft mode: delivered tokens not yet ingested into the draft cache
    pending_draft: List[int] = dataclasses.field(default_factory=list)
    # Incremental detokenization state: undecoded token ids are buffered
    # until they decode cleanly (no dangling multibyte sequence), then the
    # text accumulates here — the tokenizer only ever decodes the small
    # buffer, keeping streaming O(tokens) instead of O(tokens^2).
    buffer_ids: List[int] = dataclasses.field(default_factory=list)
    text: str = ""            # decoded text (post stop-truncation)
    emitted: int = 0          # chars of ``text`` already streamed
    # JSON mode: incremental end-of-value scanner + chars already scanned
    json_scan: Optional[Any] = None
    json_scanned: int = 0
    # True: the scheduler detokenizes inline (serial mode, or the
    # request's termination depends on decoded text — stop strings /
    # JSON mode). False: buffer_ids/text/emitted are owned by the detok
    # worker after handoff; the scheduler only appends token ids.
    sync_detok: bool = True


class _DetokWorker:
    """Dedicated detokenization + stream-write thread (overlap mode).

    The scheduler hands accepted token ids through a bounded queue and,
    for offloaded requests, never touches the slot's detok state
    (``buffer_ids``/``text``/``emitted``) again — this thread owns the
    tokenizer calls and SSE queue puts, so neither stalls device
    dispatch. Queue items are COALESCED: one ``("batch", [(info,
    toks), ...])`` entry per drained fetch covering every slot that
    produced tokens (was: one entry per slot per fetch — a full batch
    paid ``max_slots`` queue round-trips per step). A ``("finish",
    info)`` item flushes the tail, publishes ``output_text`` and sets
    the request's ``done`` event; the single FIFO queue is the
    ordering contract (the scheduler flushes the pending batch before
    queueing any finish, so all of a request's tokens precede its
    finish). Busy seconds feed the engine's host-overlap accounting
    (the flight recorder's ``host_overlap_ratio``)."""

    _STOP = object()

    def __init__(self, engine: "LLMEngine", maxsize: int = 4096):
        self._engine = engine
        # bounded: a stalled consumer backpressures dispatch instead of
        # pinning unbounded text host-side
        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self._thread: Optional[threading.Thread] = None

    def _ensure_thread(self) -> None:
        # lazy: only engines that actually offload pay for a thread.
        # Scheduler-thread-only callers, so no start race.
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="llm-detok", daemon=True
            )
            self._thread.start()

    def put_batch(
        self, items: List[Tuple["_SlotInfo", List[int]]]
    ) -> None:
        """One coalesced entry for one drained fetch's accepted tokens
        across every offloaded slot."""
        self._ensure_thread()
        self._q.put(("batch", items))

    def finish(self, info: "_SlotInfo") -> None:
        self._ensure_thread()
        self._q.put(("finish", info))

    def stop(self, timeout: float = 10.0) -> None:
        if self._thread is None:
            return
        self._q.put(self._STOP)
        self._thread.join(timeout=timeout)

    def _run(self) -> None:
        eng = self._engine
        while True:
            item = self._q.get()
            if item is self._STOP:
                return
            kind, payload = item
            t0 = time.perf_counter()
            try:
                if kind == "finish":
                    self._finish_one(payload)
                else:
                    for info, toks in payload:
                        self._tokens_one(info, toks)
            finally:
                eng._note_overlap(time.perf_counter() - t0)

    def _tokens_one(self, info: "_SlotInfo", toks: List[int]) -> None:
        try:
            info.buffer_ids.extend(toks)
            self._engine._emit_text(info, final=False)
        except Exception:
            # a tokenizer fault must fail ONE request loudly — never
            # the rest of its batch, nor any waiter queued behind it
            logger.exception("detok worker item failed")
            self._fail_request(info)

    def _finish_one(self, info: "_SlotInfo") -> None:
        try:
            # finish: flush the multibyte tail, publish, wake the
            # waiter (finish_reason was set by the scheduler before
            # the handoff)
            req = info.request
            self._engine._emit_text(info, final=True)
            req.output_text = info.text
            if req.stream is not None:
                req.stream.put(None)
            req.done.set()
        except Exception:
            logger.exception("detok worker finish failed")
            self._fail_request(info)

    @staticmethod
    def _fail_request(info: "_SlotInfo") -> None:
        req = info.request
        if not req.done.is_set():
            req.finish_reason = req.finish_reason or "error"
            # publish whatever text HAD decoded — a fault in the final
            # flush must not turn a finished request into an
            # empty-looking success
            req.output_text = info.text
            if req.stream is not None:
                req.stream.put(None)
            req.done.set()


class _KVStager:
    """Two-slot staging buffer for host→device prefix-KV uploads AND
    wire imports on the kv-copy executor: at most ``depth`` jobs in
    flight, so the next chunk job's prefix copies (or a handed-off
    block run lands) while the current chunk or the running slots'
    decode computes, without unbounded host pinning. Thread-safe:
    the scheduler thread stages prefix uploads while api_server
    executor threads stage KV-transfer imports."""

    def __init__(self, executor, depth: int = 2):
        self._ex = executor
        self._inflight: "collections.deque" = collections.deque()
        self._mu = threading.Lock()
        self.depth = depth

    def submit(self, fn):
        with self._mu:
            while self._inflight and self._inflight[0].done():
                self._inflight.popleft()
            while len(self._inflight) >= self.depth:
                # backpressure: the two-slot bound is the memory
                # contract (held under the lock — the bound is global,
                # not per-submitter)
                concurrent.futures.wait([self._inflight.popleft()])
            try:
                fut = self._ex.submit(fn)
            except RuntimeError:
                # executor shut down (engine stopping / tests draining
                # the copy pool): run inline — a resolved future keeps
                # the caller's contract
                fut = concurrent.futures.Future()
                try:
                    fut.set_result(fn())
                except Exception as e:
                    fut.set_exception(e)
            self._inflight.append(fut)
            return fut


class LLMEngine:
    """Single-replica continuous-batching LLM engine."""

    def __init__(
        self,
        cfg: ModelConfig,
        params: Dict[str, Any],
        *,
        tokenizer=None,
        model_dir: Optional[str] = None,
        max_slots: int = 8,
        max_seq_len: int = 1024,
        plan=None,
        mesh=None,
        seed: int = 0,
        speculative: str = "",       # ""|"ngram"|"draft" (forces greedy)
        spec_tokens: int = 4,        # proposals verified per spec step
        draft_cfg=None,              # draft model config (speculative=draft)
        draft_params=None,
        host_kv_cache_mb: int = 0,   # >0: host-RAM block KV cache
        kv_block_tokens: int = 0,    # block granularity (0 = default 256)
        kv_cache_int8: bool = False,  # int8 host tier (per-block scales)
        prefill_chunk: int = 0,      # >0: chunked prefill (tokens/chunk)
        pipeline_depth: int = _FETCH_LAG,  # 0 = serial reference mode
        kv_role: str = "",           # ""|"prefill"|"decode" (disagg tag)
        kv_spill_mb: int = 0,        # >0: disk spill tier under host RAM
        kv_spill_dir: str = "",      # spill directory ("" = derived tmp)
    ):
        self.cfg = cfg
        self.tokenizer = tokenizer or load_tokenizer(model_dir)
        self.runner = ModelRunner(
            cfg, params, plan=plan, mesh=mesh,
            max_slots=max_slots, max_seq_len=max_seq_len,
        )
        self.max_slots = max_slots
        self.max_seq_len = max_seq_len
        self._state: DecodeState = self.runner.new_state()
        self._slots: Dict[int, _SlotInfo] = {}
        self._free = list(range(max_slots))
        self._waiting: "queue.Queue[GenRequest]" = queue.Queue()
        self._key = jax.random.key(seed)
        self._pending: List[Tuple[Any, Dict[int, int]]] = []
        # Dispatch-ahead pipeline (docs/ENGINE_PIPELINE.md): sampled
        # tokens are fetched this many steps behind dispatch, so the
        # device always has work queued while the host inspects older
        # results. 0 = serial reference mode (fetch + inline detok every
        # step) — greedy-identical to overlapped mode, used for parity.
        # Clamped: depth only buys overlap up to the device queue, and
        # every extra step is wasted compute after a slot finishes.
        self.pipeline_depth = max(0, min(int(pipeline_depth), 16))
        self.overlap = self.pipeline_depth > 0
        self._running = False
        self._fatal = ""            # set when the scheduling loop dies
        self._thread: Optional[threading.Thread] = None
        # idle wakeup: submit() signals under this condition, replacing
        # the old 2 ms poll loop (idle-spin saved is exported via the
        # flight recorder's idle_wait counter)
        self._wake = threading.Condition()
        # detokenization + SSE stream writes off the dispatch path;
        # accepted tokens accumulate here and flush as ONE coalesced
        # queue entry per drained fetch (not one per slot)
        self._detok = _DetokWorker(self)
        self._detok_batch: List[Tuple[_SlotInfo, List[int]]] = []
        # host work overlapped with device compute (detok worker + kv
        # staging/copy executor busy seconds), drained per step into the
        # flight record's host_overlap field
        self._overlap_mu = threading.Lock()
        self._overlap_s = 0.0
        self._overlap_seen = 0.0
        self._id_counter = itertools.count()
        self._step_count = 0
        self._tokens_generated = 0
        # Flight recorder: one record per scheduler step, always on
        # (observability/flight.py — the self-measured overhead ratio
        # is exported and tier-1 asserts it stays <1% of step time).
        self.flight = _flight.FlightRecorder(max_slots)
        # per-step accumulators reset at the top of step(); written only
        # by the scheduler thread
        self._step_mode = ""
        self._step_real = 0          # tokens genuinely dispatched
        self._step_padded = 0        # tokens the padded dispatch computed
        self._step_out = 0           # tokens delivered to requests
        self._step_prompt = 0        # prompt tokens entering prefill
        self._step_spec_proposed = 0
        self._step_spec_accepted = 0
        # on-demand profiler capture (capture_profile): the scheduler
        # thread starts/stops the jax.profiler trace around N busy steps
        self._profile_mu = threading.Lock()
        self._profile: Optional[Dict[str, Any]] = None
        self.ttft_hist = LatencyHistogram(TTFT_BUCKETS_S)
        self.tpot_hist = LatencyHistogram(TPOT_BUCKETS_S)
        self.e2e_hist = LatencyHistogram(E2E_BUCKETS_S)
        # Chunked prefill (vLLM's enable-chunked-prefill role): prompts
        # longer than the chunk are prefilled chunk-by-chunk with a
        # decode step interleaved between chunks, so one long prompt
        # can't stall token cadence for every running slot. Chunks ride
        # the prefix-continuation jit path (prefill_with_prefix), so
        # each chunk's cost is one bucketed forward, never O(S^2) over
        # the whole prompt at once.
        self.prefill_chunk = 0
        if prefill_chunk > 0:
            # snap to a real bucket so chunk steps hit stable jit keys
            # (rounding UP — the effective chunk may exceed the request);
            # clamp to the top bucket: a chunk >= every possible prompt
            # makes chunking a no-op instead of a startup crash
            top = self.runner.prefill_buckets[-1]
            self.prefill_chunk = self.runner.bucket_for(
                min(prefill_chunk, top)
            )
        self._chunk_jobs: Dict[int, _ChunkJob] = {}
        self.speculative = speculative
        self.spec_tokens = max(2, spec_tokens)
        self._spec_hits = 0
        self._spec_steps = 0
        self._spec_proposed = 0   # slots x (spec_tokens-1) across steps
        # Draft-model speculation (EAGLE-class role; reference surfaces
        # EAGLE3/MTP/ngram as vLLM args, worker/backends/vllm.py:531): a
        # small proposer model runs its own slot-aligned DecodeState;
        # delivered tokens are block-ingested into its cache (catch-up),
        # it proposes spec_tokens-1 greedy continuations, and the target
        # verifies — output is bit-identical to plain greedy decode.
        self.host_kv_cache = None
        self._kv_copy_pool = None
        self._kv_stage = None
        self.kv_conv = None
        # disaggregated-serving role tag (ModelSpec prefill_replicas /
        # decode_replicas → backends --kv-role): advisory — the engine
        # serves whatever arrives; the proxy's routing and the KV
        # handoff surface (api_server /kv/export, /kv/import) are what
        # make the roles mean something
        self.kv_role = kv_role
        # KV-transfer accounting (engine/kv_transfer.py): handoff
        # bytes/blocks/failures/latency, rendered by the engine exporter
        from gpustack_tpu.engine.kv_transfer import HandoffStats

        self.kv_handoff = HandoffStats()
        if host_kv_cache_mb > 0:
            from gpustack_tpu.engine.kv_host_cache import (
                DEFAULT_BLOCK_TOKENS,
                HostKVCache,
            )

            self.host_kv_cache = HostKVCache(
                host_kv_cache_mb * 2**20,
                # <= 0 (unset, or a bad spec value — ModelSpec has no
                # range validation) falls back to the default instead
                # of crash-looping the engine process at startup
                block_tokens=(
                    kv_block_tokens if kv_block_tokens > 0
                    else DEFAULT_BLOCK_TOKENS
                ),
                int8=kv_cache_int8,
            )
            if kv_spill_mb > 0:
                from gpustack_tpu.engine.kv_spill import DiskKVSpill

                spill_dir = kv_spill_dir or os.path.join(
                    tempfile.gettempdir(),
                    f"gpustack-kv-spill-{os.getpid()}",
                )
                self.host_kv_cache.spill = DiskKVSpill(
                    spill_dir, kv_spill_mb * 2**20
                )
            # conversation index feeding the cluster KV directory:
            # the API layer records (message-chain hashes, token ids)
            # at chat finish; /kv/summary snapshots block residency
            from gpustack_tpu.engine.kv_fabric import ConvIndex

            self.kv_conv = ConvIndex()
            # device→host KV copies run off-thread: a synchronous PCIe
            # pull of a whole bucket's KV would stall the scheduler
            # thread (and every decoding slot) on each prefill miss
            self._kv_copy_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="kv-copy"
            )
            # double-buffered host→device prefix uploads ride the same
            # executor behind a two-slot stager (chunked prefill seeds)
            self._kv_stage = _KVStager(self._kv_copy_pool)
        self.draft_runner = None
        self._draft_state = None
        if speculative == "draft":
            if draft_cfg is None or draft_params is None:
                raise ValueError(
                    "speculative='draft' needs draft_cfg/draft_params"
                )
            self.draft_runner = ModelRunner(
                draft_cfg, draft_params,
                max_slots=max_slots, max_seq_len=max_seq_len,
            )
            self._draft_state = self.draft_runner.new_state()

    # ---- public API -----------------------------------------------------

    def submit(self, req: GenRequest) -> GenRequest:
        if self._fatal:
            raise ValueError(f"engine is down: {self._fatal}")
        if not req.request_id:
            req.request_id = f"req-{next(self._id_counter)}"
        req.submitted_at = time.time()
        if self.speculative:
            # Speculative verification is greedy and produces no sampled
            # distribution — REJECT incompatible requests instead of
            # silently changing their sampling semantics (round-3 trap:
            # temperature was zeroed with no signal to the API user).
            if req.temperature > 0:
                raise ValueError(
                    "this deployment runs speculative decoding, which is "
                    "greedy-only; set temperature=0 (or deploy without "
                    "--speculative) to use sampling"
                )
            if req.logprobs:
                raise ValueError(
                    "logprobs are unavailable under speculative decoding "
                    "(verification produces no per-token distribution)"
                )
            if req.embeds_override is not None:
                raise ValueError(
                    "image inputs are unavailable under speculative "
                    "decoding (the draft model has no vision tower)"
                )
            if req.logit_bias:
                raise ValueError(
                    "logit_bias is unavailable under speculative "
                    "decoding (verification argmaxes raw logits; the "
                    "bias would silently stop applying after the "
                    "first token)"
                )
        if req.logit_bias:
            from gpustack_tpu.engine.sampling import MAX_BIAS

            if len(req.logit_bias) > MAX_BIAS:
                raise ValueError(
                    f"logit_bias supports at most {MAX_BIAS} entries "
                    f"(got {len(req.logit_bias)})"
                )
            bad = [
                t for t in req.logit_bias
                if not 0 <= int(t) < self.cfg.vocab_size
            ]
            if bad:
                raise ValueError(
                    f"logit_bias token ids out of range: {bad[:5]}"
                )
        if len(req.prompt_ids) >= self.max_seq_len:
            raise ValueError(
                f"prompt of {len(req.prompt_ids)} tokens >= max_seq_len "
                f"{self.max_seq_len}"
            )
        # enqueue + notify under one lock so a submit can never slip
        # between the scheduler's emptiness check and its cv wait (the
        # classic lost wakeup)
        with self._wake:
            self._waiting.put(req)
            self._wake.notify_all()
        return req

    def generate(self, req: GenRequest, timeout: float = 300.0) -> GenRequest:
        """Blocking helper: submit and wait for completion."""
        self.submit(req)
        if not req.done.wait(timeout):
            raise TimeoutError(f"request {req.request_id} timed out")
        return req

    def start(self) -> None:
        self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="llm-engine", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        with self._wake:
            self._wake.notify_all()
        if self._thread:
            self._thread.join(timeout=30)
        # drain the detok queue so every finished request's text/done
        # landed before the engine object is abandoned
        self._detok.stop()

    def embed(self, batch_prompt_ids: List[List[int]]) -> List[List[float]]:
        """Mean-pooled, l2-normalized embeddings — one batched forward for
        the whole request. Runs directly on the runner (jax dispatch is
        thread-safe); sequence and batch dims are bucketed so jit
        specializations stay bounded."""
        for ids in batch_prompt_ids:
            if len(ids) >= self.max_seq_len:
                raise ValueError(
                    f"input of {len(ids)} tokens >= max_seq_len "
                    f"{self.max_seq_len}"
                )
        bucket = self.runner.bucket_for(
            max(1, max(len(i) for i in batch_prompt_ids))
        )
        padded = [
            list(ids) + [0] * (bucket - len(ids))
            for ids in batch_prompt_ids
        ]
        lens = [len(ids) for ids in batch_prompt_ids]
        vecs = self.runner.embed(padded, lens)
        import numpy as _np

        return _np.asarray(vecs).tolist()

    def health(self) -> Dict[str, Any]:
        return {
            "status": "error" if self._fatal else "ok",
            "error": self._fatal,
            "model": self.cfg.name,
            "slots_total": self.max_slots,
            # racy-tolerated gauge: HTTP thread reads the scheduler's
            # slot list length; worst case one admit stale
            "slots_used": self.max_slots - len(self._free),  # analysis: ignore[guarded-by]
            "waiting": self._waiting.qsize(),
            "steps": self._step_count,
            "tokens_generated": self._tokens_generated,
            "prompt_tokens": self.flight.prompt_tokens_total,
            "flight_overhead_ratio": round(
                self.flight.overhead_ratio(), 6
            ),
            # overlapped pipeline (docs/ENGINE_PIPELINE.md)
            "pipeline_depth": self.pipeline_depth,
            "overlap": self.overlap,
            "host_overlap_ratio": round(
                self.flight.host_overlap_ratio(), 6
            ),
            "pipeline_rollback_tokens": (
                self.flight.rollback_tokens_total
            ),
            "idle_wait_s": round(self.flight.idle_wait_s_total, 3),
            # the replica's multi-chip layout as one inspectable object
            # (parallel/sharding.SpecLayout)
            "layout": self.runner.layout.describe(),
            "speculative": self.speculative,
            "spec_steps": self._spec_steps,
            "spec_extra_tokens": self._spec_hits,
            # accepted proposals / proposals made (1.0 = every proposal
            # of every slot accepted)
            "spec_acceptance_rate": round(
                self._spec_hits / max(1, self._spec_proposed), 4
            ),
            "draft_model": (
                self.draft_runner.cfg.name if self.draft_runner else ""
            ),
            "kv_cache_hits": (
                self.host_kv_cache.hits if self.host_kv_cache else 0
            ),
            "kv_cache_misses": (
                self.host_kv_cache.misses if self.host_kv_cache else 0
            ),
            "kv_cache_prefix_hits": (
                self.host_kv_cache.prefix_hits
                if self.host_kv_cache else 0
            ),
            "kv_cache_prefix_tokens_reused": (
                self.host_kv_cache.prefix_tokens_reused
                if self.host_kv_cache else 0
            ),
            "kv_cache_blocks": (
                self.host_kv_cache.entries if self.host_kv_cache else 0
            ),
            "kv_cache_host_bytes": (
                self.host_kv_cache.bytes_used if self.host_kv_cache else 0
            ),
            # disaggregated serving (docs/KV_CACHE.md "KV handoff"):
            # role tag + wire-transfer accounting
            "kv_role": self.kv_role,
            "kv_handoff": self.kv_handoff.snapshot(),
            # fleet KV fabric (docs/KV_CACHE.md "Fleet KV fabric"):
            # disk spill tier counters + fault-backs + the bounded
            # conversation index feeding the cluster directory
            "kv_spill": (
                self.host_kv_cache.spill.snapshot()
                if self.host_kv_cache and self.host_kv_cache.spill
                else {}
            ),
            "kv_faultbacks": (
                self.host_kv_cache.faultbacks
                if self.host_kv_cache else 0
            ),
            "kv_conversations": (
                len(self.kv_conv) if self.kv_conv else 0
            ),
        }

    # ---- scheduling loop ------------------------------------------------

    def _loop(self) -> None:
        while self._running:
            try:
                busy = self.step()
            except Exception as e:
                # A dead scheduling thread must be LOUD and terminal, not
                # a silent hang: fail every in-flight and queued request
                # and flip health so the serve manager's probe tears the
                # instance down (e.g. a multi-host follower that never
                # connected — engine/multihost.py raises after its
                # connect window).
                logger.exception("engine scheduling loop died")
                self._fatal = f"engine loop died: {e}"
                self._fail_all_requests(str(e))
                return
            if not busy:
                # Idle: park on the wakeup condition instead of the old
                # 2 ms poll. submit() notifies under the same lock; the
                # bounded timeout is a backstop for wake sources that
                # don't notify (aborts on queued requests). Waited
                # seconds are exported as the spin this saves.
                with self._wake:
                    if self._running and self._waiting.empty():
                        t0 = time.perf_counter()
                        self._wake.wait(timeout=0.05)
                        self.flight.note_idle_wait(
                            time.perf_counter() - t0
                        )

    def _notify_wake(self) -> None:
        with self._wake:
            self._wake.notify_all()

    def _note_overlap(self, seconds: float) -> None:
        """Worker threads report host work done concurrently with the
        scheduler here; _flight_record drains the delta per step."""
        with self._overlap_mu:
            self._overlap_s += seconds

    def _flush_detok(self) -> None:
        """Hand the accumulated (info, tokens) pairs to the detok
        worker as ONE queue entry — called once per drained fetch (and
        before any finish item, so the FIFO ordering contract holds)."""
        if self._detok_batch:
            batch, self._detok_batch = self._detok_batch, []
            self._detok.put_batch(batch)

    def _fail_all_requests(self, message: str) -> None:
        self._flush_detok()
        for info in list(self._slots.values()):
            req = info.request
            req.finish_reason = "error"
            if info.sync_detok:
                req.output_text = info.text
                if req.stream is not None:
                    req.stream.put(None)
                req.done.set()
            else:
                # the detok worker owns this request's text/stream/done;
                # queue ordering delivers any buffered tokens first
                self._detok.finish(info)
        self._slots.clear()
        # mid-chunked-prefill requests live in _chunk_jobs, not _slots —
        # they must fail just as loudly (their clients are blocked on
        # done too)
        for job in self._chunk_jobs.values():
            req = job.req
            req.finish_reason = "error"
            if req.stream is not None:
                req.stream.put(None)
            req.done.set()
        self._chunk_jobs.clear()
        while not self._waiting.empty():
            try:
                req = self._waiting.get_nowait()
            except queue.Empty:
                break
            req.finish_reason = "error"
            if req.stream is not None:
                req.stream.put(None)
            req.done.set()

    def step(self) -> bool:
        """One scheduling iteration. Returns False when fully idle."""
        t0 = time.perf_counter()
        self._step_mode = ""
        self._step_real = self._step_padded = 0
        self._step_out = self._step_prompt = 0
        self._step_spec_proposed = self._step_spec_accepted = 0
        # Eager-ready drain BEFORE admission: fetch whatever the device
        # already finished (non-blocking readiness probe), so a slot
        # whose request ended re-tenants THIS step instead of
        # pipeline_depth steps later. The depth is a cap on in-flight
        # work (the only place the host may block), never a mandatory
        # delay — on a fast link results drain one step after dispatch,
        # on a slow link up to `depth` dispatches proceed unfetched.
        self._drain_ready()
        admitted = self._admit()
        # at most one prefill chunk per step: decode cadence for running
        # slots is bounded by one chunk's latency, not a whole prompt's
        progressed = self._advance_chunk()
        if self._slots:
            self._decode_once()
            self._flight_record(t0)
            return True
        if admitted or progressed or self._chunk_jobs:
            self._flight_record(t0)
            return True
        # Nothing active: drain any lagging fetches so finished requests
        # complete deterministically.
        self._drain_pending()
        if self._step_out or self._step_spec_accepted:
            # tokens delivered by the drain would otherwise vanish when
            # the next step resets the accumulators — record them so
            # flight tokens_out/spec_accepted match tokens_generated
            self._flight_record(t0)
        return not self._waiting.empty()

    def _flight_record(self, t0: float) -> None:
        """Seal this step's flight record (and advance an in-flight
        profiler capture). Scheduler-thread only."""
        dur_s = time.perf_counter() - t0
        oldest = 0.0
        try:
            # peeking the queue head without its mutex is safe here:
            # worst case a racing admit swaps the head and the gauge is
            # one submit stale — observability, not control flow
            oldest = time.time() - self._waiting.queue[0].submitted_at
        except (IndexError, AttributeError):
            pass
        kv = self.host_kv_cache
        with self._overlap_mu:
            overlap_total = self._overlap_s
        overlap_delta = overlap_total - self._overlap_seen
        self._overlap_seen = overlap_total
        self.flight.record(
            dur_s=dur_s,
            host_overlap_s=max(0.0, overlap_delta),
            mode=self._step_mode or "decode",
            slots_used=self.max_slots - len(self._free),
            waiting=self._waiting.qsize(),
            oldest_wait_s=max(0.0, oldest),
            tokens_real=self._step_real,
            tokens_padded=self._step_padded,
            tokens_out=self._step_out,
            prompt_tokens=self._step_prompt,
            spec_proposed=self._step_spec_proposed,
            spec_accepted=self._step_spec_accepted,
            kv_blocks=kv.entries if kv is not None else 0,
            kv_reused_total=(
                kv.prefix_tokens_reused if kv is not None else 0
            ),
        )
        # unlocked fast-path probe: None is the steady state, and a
        # stale non-None just pays one _profile_step() lock round-trip
        if self._profile is not None:  # analysis: ignore[guarded-by]
            self._profile_step()

    # ---- on-demand profiler capture -----------------------------------

    def capture_profile(
        self, steps: int, out_dir: str = "", timeout_s: float = 30.0
    ) -> Dict[str, Any]:
        """Wrap the next ``steps`` busy scheduler steps in a
        ``jax.profiler`` trace (hasattr-guarded: jax builds in this
        container drift across 0.4.x — when the profiler API is
        missing, or ``out_dir`` is empty, the capture degrades to
        flight-records-only) and return the captured step summary.

        Blocks up to ``timeout_s`` for the steps to elapse; an idle
        engine returns whatever was captured by the deadline. One
        capture at a time — a concurrent request gets a ValueError
        (profiler state is process-global)."""
        cap: Dict[str, Any] = {
            "remaining": max(1, min(int(steps), 10_000)),
            "requested": max(1, min(int(steps), 10_000)),
            "records": [],
            "out_dir": out_dir,
            "profiler": "flight-only",
            "started": False,
            "error": "",
            "done": threading.Event(),
        }
        with self._profile_mu:
            if self._profile is not None:
                raise ValueError(
                    "a profile capture is already in progress"
                )
            self._profile = cap
        cap["done"].wait(timeout_s)
        with self._profile_mu:
            if self._profile is cap:
                self._profile = None
            if cap["started"]:
                # idle-timeout path: the scheduler never reached zero
                # remaining, so the trace is still open — close it here
                # (stop mid-step only truncates collection)
                self._profiler_stop(cap)
        records = list(cap["records"])
        return {
            "requested": cap["requested"],
            "steps_captured": len(records),
            "profiler": cap["profiler"],
            "artifact": out_dir if cap["profiler"] == "jax" else "",
            "error": cap["error"],
            "records": records,
            "aggregate": _flight.aggregate_records(
                records, self.max_slots,
                overhead_ratio=self.flight.overhead_ratio(),
            ) if records else {},
        }

    def _profile_step(self) -> None:
        """Advance the active capture by one recorded step (scheduler
        thread; the lock only guards handoff with the capture thread's
        timeout finalizer, never device work)."""
        with self._profile_mu:
            cap = self._profile
            if cap is None or cap["remaining"] <= 0:
                return
            if not cap["started"]:
                cap["started"] = True
                if cap["out_dir"] and self._profiler_start(cap):
                    cap["profiler"] = "jax"
            snap = self.flight.snapshot(limit=1)
            if snap:
                cap["records"].append(snap[-1])
            cap["remaining"] -= 1
            if cap["remaining"] <= 0:
                self._profiler_stop(cap)
                self._profile = None
                cap["done"].set()

    @staticmethod
    def _profiler_start(cap: Dict[str, Any]) -> bool:
        prof = getattr(jax, "profiler", None)
        start = getattr(prof, "start_trace", None)
        if start is None or not hasattr(prof, "stop_trace"):
            cap["error"] = "jax.profiler.start_trace unavailable"
            return False
        try:
            start(cap["out_dir"])
            return True
        except Exception as e:  # profiler must never kill the loop
            cap["error"] = f"start_trace failed: {e}"
            return False

    @staticmethod
    def _profiler_stop(cap: Dict[str, Any]) -> None:
        if cap.get("profiler") != "jax" or cap.get("_stopped"):
            return
        cap["_stopped"] = True
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            cap["error"] = f"stop_trace failed: {e}"
            cap["profiler"] = "flight-only"

    def _plan_chunk_job(
        self, req: GenRequest, ids, matched: int = 0
    ) -> "Optional[_ChunkJob]":
        """Chunk schedule for a long prompt, seeded from the host KV
        cache's matched block run (``matched``, probed once by the
        caller) when one fits. Returns None when any continuation would
        overflow the top bucket (possible with non-power-of-two
        max_seq_len shapes) — the caller then falls back to one-shot
        prefill, which always fits."""
        top = self.runner.prefill_buckets[-1]

        def fits(start: int) -> bool:
            # every continuation writes its suffix block at
            # [start, start + sb); dynamic_update_slice CLAMPS
            # out-of-range starts, so overflow = silent corruption —
            # same bounds contract as the one-shot prefix path
            while start < len(ids):
                n = min(self.prefill_chunk, len(ids) - start)
                sb = self.runner.bucket_for(n)
                if start and start + sb > top:
                    return False
                start += n
            return True

        kv_cache = self.host_kv_cache
        if kv_cache is not None and matched > 0:
            # block granularity means the bounds guard can trim the
            # matched run block-by-block instead of rejecting it
            # outright — a partially usable prefix still saves its
            # blocks' prefill FLOPs. Trim BEFORE gathering so no KV
            # bytes are assembled for blocks the guard discards.
            plen = matched
            while plen > 0 and not fits(plen):
                plen -= kv_cache.block_tokens
            if plen > 0 and self._kv_stage is not None and fits(0):
                # double-buffered staging: the gather (host memcpy) and
                # upload (host→device) run on the kv-copy executor while
                # this and later steps decode the running slots; the
                # chunk job rendezvouses when it is actually reached.
                # fits(0) guards the eviction fallback: a run that
                # vanishes between match and gather cold-starts the job.
                fut = self._kv_stage.submit(
                    self._stage_prefix_fn(req, ids, plen, kv_cache)
                )
                return _ChunkJob(req=req, ids=list(ids), pending_kv=fut)
            got = (
                self._gather_and_upload(req, ids, plen, kv_cache)
                if plen > 0 else None
            )
            if got is not None:
                k, v, _ = got
                return _ChunkJob(
                    req=req, ids=list(ids), done=plen, k=k, v=v,
                )
        if fits(0):
            return _ChunkJob(req=req, ids=list(ids))
        return None

    def _gather_and_upload(self, req, ids, plen: int, kv_cache):
        """Gather a matched block run from host RAM and upload it at
        bucket width. Returns ``(k, v, plen)``, or None when the run
        evicted between match and gather. Hit counters and the request's
        attribution are recorded here, success-only — the ONE
        implementation behind both the staged (executor) and cold
        (inline fallback) prefix paths, so their accounting can't
        drift."""
        got = kv_cache.gather_prefix(list(ids), plen)
        if got is None:
            return None
        pk, pv = got
        kv_cache.prefix_hits += 1
        kv_cache.prefix_tokens_reused += plen
        req.prefix_tokens_reused = plen
        t0 = time.time()
        k, v = self._upload_prefix(pk, pv, plen)
        req.kv_upload_s = time.time() - t0
        return k, v, plen

    def _stage_prefix_fn(self, req, ids, plen: int, kv_cache):
        """Build the kv-copy-executor job for a chunked prefix seed
        (``_upload_prefix`` blocks off-thread — that wait IS the
        overlap being bought)."""
        ids_t = tuple(ids)

        def stage():
            t0 = time.perf_counter()
            try:
                return self._gather_and_upload(
                    req, list(ids_t), plen, kv_cache
                )
            finally:
                self._note_overlap(time.perf_counter() - t0)
                self._notify_wake()
        return stage

    def _resolve_staged_prefix(self, job: "_ChunkJob") -> None:
        """Rendezvous with a staged gather+upload — the designated wait
        point (may block when the job is reached before the upload
        lands, i.e. when there was no decode work to overlap with). A
        failed or evicted stage cold-starts the job."""
        fut, job.pending_kv = job.pending_kv, None
        try:
            got = fut.result()
        except Exception as e:
            logger.warning(
                "prefix staging failed; cold chunked prefill: %s", e
            )
            got = None
        if got is not None:
            job.k, job.v, job.done = got

    def _upload_prefix(self, pk, pv, use_len: int):
        """Upload a matched prefix run padded to its BUCKET width, not
        its exact block-multiple length: prefill_with_prefix jit-keys on
        (Pb, Tsb, total_bucket), so exact widths would compile one fresh
        executable per distinct matched length — bucket padding keeps the
        key set as bounded as v1's bucket-stored arrays. Pad rows sit at
        positions >= use_len: overwritten by the suffix's own writes or
        invisible through the causal mask (the prefix-prefill invariant).
        Blocks until resident so the caller's kv_upload timing is
        honest (prefill would stall on the transfer anyway)."""
        import jax.numpy as jnp

        pw = self.runner.bucket_for(use_len)
        if pk.shape[1] >= pw:
            k_host, v_host = pk[:, :pw], pv[:, :pw]
        else:
            pad = ((0, 0), (0, pw - pk.shape[1]), (0, 0), (0, 0))
            k_host = np.pad(pk, pad)
            v_host = np.pad(pv, pad)
        k = jnp.asarray(k_host)
        v = jnp.asarray(v_host)
        jax.block_until_ready((k, v))
        return k, v

    def _advance_chunk(self) -> bool:
        """Run ONE chunk of the oldest runnable in-progress chunked
        prefill. A job whose staged prefix upload is still in flight is
        passed over while any decode work exists — that concurrency is
        the double-buffer win; with nothing else to run, the oldest
        upload is awaited instead."""
        if not self._chunk_jobs:
            return False
        slot = job = None
        for s, j in self._chunk_jobs.items():
            if j.pending_kv is None or j.pending_kv.done():
                slot, job = s, j
                break
        if job is None:
            if self._slots:
                return False   # decode while the upload lands
            slot = next(iter(self._chunk_jobs))
            job = self._chunk_jobs[slot]
        if job.req.aborted.is_set():
            # abandon the remaining chunks; the slot never activated
            del self._chunk_jobs[slot]
            self._free.append(slot)
            abort_op = getattr(self.runner, "chunk_abort", None)
            if abort_op is not None and job.done > 0 and not job.one_shot:
                # multi-host: followers drop their chunk register too,
                # or the aborted prompt's partial K/V stays pinned in
                # device memory until the next chunked job (one-shot
                # jobs never touched a chunk register)
                abort_op()
            self._finish_aborted(job.req)
            return True
        if job.pending_kv is not None:
            self._resolve_staged_prefix(job)
        if job.one_shot:
            self._advance_one_shot(slot, job)
            return True
        start = job.done
        chunk = job.ids[start : start + self.prefill_chunk]
        self._step_mode = self._step_mode or "prefill_chunk"
        self._step_real += len(chunk)
        self._step_prompt += len(chunk)
        self._step_padded += self.runner.bucket_for(len(chunk))
        # chunk-specific runner entry points exist on the multi-host
        # BroadcastingRunner (separate follower register + no device
        # arrays on the wire); the single-host runner serves both roles
        # with its plain methods
        r = self.runner
        if start == 0:
            b = r.bucket_for(len(chunk))
            padded = list(chunk) + [0] * (b - len(chunk))
            fn = getattr(r, "prefill_chunk", None) or r.prefill
            job.last, job.k, job.v = fn(padded, len(chunk))
        else:
            sb = r.bucket_for(len(chunk))
            total_bucket = r.bucket_for(start + sb)
            padded = list(chunk) + [0] * (sb - len(chunk))
            fn = (
                getattr(r, "prefill_continue_chunk", None)
                or r.prefill_with_prefix
            )
            job.last, job.k, job.v = fn(
                job.k, job.v, start, padded, len(chunk), total_bucket
            )
        job.done += len(chunk)
        if job.done >= len(job.ids):
            del self._chunk_jobs[slot]
            ids = job.ids
            # block insert trims to full blocks <= len(ids); the copy
            # worker trims the (continuation-padded) arrays to match
            self._submit_kv_copy(ids, job.k, job.v, len(ids))
            commit = getattr(self.runner, "chunk_commit", None)
            if commit is not None:
                # multi-host: followers promote their chunk register so
                # the sample_first/insert pair replays the right arrays
                commit()
            self._finalize_start(slot, job.req, job.last, job.k, job.v)
        return True

    def _advance_one_shot(self, slot: int, job: "_ChunkJob") -> None:
        """Complete a deferred one-shot prefill: the staged prefix (if
        it landed — an evicted or failed stage leaves ``done == 0`` and
        the job cold-starts) plus ONE bucketed forward over the entire
        suffix, then slot activation. Greedy-identical to the old
        inline path; only the scheduler-blocking gather+upload moved
        onto the stager."""
        req, ids = job.req, job.ids
        r = self.runner
        self._step_mode = self._step_mode or "prefill"
        if job.done > 0:
            suffix = ids[job.done:]
            sb = r.bucket_for(len(suffix))
            total_bucket = r.bucket_for(job.done + sb)
            self._step_real += len(suffix)
            self._step_prompt += len(suffix)
            self._step_padded += sb
            padded = list(suffix) + [0] * (sb - len(suffix))
            last_logits, k, v = r.prefill_with_prefix(
                job.k, job.v, job.done, padded, len(suffix),
                total_bucket,
            )
        else:
            bucket = r.bucket_for(max(1, len(ids)))
            self._step_real += len(ids)
            self._step_prompt += len(ids)
            self._step_padded += bucket
            padded = list(ids) + [0] * (bucket - len(ids))
            last_logits, k, v = r.prefill(padded, len(ids))
        del self._chunk_jobs[slot]
        self._submit_kv_copy(ids, k, v, len(ids))
        self._finalize_start(slot, req, last_logits, k, v)

    # admit as many waiting requests as there are free slots
    def _admit(self) -> bool:
        admitted = False
        while self._free and not self._waiting.empty():
            try:
                req = self._waiting.get_nowait()
            except queue.Empty:
                break
            if req.aborted.is_set():
                # client gone while queued: never spend a prefill on it
                self._finish_aborted(req)
                continue
            slot = self._free.pop(0)
            self._start_request(slot, req)
            admitted = True
        return admitted

    def _finish_aborted(self, req: GenRequest) -> None:
        """Terminal bookkeeping for a request aborted before it owned a
        slot (queued, or mid-chunked-prefill)."""
        req.finish_reason = "abort"
        req.finished_at = time.time()
        if req.stream is not None:
            req.stream.put(None)
        req.done.set()

    def _start_request(self, slot: int, req: GenRequest) -> None:
        ids = req.prompt_ids
        bucket = self.runner.bucket_for(max(1, len(ids)))
        padded = list(ids) + [0] * (bucket - len(ids))
        if req.embeds_override is not None:
            # VLM prompt: placeholder ids alias across different images,
            # so the token-keyed host KV cache and chunked prefill don't
            # apply — one fused prefill with the embedding override
            self._step_mode = self._step_mode or "prefill"
            self._step_real += len(ids)
            self._step_prompt += len(ids)
            self._step_padded += bucket
            embeds, mask = self._padded_embeds(req, bucket, len(ids))
            last_logits, k, v = self.runner.prefill_with_embeds(
                padded, len(ids), embeds, mask
            )
            self._finalize_start(slot, req, last_logits, k, v)
            return
        # ONE prefix probe per request (counts one hit or miss), shared
        # by the chunked and one-shot paths. Local read: the copy worker
        # may null host_kv_cache concurrently.
        kv_cache = self.host_kv_cache
        matched = (
            kv_cache.match_prefix_len(ids) if kv_cache is not None else 0
        )
        if (
            self.prefill_chunk
            and len(ids) > self.prefill_chunk
            and (job := self._plan_chunk_job(req, ids, matched)) is not None
        ):
            # long prompt: prefill in chunks, one per scheduler step
            # (the step loop interleaves decode between chunks; the job
            # planner seeds from the host cache's matched block run)
            self._step_mode = self._step_mode or "prefill_chunk"
            self._chunk_jobs[slot] = job
            return
        use_len = matched
        if use_len > 0:
            top = self.runner.prefill_buckets[-1]
            # cache bounds contract: the suffix BLOCK (bucketed) must
            # fit above the prefix within a REAL bucket —
            # dynamic_update_slice clamps out-of-range writes and would
            # silently corrupt the tail. Block granularity lets the
            # guard trim the matched run one block at a time instead of
            # rejecting the whole match; trimming happens BEFORE any KV
            # bytes are assembled.
            while use_len > 0:
                sb = self.runner.bucket_for(len(ids) - use_len)
                if use_len + sb <= top:
                    break
                use_len -= kv_cache.block_tokens
        if use_len > 0 and self._kv_stage is not None:
            # Deferred one-shot prefill: the gather+upload used to run
            # INLINE here, blocking the scheduler (and every decoding
            # slot) on the host→device copy. It now rides the same
            # two-slot stager as the chunked path — the slot holds a
            # one-shot job whose single "chunk" is the entire suffix,
            # and decode proceeds while the upload lands.
            fut = self._kv_stage.submit(
                self._stage_prefix_fn(req, ids, use_len, kv_cache)
            )
            self._chunk_jobs[slot] = _ChunkJob(
                req=req, ids=list(ids), pending_kv=fut, one_shot=True,
            )
            return
        prefix = (
            kv_cache.gather_prefix(ids, use_len) if use_len > 0 else None
        )
        if prefix is not None:
            pk, pv = prefix
            # prefix reuse: upload the cached block run, prefill only
            # the suffix from that offset. Counted here, not in the
            # lookup — a match the bounds guard rejected (or that
            # evicted before the gather) saved nothing.
            kv_cache.prefix_hits += 1
            kv_cache.prefix_tokens_reused += use_len
            req.prefix_tokens_reused = use_len
            suffix = ids[use_len:]
            sb = self.runner.bucket_for(len(suffix))
            total_bucket = self.runner.bucket_for(use_len + sb)
            self._step_mode = self._step_mode or "prefill"
            self._step_real += len(suffix)
            self._step_prompt += len(suffix)
            self._step_padded += sb
            t0 = time.time()
            pk_dev, pv_dev = self._upload_prefix(pk, pv, use_len)
            req.kv_upload_s = time.time() - t0
            suffix_padded = list(suffix) + [0] * (sb - len(suffix))
            last_logits, k, v = self.runner.prefill_with_prefix(
                pk_dev, pv_dev, use_len, suffix_padded, len(suffix),
                total_bucket,
            )
        else:
            self._step_mode = self._step_mode or "prefill"
            self._step_real += len(ids)
            self._step_prompt += len(ids)
            self._step_padded += bucket
            last_logits, k, v = self.runner.prefill(padded, len(ids))
        if kv_cache is not None:
            self._submit_kv_copy(ids, k, v, len(ids))
        self._finalize_start(slot, req, last_logits, k, v)

    @staticmethod
    def _padded_embeds(req: GenRequest, bucket: int, n_ids: int):
        """Bucket-pad a VLM request's override embeddings (host-side np
        prep — kept out of the declared dispatch functions)."""
        embeds, mask = req.embeds_override
        pad_rows = bucket - n_ids
        embeds = np.pad(
            np.asarray(embeds, np.float32), ((0, pad_rows), (0, 0))
        )
        mask = np.pad(np.asarray(mask, bool), (0, pad_rows))
        return embeds, mask

    def _submit_kv_copy(self, seq, k_dev, v_dev, total: int) -> None:
        """Queue an async device→host copy + block insert of ``seq``'s
        KV. The device arrays may be wider than ``total`` (bucket or
        prefix-continuation padding); they are trimmed host-side in the
        copy worker. Shared by the prefill-time and finish-time stores
        so the disable-on-error path exists exactly once."""
        kv_cache = self.host_kv_cache
        if kv_cache is None or self._kv_copy_pool is None:
            return

        def copy_to_host(
            seq=tuple(seq), k_=k_dev, v_=v_dev,
            kv_cache=kv_cache, total=total,
        ):
            try:
                kv_cache.insert_sequence(
                    seq,
                    np.asarray(k_)[:, :total],
                    np.asarray(v_)[:, :total],
                )
            except RuntimeError as e:
                # non-addressable shards (defensive: backends gates
                # multi-host off already)
                logger.warning("disabling host KV cache: %s", e)
                self.host_kv_cache = None

        try:
            self._kv_copy_pool.submit(copy_to_host)
        except RuntimeError:
            # pool shut down (engine stopping) — skip the store; the
            # cache is an optimization, never required for correctness
            pass

    def kv_import_prepared(self, tokens, prepared):
        """Land a handed-off block run (already wire-decoded and
        converted to the cache's tier) through the ``_KVStager`` so the
        scheduler — and therefore every decoding slot — never stalls on
        the transfer. Returns a ``concurrent.futures.Future`` resolving
        to the number of blocks attached (0 when the cache is off)."""
        kv_cache = self.host_kv_cache

        def land():
            if kv_cache is None:
                return 0
            t0 = time.perf_counter()
            try:
                n = kv_cache.import_blocks(tokens, prepared)
                self.kv_handoff.blocks_in += n
                return n
            finally:
                self._note_overlap(time.perf_counter() - t0)

        if self._kv_stage is not None:
            return self._kv_stage.submit(land)
        fut = concurrent.futures.Future()
        try:
            fut.set_result(land())
        except Exception as e:  # pragma: no cover - cache insert bug
            fut.set_exception(e)
        return fut

    def _store_finished_sequence(self, slot: int, req: GenRequest) -> None:
        """Cache the FULL finished sequence (prompt + generated tokens)
        so turn N+1 of a conversation prefix-hits the blocks turn N
        decoded — the multi-turn/agent-loop win block granularity
        exists for. Rides the same kv-copy executor as the prefill
        store. Single-host only by construction: worker/backends.py
        never passes ``host_kv_cache_mb`` to multi-host replicas, so
        the decode-state rows sliced here are always addressable."""
        kv_cache = self.host_kv_cache
        if kv_cache is None or self._kv_copy_pool is None:
            return
        if req.embeds_override is not None:
            # VLM prompt: placeholder ids alias across different images,
            # so image-conditioned KV must never enter the token-keyed
            # cache (same exclusion as the prefill-time paths)
            return
        # Drop the trailing output token: a sampled token's KV is only
        # written on device when it is *fed* on a later step, which may
        # not have happened for the final one by finish time. Every
        # earlier token was fed (its successor was sampled from it).
        seq = list(req.prompt_ids) + list(req.output_ids[:-1])
        bt = kv_cache.block_tokens
        if len(seq) // bt <= len(req.prompt_ids) // bt:
            # no full block beyond what the prefill-time store already
            # indexed — skip the device pull entirely
            return
        total = len(seq)
        # slice at a bucketed width so the dispatched slice executables
        # stay bounded; trim to the true length host-side in the worker
        width = self.runner.bucket_for(total)
        k_dev, v_dev = self.runner.slot_kv(self._state, slot, width)
        self._submit_kv_copy(seq, k_dev, v_dev, total)

    def _new_slot_info(self, req: GenRequest) -> _SlotInfo:
        info = _SlotInfo(request=req)
        # Stop strings and JSON-mode termination decide WHICH tokens
        # count from decoded text, so their detok must stay inline on
        # the scheduler (decision before the next delivery) — plain
        # requests stream through the detok worker in overlap mode.
        info.sync_detok = (
            not self.overlap
            or bool(req.stop_texts)
            or req.json_mode
        )
        if req.json_mode:
            from gpustack_tpu.engine.openai_tools import JsonScanner

            info.json_scan = JsonScanner()
        if self.speculative == "ngram":
            info.ngram = _NgramIndex(req.prompt_ids)
        return info

    def _finalize_start(
        self, slot: int, req: GenRequest, last_logits, k, v
    ) -> None:
        """Insert a finished prefill into the decode state and feed the
        first sampled token (shared by the one-shot, cached and chunked
        prefill paths).

        Overlap mode: the sampled token never touches the host here —
        ``insert`` consumes it as a device scalar, and the host learns
        it through the fetch pipeline like any decode token, so
        admission N+1 dispatches while N's prefill+sample is still in
        flight on device. Speculative modes (the proposers need exact
        host state) and logprobs requests (per-token arrays wanted
        immediately) take the synchronous path.
        """
        ids = req.prompt_ids
        # First generated token through the runner's device sampler
        # (multi-host followers replay the same call). Seeded rows draw
        # noise from fold_in(seed, position); decode samples token 2 at
        # position len(ids) (pre-increment), so the first token uses
        # len(ids)-1 to keep every draw's stream unique — a collision
        # would replay identical gumbel noise on two consecutive,
        # similarly-distributed steps.
        self._key, first_key = jax.random.split(self._key)
        seed = 0 if req.seed is None else int(req.seed) & 0xFFFFFFFF
        toks, tok_lp, top_ids, top_lps = self.runner.sample_first(
            last_logits, req.temperature, req.top_k, req.top_p,
            seed, req.seed is not None, len(ids) - 1, first_key,
            logit_bias=req.logit_bias,
        )
        if (
            self.overlap
            and not self.speculative
            and not req.logprobs
            and getattr(self.runner, "supports_async_insert", False)
        ):
            self._state = self.runner.insert(
                self._state, k, v, slot, len(ids), toks[0],
                req.temperature, req.top_k, req.top_p,
                seed, req.seed is not None, req.logit_bias,
            )
            self._slots[slot] = self._new_slot_info(req)
            # deferred first-token feed: fetched (and rolled back if the
            # request was aborted meanwhile) with the decode pipeline
            self._pending.append(
                (("first", toks), {slot: req.request_id})
            )
            return
        self._finalize_start_sync(
            slot, req, k, v, seed, toks, tok_lp, top_ids, top_lps
        )

    def _finalize_start_sync(
        self, slot, req, k, v, seed, toks, tok_lp, top_ids, top_lps
    ) -> None:
        """Synchronous first-token path (serial mode, speculative
        proposers, logprobs, multi-host broadcast runners): reads the
        sampled token to the host before insert — a designated sync."""
        ids = req.prompt_ids
        first = int(toks[0])
        first_lps = None
        if req.logprobs:
            first_lps = [(
                float(tok_lp[0]),
                [
                    (int(i), float(lp))
                    for i, lp in zip(
                        np.asarray(top_ids[0]), np.asarray(top_lps[0])
                    )
                ],
            )]
        self._state = self.runner.insert(
            self._state, k, v, slot, len(ids), first,
            req.temperature, req.top_k, req.top_p,
            seed, req.seed is not None, req.logit_bias,
        )
        info = self._new_slot_info(req)
        if self.draft_runner is not None:
            # mirror the slot on the draft: prefill + insert (greedy)
            dk_bucket = self.draft_runner.bucket_for(max(1, len(ids)))
            d_padded = list(ids) + [0] * (dk_bucket - len(ids))
            _, dk, dv = self.draft_runner.prefill(d_padded, len(ids))
            self._draft_state = self.draft_runner.insert(
                self._draft_state, dk, dv, slot, len(ids), first,
                0.0, 0, 1.0,
            )
        self._slots[slot] = info
        self._deliver(slot, info, [first], first_lps)
        # admission-time delivery: its own coalesced entry (the fetch
        # pipeline's flush points never see this path)
        self._flush_detok()
        if self.draft_runner is not None and slot in self._slots:
            # `first` is already the draft's pending last token (set at
            # insert); queueing it again would double-feed it
            self._slots[slot].pending_draft.clear()

    def _decode_once(self) -> None:
        if self.draft_runner is not None and self._spec_safe():
            # Drain the fetch pipeline first: a draft chain must continue
            # the target's ACTUAL last token — proposing from a lagged
            # context misaligns the whole chain and collapses acceptance
            # (the ngram proposer tolerates lag; a sequential draft does
            # not). One host sync per spec step, amortized over up to
            # spec_tokens generated tokens.
            self._drain_pending()
        # Snapshot slot ownership at dispatch time: by the time this step's
        # tokens are fetched (lagged), a slot may have been retired and
        # re-used — the request_id check drops such stale tokens.
        owners = {
            s: info.request.request_id for s, info in self._slots.items()
        }
        if not owners:
            return
        if self.speculative == "ngram" and self._spec_safe():
            proposals = self._build_proposals()
            self._state, tokens, produced = self.runner.verify_step(
                self._state, proposals
            )
            self._spec_steps += 1
            self._spec_proposed += len(owners) * (self.spec_tokens - 1)
            self._pending.append((("spec", (tokens, produced)), owners))
            self._note_spec_dispatch(len(owners))
        elif self.draft_runner is not None and self._spec_safe():
            proposals = self._draft_propose()
            self._state, tokens, produced = self.runner.verify_step(
                self._state, proposals
            )
            self._spec_steps += 1
            self._spec_proposed += len(owners) * (self.spec_tokens - 1)
            self._pending.append((("spec", (tokens, produced)), owners))
            self._note_spec_dispatch(len(owners))
        else:
            self._key, step_key = jax.random.split(self._key)
            self._state, out = self.runner.decode_step(
                self._state, step_key
            )
            self._pending.append((("decode", out), owners))
            # decode runs every slot whether or not it is active: the
            # idle-slot share is the decode side of padding waste
            self._step_mode = self._step_mode or "decode"
            self._step_real += len(owners)
            self._step_padded += self.max_slots
        self._step_count += 1
        if len(self._pending) > self.pipeline_depth:
            self._process_fetch(*self._pending.pop(0))

    def _note_spec_dispatch(self, active: int) -> None:
        """Flight accounting for one verify step: every slot computes
        spec_tokens positions whether active or not."""
        self._step_mode = self._step_mode or "spec_verify"
        self._step_real += active * self.spec_tokens
        self._step_padded += self.max_slots * self.spec_tokens
        self._step_spec_proposed += active * (self.spec_tokens - 1)

    # ---- speculative decoding (greedy n-gram) -------------------------

    def _spec_safe(self) -> bool:
        """Spec steps write P KV slots contiguously; stay clear of the
        cache end (host view lags by pipeline_depth steps, so add
        margin)."""
        margin = self.spec_tokens * (self.pipeline_depth + 2)
        for info in self._slots.values():
            req = info.request
            used = len(req.prompt_ids) + len(req.output_ids)
            if used + margin >= self.max_seq_len:
                return False
        return True

    def _build_proposals(self) -> np.ndarray:
        """N-gram lookup on each slot's (lagged) context via the
        incremental index — O(1) per slot per step."""
        P = self.spec_tokens
        proposals = np.zeros((self.max_slots, P), dtype=np.int32)
        for slot, info in self._slots.items():
            if info.ngram is None:
                continue
            prop = info.ngram.propose(P - 1)
            if prop:
                proposals[slot, : len(prop)] = prop
        return proposals

    def _draft_propose(self) -> np.ndarray:
        """Draft-model proposals [B, spec_tokens].

        1. catch-up: block-ingest each slot's delivered-but-uningested
           tokens into the draft cache (one jitted forward),
        2. propose: spec_tokens-1 greedy draft decode steps,
        3. rewind: restore the draft's positions/last_tokens — the
           speculative cache entries sit above the restored positions and
           are invisible until genuinely accepted tokens overwrite them.

        The draft sees the host's (fetch-lagged) view of each sequence —
        like the ngram proposer, this affects acceptance rate only; the
        target's verify step guarantees greedy-exact output.
        """
        P = self.spec_tokens
        ingest_width = max(
            (len(i.pending_draft) for i in self._slots.values()),
            default=0,
        )
        if ingest_width:
            # bound jit specializations: pad the block to the next power
            # of two, ingest at most 2P per step (leftover stays queued)
            ingest_width = min(ingest_width, 2 * P)
            width = 1
            while width < ingest_width:
                width *= 2
            block = np.zeros((self.max_slots, width), np.int32)
            counts = np.zeros((self.max_slots,), np.int32)
            for slot, info in self._slots.items():
                take = info.pending_draft[:width]
                info.pending_draft = info.pending_draft[len(take):]
                block[slot, : len(take)] = take
                counts[slot] = len(take)
            self._draft_state = self.draft_runner.ingest_step(
                self._draft_state, block, counts
            )
        snap = self.draft_runner.snapshot_sequence(self._draft_state)
        proposals = np.zeros((self.max_slots, P), np.int32)
        key = jax.random.key(0)  # draft sampling is greedy; key unused
        for j in range(P - 1):
            self._draft_state, out = self.draft_runner.decode_step(
                self._draft_state, key
            )
            proposals[:, j] = np.asarray(out[0])
        self._draft_state = self.draft_runner.restore_sequence(
            self._draft_state, snap
        )
        return proposals

    @staticmethod
    def _entry_ready(entry) -> bool:
        """Non-blocking: has the device finished computing this pending
        entry's tokens? (hasattr-guarded — jax builds in this container
        drift across 0.4.x; without the probe, entries wait out the
        full pipeline depth, which is correct, just lazier)."""
        (kind, payload), _ = entry
        arr = payload if kind == "first" else payload[0]
        ready = getattr(arr, "is_ready", None)
        return bool(ready()) if ready is not None else False

    def _drain_ready(self) -> None:
        """Fetch every leading pending entry whose device work already
        completed — the fetches are free (no wait), and delivering them
        promptly keeps slot turnover at serial-mode latency."""
        while self._pending and self._entry_ready(self._pending[0]):
            self._process_fetch(*self._pending.pop(0))

    def _drain_pending(self) -> None:
        while self._pending:
            self._process_fetch(*self._pending.pop(0))

    def _process_fetch(self, out, owners: Dict[int, str]) -> None:
        kind, payload = out
        lp_arr = top_ids_arr = top_lps_arr = None
        if kind == "first":
            # deferred first token from an overlapped admission: one row
            ((slot, owner_id),) = owners.items()
            info = self._slots.get(slot)
            if info is None or info.request.request_id != owner_id:
                # admission was aborted/finished before the fetch —
                # the speculative feed rolls back
                self.flight.note_rollback(1)
                return
            self._deliver(slot, info, [int(np.asarray(payload)[0])])
            self._flush_detok()
            return
        if kind == "spec":
            tok_arr, produced = (np.asarray(x) for x in payload)
        else:
            tokens, tok_lp, top_ids, top_lps = payload
            tok_arr = np.asarray(tokens)[:, None]   # sync point (lagged)
            produced = None
            lp_arr = np.asarray(tok_lp)
            top_ids_arr = np.asarray(top_ids)
            top_lps_arr = np.asarray(top_lps)
        for slot, owner_id in owners.items():
            n = (
                int(produced[slot]) if produced is not None
                else tok_arr.shape[1]
            )
            info = self._slots.get(slot)
            if info is None or info.request.request_id != owner_id:
                # rollback: this step was dispatched before a lagged
                # fetch ended (or re-tenanted) the slot — its tokens
                # never existed as far as any request is concerned
                if n > 0:
                    self.flight.note_rollback(n)
                continue
            if n <= 0:
                continue
            if produced is not None:
                self._spec_hits += n - 1
                self._step_spec_accepted += n - 1
            lps = None
            if lp_arr is not None and info.request.logprobs:
                lps = [(
                    float(lp_arr[slot]),
                    [
                        (int(i), float(lp))
                        for i, lp in zip(top_ids_arr[slot], top_lps_arr[slot])
                    ],
                )]
            self._deliver(
                slot, info, [int(t) for t in tok_arr[slot, :n]], lps
            )
        # coalesce: every slot's accepted tokens from THIS fetch ride
        # one detok queue entry
        self._flush_detok()

    def _deliver(
        self, slot: int, info: _SlotInfo, toks: List[int], lps=None
    ) -> None:
        """Deliver newly generated tokens (``lps``: optional aligned list
        of (token_logprob, [(id, logprob) alternatives])). Termination
        is decided here at the id level; detokenization either runs
        inline (``sync_detok`` — serial mode, stop strings, JSON mode)
        or is batched onto the detok worker."""
        req = info.request
        if req.aborted.is_set():
            # client disconnected mid-generation: free the slot now
            # instead of decoding to max_tokens for nobody
            self._finish(slot, info, "abort")
            return
        if not req.first_token_at:
            req.first_token_at = time.time()
        offload: List[int] = []
        for j, tok in enumerate(toks):
            is_eos = tok in self.tokenizer.eos_ids or tok in req.stop_ids
            if not is_eos:
                req.output_ids.append(tok)
                if lps is not None and j < len(lps):
                    req.output_logprobs.append(lps[j][0])
                    req.output_top_logprobs.append(lps[j][1])
                self._tokens_generated += 1
                self._step_out += 1
                if info.ngram is not None:
                    info.ngram.append(tok)
                if self.draft_runner is not None:
                    info.pending_draft.append(tok)
                if info.sync_detok:
                    info.buffer_ids.append(tok)
                    if self._emit_text(info, final=False):
                        dropped = len(toks) - j - 1
                        if dropped:
                            self.flight.note_rollback(dropped)
                        self._finish(slot, info, "stop")
                        return
                else:
                    offload.append(tok)
            at_cap = (
                len(req.prompt_ids) + len(req.output_ids)
                >= self.max_seq_len - 1
            )
            if is_eos or at_cap or len(req.output_ids) >= req.max_tokens:
                dropped = len(toks) - j - 1
                if dropped:
                    self.flight.note_rollback(dropped)
                if offload:
                    self._detok_batch.append((info, offload))
                self._finish(slot, info, "stop" if is_eos else "length")
                return
        if offload:
            self._detok_batch.append((info, offload))

    def _emit_text(self, info: _SlotInfo, final: bool) -> bool:
        """Advance incremental detokenization; stream newly-safe text.

        Returns True when a stop string matched (text already truncated and
        flushed). Text that could still turn into a stop string — or a
        dangling multibyte sequence — is held back until resolved.
        """
        req = info.request
        if info.buffer_ids:
            piece = self.tokenizer.decode(info.buffer_ids)
            if final or not piece.endswith("�"):
                info.text += piece
                info.buffer_ids.clear()
        # JSON mode: the first complete top-level JSON value ends the
        # request — scan only the newly decoded chars (incremental state
        # lives in the scanner), truncate any tail past the closing
        # bracket, flush, stop.
        if info.json_scan is not None and len(info.text) > info.json_scanned:
            rel = info.json_scan.feed(info.text[info.json_scanned:])
            if rel != -1:
                info.text = info.text[: info.json_scanned + rel]
                self._push(info, info.text[info.emitted:])
                return True
            info.json_scanned = len(info.text)
        unemitted = info.text[info.emitted:]
        # Stop-string search: hold-back guarantees no stop can straddle the
        # emitted boundary, so searching the unemitted tail is complete.
        for s in req.stop_texts:
            idx = unemitted.find(s)
            if idx != -1:
                info.text = info.text[: info.emitted + idx]
                self._push(info, info.text[info.emitted:])
                return True
        hold = 0
        if not final:
            for s in req.stop_texts:
                for k in range(min(len(s) - 1, len(unemitted)), 0, -1):
                    if unemitted.endswith(s[:k]):
                        hold = max(hold, k)
                        break
        self._push(info, unemitted[: len(unemitted) - hold] if hold else unemitted)
        return False

    def _push(self, info: _SlotInfo, piece: str) -> None:
        if not piece:
            return
        info.emitted += len(piece)
        req = info.request
        if req.stream is not None:
            last = req.output_ids[-1] if req.output_ids else 0
            req.stream.put((last, piece))

    def _finish(self, slot: int, info: _SlotInfo, reason: str) -> None:
        req = info.request
        if info.sync_detok:
            # A late stop-match during the final flush upgrades the
            # reason (only sync requests can carry stop strings).
            if self._emit_text(info, final=True):
                reason = "stop"
            req.output_text = info.text
        req.finish_reason = reason
        req.finished_at = time.time()
        if reason in ("stop", "length"):
            # aborted/errored slots may have undelivered device state;
            # only cleanly finished sequences are safe to cache
            self._store_finished_sequence(slot, info.request)
        if req.first_token_at and req.submitted_at:
            self.ttft_hist.observe(req.first_token_at - req.submitted_at)
            self.e2e_hist.observe(req.finished_at - req.submitted_at)
            if len(req.output_ids) > 1:
                self.tpot_hist.observe(
                    (req.finished_at - req.first_token_at)
                    / (len(req.output_ids) - 1)
                )
        self._state = self.runner.deactivate(self._state, slot)
        if self.draft_runner is not None:
            self._draft_state = self.draft_runner.deactivate(
                self._draft_state, slot
            )
        del self._slots[slot]
        self._free.append(slot)
        if info.sync_detok:
            if req.stream is not None:
                req.stream.put(None)  # sentinel: stream end
            req.done.set()
        else:
            # the final flush, stream sentinel and done event ride the
            # detok worker: flushing the coalesced batch FIRST keeps
            # the FIFO queue's ordering contract (this request's last
            # tokens precede its finish)
            self._flush_detok()
            self._detok.finish(info)
