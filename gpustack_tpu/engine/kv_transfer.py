"""Content-addressed KV block transfer: disaggregated prefill/decode.

The host KV cache (engine/kv_host_cache.py) already makes KV blocks
content-addressed (radix trie on rolling sha256 block hashes) and
serializable (host-RAM numpy, opt-in int8 with per-block scales). This
module turns those blocks into a **wire format** so a prefill-role
replica can hand a finished prompt's blocks to a decode-role replica
(the reference treats extended KV cache + prefill-context-parallel as
first-class placement fields, SURVEY §5 "Long-context"; vLLM's
disaggregated serving moves KV over NCCL/LMCache — over PCIe-attached
TPU hosts the transfer is plain HTTP between host RAMs).

Wire format — a stream of self-describing frames, no stream trailer
(the decoder yields every frame whose bytes fully arrived, so a peer
dying mid-stream loses only the tail — the importer keeps the complete
prefix, which is exactly what a radix cache can use):

    magic   b"GKVX1\\n"                     (once, start of stream)
    frame   u32 meta_len | meta JSON | k bytes | v bytes
            | k_scale bytes | v_scale bytes

``meta`` carries the block's chain key (hex — advisory; the importer
recomputes keys from tokens, so content addressing survives the wire),
its tokens, dtype/shape info, explicit payload byte lengths, and a
crc32 of the payload. int8 blocks travel **as stored** (int8 + scales)
— half the bytes of the fp tier, dequantized only if the receiving
cache is not int8. A frame may be ``skipped`` (tokens only, no
payload): the exporter elides blocks the requester declared it already
holds (``have`` keys), while the token chain stays intact so the
importer can rebuild the radix path.
"""

from __future__ import annotations

import binascii
import dataclasses
import json
import struct
import threading
from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple

import numpy as np

MAGIC = b"GKVX1\n"
_U32 = struct.Struct("<I")

# one frame's meta must stay far under this; a larger announced meta is
# a corrupt or hostile stream, not a big block
MAX_META_BYTES = 1 << 20


def _dtype_name(dtype) -> str:
    return np.dtype(dtype).name if not _is_bf16(dtype) else "bfloat16"


def _is_bf16(dtype) -> bool:
    return str(np.dtype(dtype)) == "bfloat16" or str(dtype) == "bfloat16"


def _dtype_from_name(name: str):
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


@dataclasses.dataclass
class Frame:
    """One decoded wire frame (``skipped`` frames carry no arrays)."""

    key: str                      # hex chain key (advisory)
    tokens: Tuple[int, ...]
    skipped: bool = False
    k: Optional[np.ndarray] = None
    v: Optional[np.ndarray] = None
    k_scale: Optional[np.ndarray] = None
    v_scale: Optional[np.ndarray] = None
    dtype: str = ""               # logical (dequantized) dtype name
    nbytes: int = 0               # payload bytes on the wire


def _array_bytes(arr: Optional[np.ndarray]) -> bytes:
    if arr is None:
        return b""
    return np.ascontiguousarray(arr).tobytes()


def encode_frame(
    key_hex: str,
    tokens,
    *,
    k: Optional[np.ndarray] = None,
    v: Optional[np.ndarray] = None,
    k_scale: Optional[np.ndarray] = None,
    v_scale: Optional[np.ndarray] = None,
    dtype: str = "",
) -> bytes:
    """One block → one wire frame. ``k is None`` encodes a skipped
    (dedup) frame."""
    kb, vb = _array_bytes(k), _array_bytes(v)
    ksb, vsb = _array_bytes(k_scale), _array_bytes(v_scale)
    payload = kb + vb + ksb + vsb
    meta: Dict[str, Any] = {
        "key": key_hex,
        "tokens": [int(t) for t in tokens],
    }
    if k is None:
        meta["skipped"] = True
    else:
        meta.update(
            dtype=dtype or _dtype_name(k.dtype),
            stored_dtype=_dtype_name(k.dtype),
            k_shape=list(k.shape),
            v_shape=list(v.shape),
            k_len=len(kb),
            v_len=len(vb),
            ks_len=len(ksb),
            vs_len=len(vsb),
            crc=binascii.crc32(payload) & 0xFFFFFFFF,
        )
        if k_scale is not None:
            meta["ks_shape"] = list(k_scale.shape)
            meta["vs_shape"] = list(v_scale.shape)
    mb = json.dumps(meta, separators=(",", ":")).encode()
    return _U32.pack(len(mb)) + mb + payload


def encode_stream(frames: Iterable[bytes]) -> Iterator[bytes]:
    """Prepend the magic; yield each encoded frame."""
    yield MAGIC
    yield from frames


class FrameDecoder:
    """Incremental decoder: ``feed(chunk)`` yields every frame whose
    bytes fully arrived. A truncated tail (peer died mid-stream) is
    simply never yielded; a corrupt frame (bad magic, oversized meta,
    crc mismatch) raises ``ValueError`` — the importer treats both the
    same way: keep what landed, cold-start the rest."""

    def __init__(self) -> None:
        self._buf = bytearray()
        self._magic_seen = False

    def feed(self, chunk: bytes) -> List[Frame]:
        self._buf.extend(chunk)
        out: List[Frame] = []
        if not self._magic_seen:
            if len(self._buf) < len(MAGIC):
                return out
            if bytes(self._buf[: len(MAGIC)]) != MAGIC:
                raise ValueError("kv-transfer: bad stream magic")
            del self._buf[: len(MAGIC)]
            self._magic_seen = True
        while True:
            frame = self._try_frame()
            if frame is None:
                return out
            out.append(frame)

    def _try_frame(self) -> Optional[Frame]:
        if len(self._buf) < _U32.size:
            return None
        (meta_len,) = _U32.unpack(bytes(self._buf[: _U32.size]))
        if meta_len > MAX_META_BYTES:
            raise ValueError(
                f"kv-transfer: frame meta of {meta_len} bytes exceeds "
                f"the {MAX_META_BYTES} cap"
            )
        if len(self._buf) < _U32.size + meta_len:
            return None
        meta = json.loads(
            bytes(self._buf[_U32.size : _U32.size + meta_len])
        )
        if meta.get("skipped"):
            del self._buf[: _U32.size + meta_len]
            return Frame(
                key=str(meta.get("key", "")),
                tokens=tuple(int(t) for t in meta["tokens"]),
                skipped=True,
            )
        payload_len = (
            meta["k_len"] + meta["v_len"]
            + meta.get("ks_len", 0) + meta.get("vs_len", 0)
        )
        total = _U32.size + meta_len + payload_len
        if len(self._buf) < total:
            return None
        payload = bytes(self._buf[_U32.size + meta_len : total])
        del self._buf[:total]
        if (binascii.crc32(payload) & 0xFFFFFFFF) != meta.get("crc"):
            raise ValueError("kv-transfer: frame crc mismatch")
        off = 0

        def take(n: int, shape, dtype) -> Optional[np.ndarray]:
            nonlocal off
            if n == 0:
                return None
            raw = payload[off : off + n]
            off += n
            return np.frombuffer(raw, dtype=dtype).reshape(shape)

        stored = _dtype_from_name(
            meta.get("stored_dtype") or meta["dtype"]
        )
        k = take(meta["k_len"], meta["k_shape"], stored)
        v = take(meta["v_len"], meta["v_shape"], stored)
        ks = take(
            meta.get("ks_len", 0), meta.get("ks_shape"), np.float32
        )
        vs = take(
            meta.get("vs_len", 0), meta.get("vs_shape"), np.float32
        )
        return Frame(
            key=str(meta.get("key", "")),
            tokens=tuple(int(t) for t in meta["tokens"]),
            k=k, v=v, k_scale=ks, v_scale=vs,
            dtype=meta["dtype"],
            nbytes=payload_len,
        )


def decode_stream(data: bytes) -> List[Frame]:
    """Whole-buffer convenience over :class:`FrameDecoder`."""
    return FrameDecoder().feed(data)


# ---------------------------------------------------------------------------
# Cache-facing export / import
# ---------------------------------------------------------------------------


def encode_block(blk: Dict[str, Any], have_set) -> Tuple[bytes, bool]:
    """One exported cache block → ``(wire frame, carried_payload)``:
    a block the requester already holds travels as a token-only dedup
    frame (payload False)."""
    if blk["key"] in have_set:
        return encode_frame(blk["key"], blk["tokens"]), False
    return (
        encode_frame(
            blk["key"], blk["tokens"],
            k=blk["k"], v=blk["v"],
            k_scale=blk["k_scale"], v_scale=blk["v_scale"],
            dtype=blk["dtype"],
        ),
        True,
    )


def export_frames(
    cache,
    prompt_ids,
    have: Optional[Iterable[str]] = None,
    max_blocks: int = 0,
) -> Iterator[bytes]:
    """Encode ``cache``'s matched block run for ``prompt_ids`` as wire
    frames, eliding payloads for blocks whose chain key the requester
    declared in ``have``. Blocks travel AS STORED (int8 stays int8 —
    half the wire bytes), so export does no quantization work."""
    have_set = frozenset(have or ())
    blocks = cache.export_blocks(prompt_ids, max_blocks=max_blocks)
    yield MAGIC
    for blk in blocks:
        yield encode_block(blk, have_set)[0]


def prepare_import(
    cache, frames: List[Frame]
) -> Tuple[List[int], Dict[int, Tuple], int]:
    """Convert decoded frames to the receiving cache's storage tier:
    ``(token_chain, prepared_blocks, wire_bytes)`` ready for
    ``cache.import_blocks`` (or the engine's stager-backed
    ``kv_import_prepared``). Pure CPU work — callers run it off the
    event loop. Every frame must carry exactly the importing cache's
    block granularity: ``import_blocks`` re-slices the concatenated
    token chain by ITS block_tokens, so a block-size-mismatched peer
    (e.g. an old-generation exporter mid-rollout of a kv_block_tokens
    change) would silently attach K/V to the wrong token runs —
    rejected here instead (callers degrade to a cold prefill)."""
    tokens: List[int] = []
    prepared: Dict[int, Tuple] = {}
    bytes_in = 0
    for i, fr in enumerate(frames):
        if len(fr.tokens) != cache.block_tokens:
            raise ValueError(
                f"kv-transfer: frame of {len(fr.tokens)} tokens does "
                f"not match the cache's block_tokens="
                f"{cache.block_tokens} (peer block-size mismatch)"
            )
        tokens.extend(fr.tokens)
        if fr.skipped:
            continue
        bytes_in += fr.nbytes
        prepared[i] = _to_cache_tier(cache, fr)
    return tokens, prepared, bytes_in


def import_frames(cache, frames: List[Frame]) -> Tuple[int, int, int]:
    """Land decoded frames in ``cache``: rebuild the token chain (keys
    are recomputed by the cache from tokens — the wire's hex keys are
    advisory), convert payloads to the cache's tier (int8↔fp as
    needed), attach. Returns ``(blocks_attached, tokens, bytes_in)``.

    Skipped frames contribute tokens only (the requester already holds
    those blocks); a skipped frame for a block the cache does NOT hold
    ends the run — attaching past a gap would corrupt the radix path.
    """
    if not frames:
        return 0, 0, 0
    tokens, prepared, bytes_in = prepare_import(cache, frames)
    attached = cache.import_blocks(tokens, prepared)
    return attached, len(tokens), bytes_in


def _to_cache_tier(cache, fr: Frame) -> Tuple:
    """(k, v, scales|None, dtype, nbytes) in the receiving cache's
    storage tier."""
    from gpustack_tpu.engine.kv_host_cache import (
        _dequantize_block,
        _quantize_block,
    )

    logical = _dtype_from_name(fr.dtype)
    is_int8 = fr.k_scale is not None
    if cache.int8:
        if is_int8:
            k, v, scales = fr.k, fr.v, (fr.k_scale, fr.v_scale)
        else:
            qk, sk = _quantize_block(fr.k)
            qv, sv = _quantize_block(fr.v)
            k, v, scales = qk, qv, (sk, sv)
        nbytes = (
            k.nbytes + v.nbytes
            + scales[0].nbytes + scales[1].nbytes
        )
        return k, v, scales, logical, nbytes
    if is_int8:
        k = _dequantize_block(fr.k, fr.k_scale, logical)
        v = _dequantize_block(fr.v, fr.v_scale, logical)
    else:
        k, v = fr.k, fr.v
    k = np.ascontiguousarray(k)
    v = np.ascontiguousarray(v)
    return k, v, None, logical, k.nbytes + v.nbytes


# ---------------------------------------------------------------------------
# Handoff accounting (rendered by the engine exporter)
# ---------------------------------------------------------------------------

HANDOFF_BUCKETS_S = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class SecondsHist:
    """Minimal fixed-bucket histogram with the same ``snapshot()``
    contract as the engine's LatencyHistogram (the exporter renders
    both through one loop). Thread-safe: observed from request
    handlers and executor threads."""

    def __init__(self, buckets=HANDOFF_BUCKETS_S):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)
        self.total = 0.0
        self.count = 0
        self._mu = threading.Lock()

    def observe(self, value: float) -> None:
        with self._mu:
            self.total += value
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    self.counts[i] += 1
                    break
            else:
                self.counts[-1] += 1
            self.count += 1

    def snapshot(self):
        with self._mu:
            counts = list(self.counts)
            total, count = self.total, self.count
        cum, out = 0, []
        for ub, c in zip(self.buckets, counts):
            cum += c
            out.append((ub, cum))
        inf = cum + counts[-1]
        out.append((float("inf"), inf))
        return out, total, min(count, inf)


class HandoffStats:
    """Engine-side handoff accounting: bytes/blocks in either
    direction, failures, and end-to-end pull latency. Counter writes
    are GIL-atomic int adds from the aiohttp handlers and the kv-copy
    executor; no lock needed."""

    def __init__(self) -> None:
        self.bytes_in = 0
        self.bytes_out = 0
        self.blocks_in = 0
        self.blocks_out = 0
        self.failures = 0
        self.pulls = 0
        self.seconds = SecondsHist()

    def snapshot(self) -> Dict[str, Any]:
        return {
            "bytes_in": self.bytes_in,
            "bytes_out": self.bytes_out,
            "blocks_in": self.blocks_in,
            "blocks_out": self.blocks_out,
            "failures": self.failures,
            "pulls": self.pulls,
        }
