"""OpenAI-compatible HTTP front for the engine (aiohttp).

Endpoint parity with the engine-level API surface the reference proxies to
(reference gpustack/routes/openai.py registers chat/completions/embeddings
prefixes and relays the full parameter surface — tools, logprobs, n,
response_format, seed — to the backend engines, openai.py:185-313):
``/v1/completions``, ``/v1/chat/completions`` (+SSE streaming),
``/v1/models``, ``/healthz``, ``/metrics``.

Runs as a standalone process per model instance — the unit the worker's
serve manager launches and health-probes (reference
worker/serve_manager.py:1291-1412 spawns engine processes the same way).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import queue
import time
import uuid
from typing import Any, Dict, List, Optional

from aiohttp import web

from gpustack_tpu.engine.engine import GenRequest, LLMEngine
from gpustack_tpu.engine.openai_tools import (
    JSON_MODE_INSTRUCTION,
    ToolCallHoldback,
    forced_function,
    parse_tool_calls,
)

logger = logging.getLogger(__name__)

# Reported when the request set ``seed``: OpenAI pairs seeded determinism
# with a fingerprint identifying the backend configuration.
SYSTEM_FINGERPRINT = "fp_gpustack_tpu"
MAX_N = 8          # parallel choices per request (each takes a slot)
MAX_TOP_LOGPROBS = 20


def _usage(reqs) -> Dict[str, int]:
    if isinstance(reqs, GenRequest):
        reqs = [reqs]
    # n>1 choices share one prompt: bill prompt tokens once (OpenAI
    # semantics), completions per choice
    pt = len(reqs[0].prompt_ids) if reqs else 0
    ct = sum(len(r.output_ids) for r in reqs)
    return {
        "prompt_tokens": pt,
        "completion_tokens": ct,
        "total_tokens": pt + ct,
    }


def _token_entry(tokenizer, tid: int, lp: float) -> Dict[str, Any]:
    text = tokenizer.decode([tid])
    return {
        "token": text,
        "logprob": lp,
        "bytes": list(text.encode("utf-8")),
    }


def _chat_logprobs(req: GenRequest, tokenizer) -> Dict[str, Any]:
    """OpenAI chat logprobs shape: choices[].logprobs.content[]."""
    content = []
    k = req.top_logprobs
    for tid, lp, tops in zip(
        req.output_ids, req.output_logprobs, req.output_top_logprobs
    ):
        entry = _token_entry(tokenizer, tid, lp)
        entry["top_logprobs"] = [
            _token_entry(tokenizer, i, p) for i, p in tops[:k]
        ]
        content.append(entry)
    return {"content": content}


def _completion_logprobs(req: GenRequest, tokenizer, k: int) -> Dict[str, Any]:
    """Legacy completions logprobs shape: tokens/token_logprobs/
    top_logprobs/text_offset arrays."""
    tokens, offsets = [], []
    off = 0
    for tid in req.output_ids:
        text = tokenizer.decode([tid])
        tokens.append(text)
        offsets.append(off)
        off += len(text)
    return {
        "tokens": tokens,
        "token_logprobs": list(req.output_logprobs),
        "top_logprobs": [
            {tokenizer.decode([i]): p for i, p in tops[:k]}
            for tops in req.output_top_logprobs
        ],
        "text_offset": offsets,
    }


class OpenAIServer:
    """aiohttp application serving one LLMEngine."""

    def __init__(self, engine: LLMEngine, model_name: Optional[str] = None):
        from gpustack_tpu.observability.tracing import trace_middleware

        self.engine = engine
        self.model_name = model_name or engine.cfg.name
        # the engine is the last hop of the trace: the middleware adopts
        # the worker proxy's traceparent and logs this hop's trace=… line.
        # Body cap matches the worker reverse proxy's (256 MiB): a KV
        # handoff push at POST /kv/import carries whole block runs —
        # the aiohttp default 1 MiB would 413 any real import
        self.app = web.Application(
            middlewares=[trace_middleware("engine")],
            client_max_size=256 * 2**20,
        )
        self.app.add_routes(
            [
                web.get("/healthz", self.healthz),
                web.get("/v1/models", self.models),
                web.post("/v1/completions", self.completions),
                web.post("/v1/chat/completions", self.chat_completions),
                web.post("/v1/embeddings", self.embeddings),
                web.post("/v1/rerank", self.rerank),
                web.get("/metrics", self.metrics),
                web.get("/debug/flight", self.debug_flight),
                web.post("/debug/profile", self.debug_profile),
                # disaggregated prefill/decode (docs/KV_CACHE.md "KV
                # handoff"): content-addressed block export/import
                web.post("/kv/export", self.kv_export),
                web.post("/kv/import", self.kv_import),
                # fleet KV fabric (docs/KV_CACHE.md "Fleet KV fabric"):
                # directory scrape + background prefetch trigger
                web.post("/kv/summary", self.kv_summary),
                web.post("/kv/pull", self.kv_pull),
            ]
        )
        self._started = time.time()
        # lazy session for pulling handed-off KV from a peer replica
        # (the X-GPUStack-KV-Source request header names the source)
        self._kv_session = None
        # in-flight background prefetch pulls (strong refs) + outcome
        # counters for the gpustack_kv_prefetch_total metric family
        self._kv_pulls: set = set()
        self.prefetch_ok = 0
        self.prefetch_failed = 0

    # ---- endpoints ------------------------------------------------------

    async def healthz(self, request: web.Request) -> web.Response:
        health = self.engine.health()
        return web.json_response(
            health, status=200 if health["status"] == "ok" else 503
        )

    async def models(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "object": "list",
                "data": [
                    {
                        "id": self.model_name,
                        "object": "model",
                        "created": int(self._started),
                        "owned_by": "gpustack_tpu",
                    }
                ],
            }
        )

    async def metrics(self, request: web.Request) -> web.Response:
        h = self.engine.health()
        lines = [
            "# TYPE gpustack_engine_slots_used gauge",
            f"gpustack_engine_slots_used {h['slots_used']}",
            "# TYPE gpustack_engine_slots_total gauge",
            f"gpustack_engine_slots_total {h['slots_total']}",
            "# TYPE gpustack_engine_waiting gauge",
            f"gpustack_engine_waiting {h['waiting']}",
            "# TYPE gpustack_engine_decode_steps_total counter",
            f"gpustack_engine_decode_steps_total {h['steps']}",
            "# TYPE gpustack_engine_tokens_generated_total counter",
            f"gpustack_engine_tokens_generated_total {h['tokens_generated']}",
        ]
        # host KV cache: TYPE text derives from the declared vocabulary
        # (observability/metrics.py METRIC_FAMILIES) so the metrics-
        # drift analyzer sees exactly one declaration site per family
        from gpustack_tpu.observability.metrics import METRIC_FAMILIES

        for family, value in (
            ("gpustack_kv_cache_hits", h["kv_cache_hits"]),
            ("gpustack_kv_cache_misses", h["kv_cache_misses"]),
            (
                "gpustack_kv_cache_prefix_tokens_reused",
                h["kv_cache_prefix_tokens_reused"],
            ),
            ("gpustack_kv_cache_bytes", h["kv_cache_host_bytes"]),
        ):
            lines.append(f"# TYPE {family} {METRIC_FAMILIES[family]}")
            lines.append(f"{family} {value}")
        # disaggregated KV handoff (engine/kv_transfer.py): wire
        # bytes/blocks per direction + pull failures; the latency
        # histogram rides the request-histogram loop below
        ho = self.engine.kv_handoff
        for family, series in (
            (
                "gpustack_kv_handoff_bytes_total",
                (("in", ho.bytes_in), ("out", ho.bytes_out)),
            ),
            (
                "gpustack_kv_handoff_blocks_total",
                (("in", ho.blocks_in), ("out", ho.blocks_out)),
            ),
        ):
            lines.append(f"# TYPE {family} {METRIC_FAMILIES[family]}")
            for direction, value in series:
                lines.append(
                    f'{family}{{direction="{direction}"}} {value}'
                )
        lines.append(
            "# TYPE gpustack_kv_handoff_failures_total "
            f"{METRIC_FAMILIES['gpustack_kv_handoff_failures_total']}"
        )
        lines.append(
            f"gpustack_kv_handoff_failures_total {ho.failures}"
        )
        # fleet KV fabric: disk spill tier + background prefetch
        cache = self.engine.host_kv_cache
        spill = cache.spill if cache is not None else None
        if spill is not None:
            s = spill.snapshot()
            for family, series in (
                (
                    "gpustack_kv_spill_bytes_total",
                    (
                        ("out", s["bytes_spilled"]),
                        ("in", s["bytes_loaded"]),
                    ),
                ),
                (
                    "gpustack_kv_spill_blocks_total",
                    (
                        ("out", s["blocks_spilled"]),
                        ("in", s["blocks_loaded"]),
                    ),
                ),
            ):
                lines.append(
                    f"# TYPE {family} {METRIC_FAMILIES[family]}"
                )
                for direction, value in series:
                    lines.append(
                        f'{family}{{direction="{direction}"}} {value}'
                    )
            for family, value in (
                ("gpustack_kv_spill_resident_bytes", s["bytes"]),
                ("gpustack_kv_spill_corrupt_total", s["corrupt"]),
                ("gpustack_kv_spill_evictions_total", s["evictions"]),
                (
                    "gpustack_kv_spill_faultbacks_total",
                    cache.faultbacks,
                ),
            ):
                lines.append(
                    f"# TYPE {family} {METRIC_FAMILIES[family]}"
                )
                lines.append(f"{family} {value}")
        if cache is not None:
            family = "gpustack_kv_prefetch_total"
            lines.append(f"# TYPE {family} {METRIC_FAMILIES[family]}")
            for result, value in (
                ("ok", self.prefetch_ok),
                ("failed", self.prefetch_failed),
            ):
                lines.append(f'{family}{{result="{result}"}} {value}')
        # flight recorder: per-step scheduler telemetry (step-time
        # histogram by mode, real-vs-padded dispatch, occupancy, queue
        # wait, speculation economics — observability/flight.py)
        flight = getattr(self.engine, "flight", None)
        if flight is not None:
            lines.extend(flight.metrics_lines())
        # request-latency histograms (vLLM's ttft/tpot observability
        # parity — the reference normalizes these into its dashboards,
        # metrics_config.yaml)
        for name, hist in (
            ("gpustack_engine_ttft_seconds", self.engine.ttft_hist),
            ("gpustack_engine_tpot_seconds", self.engine.tpot_hist),
            ("gpustack_engine_e2e_seconds", self.engine.e2e_hist),
            ("gpustack_kv_handoff_seconds", ho.seconds),
        ):
            cum, total, count = hist.snapshot()
            lines.append(f"# TYPE {name} histogram")
            for ub, c in cum:
                le = "+Inf" if ub == float("inf") else repr(ub)
                lines.append(f'{name}_bucket{{le="{le}"}} {c}')
            lines.append(f"{name}_sum {total:.6f}")
            lines.append(f"{name}_count {count}")
        return web.Response(text="\n".join(lines) + "\n")

    async def debug_flight(self, request: web.Request) -> web.Response:
        """Raw flight-recorder view: the most recent per-step records
        plus windowed aggregates (``window_s=`` bounds the aggregate to
        recent steps; ``limit=`` caps the raw records returned). The
        fleet rollup (server ``GET /v2/debug/fleet``) consumes the same
        numbers through the normalized /metrics path — this endpoint is
        the ground truth it must agree with."""
        flight = getattr(self.engine, "flight", None)
        if flight is None:
            return _error(404, "engine has no flight recorder")
        try:
            limit = min(2048, int(request.query.get("limit", 100)))
            window_s = request.query.get("window_s")
            window = float(window_s) if window_s is not None else None
        except ValueError:
            return _error(400, "limit/window_s must be numbers")
        return web.json_response({
            "model": self.model_name,
            "records": flight.snapshot(limit=limit),
            "aggregate": flight.aggregate(window_s=window),
            "overhead_ratio": round(flight.overhead_ratio(), 6),
        })

    async def debug_profile(self, request: web.Request) -> web.Response:
        """On-demand profiler capture: wrap the next N busy scheduler
        steps in ``jax.profiler.trace`` (when this jax build has the
        profiler API — degrades to flight-records-only otherwise),
        writing the artifact under ``out_dir``. Blocks until the steps
        elapse or ``timeout_s`` passes; an idle engine returns whatever
        it captured. Relayed from the server admin surface
        (``POST /v2/model-instances/{id}/profile``) via the worker."""
        try:
            steps = int(request.query.get("steps", 20))
            timeout_s = min(
                120.0, float(request.query.get("timeout_s", 30.0))
            )
        except ValueError:
            return _error(400, "steps/timeout_s must be numbers")
        if steps < 1:
            return _error(400, "steps must be >= 1")
        out_dir = request.query.get("out_dir", "")
        loop = asyncio.get_running_loop()
        try:
            result = await loop.run_in_executor(
                None,
                lambda: self.engine.capture_profile(
                    steps, out_dir=out_dir, timeout_s=timeout_s
                ),
            )
        except ValueError as e:
            return _error(409, str(e))
        return web.json_response(result)

    # ---- disaggregated KV handoff (docs/KV_CACHE.md) -------------------

    @staticmethod
    def _handoff_timeout() -> float:
        return float(
            os.environ.get("GPUSTACK_TPU_KV_HANDOFF_TIMEOUT") or 10.0
        )

    async def kv_export(self, request: web.Request) -> web.StreamResponse:
        """Stream the host cache's matched radix block run for a prompt
        as content-addressed wire frames (engine/kv_transfer.py).

        Body: ``{"prompt_ids": [...], "have": [hex...], "prefill":
        bool}``. ``have`` keys the requester already holds travel as
        token-only dedup frames. ``prefill=true`` on a miss runs a
        one-token generation first so a prefill-role replica can be
        handed a prompt it has never seen — THE disaggregated-serving
        hop: prefill compute happens here, the decode replica imports
        the blocks and prefills only the sub-block tail."""
        eng = self.engine
        cache = eng.host_kv_cache
        if cache is None:
            return _error(404, "engine has no host KV cache")
        try:
            body = await request.json()
            prompt_ids = [int(t) for t in body.get("prompt_ids") or []]
        except (json.JSONDecodeError, TypeError, ValueError):
            return _error(400, "invalid JSON body")
        # tail_key mode (fleet prefetch): the puller has no tokens —
        # only the directory-advertised chain key of the deepest block
        # — so the export walks parent pointers instead of the prompt
        tail_key = str(body.get("tail_key") or "")
        if not prompt_ids and not tail_key:
            return _error(400, "missing 'prompt_ids' or 'tail_key'")
        have = [str(k) for k in body.get("have") or []]
        want_blocks = (
            (len(prompt_ids) - 1) // cache.block_tokens
            if prompt_ids else 0
        )
        loop = asyncio.get_running_loop()
        if prompt_ids and body.get("prefill") and want_blocks > 0:
            held = await loop.run_in_executor(
                None, cache.peek_prefix_len, prompt_ids
            )
            if held < want_blocks * cache.block_tokens:
                err = await loop.run_in_executor(
                    None, self._prefill_for_export, prompt_ids,
                    want_blocks * cache.block_tokens,
                )
                if err:
                    return _error(503, err)
        from gpustack_tpu.engine.kv_transfer import MAGIC, encode_block

        def assemble():
            # ONE trie walk: encode straight off export_blocks and
            # count payload frames as they are produced (a second walk
            # just to count could disagree under concurrent eviction)
            have_set = frozenset(have)
            chunks = [MAGIC]
            payload_blocks = 0
            blocks = (
                cache.export_blocks(prompt_ids) if prompt_ids
                else cache.export_chain(tail_key)
            )
            for blk in blocks:
                frame, carried = encode_block(blk, have_set)
                chunks.append(frame)
                payload_blocks += int(carried)
            return chunks, payload_blocks

        chunks, payload_blocks = await loop.run_in_executor(
            None, assemble
        )
        resp = web.StreamResponse(
            headers={"Content-Type": "application/x-gpustack-kv"}
        )
        await resp.prepare(request)
        for chunk in chunks:
            await resp.write(chunk)
            eng.kv_handoff.bytes_out += len(chunk)
        eng.kv_handoff.blocks_out += payload_blocks
        await resp.write_eof()
        return resp

    def _prefill_for_export(
        self, prompt_ids, want_tokens: int
    ) -> str:
        """Run a one-token generation so the prompt's KV lands in the
        host cache (the prefill-time async store), then wait — bounded
        — for the store to become matchable. Returns an error string,
        or "" on success. Executor-thread only."""
        timeout = self._handoff_timeout()
        try:
            req = GenRequest(
                prompt_ids=list(prompt_ids), max_tokens=1,
                temperature=0.0,
            )
            self.engine.generate(req, timeout=timeout)
        except (TimeoutError, ValueError) as e:
            return f"prefill for export failed: {e}"
        cache = self.engine.host_kv_cache
        if cache is None:
            return "host KV cache disabled mid-prefill"
        deadline = time.time() + timeout
        while (
            cache.peek_prefix_len(prompt_ids) < want_tokens
            and time.time() < deadline
        ):
            time.sleep(0.01)
        return ""

    async def kv_import(self, request: web.Request) -> web.Response:
        """Land wire frames (a prefill replica's push, or a relay) in
        this engine's host cache through the kv stager — decode slots
        never stall on the insert."""
        eng = self.engine
        cache = eng.host_kv_cache
        if cache is None:
            return _error(404, "engine has no host KV cache")
        from gpustack_tpu.engine.kv_transfer import (
            decode_stream,
            prepare_import,
        )

        raw = await request.read()
        loop = asyncio.get_running_loop()

        def convert():
            frames = decode_stream(raw)
            return prepare_import(cache, frames)

        try:
            tokens, prepared, bytes_in = await loop.run_in_executor(
                None, convert
            )
        except ValueError as e:
            eng.kv_handoff.failures += 1
            return _error(400, str(e))
        try:
            # the stager SUBMIT itself can block (two-slot backpressure
            # while an upload lands) — keep it off the event loop, or
            # every SSE stream and health probe on this engine stalls
            fut = await loop.run_in_executor(
                None, eng.kv_import_prepared, tokens, prepared
            )
            attached = await asyncio.wait_for(
                asyncio.wrap_future(fut), self._handoff_timeout()
            )
        except asyncio.TimeoutError:
            eng.kv_handoff.failures += 1
            return _error(
                503,
                "kv import did not land within "
                f"{self._handoff_timeout()}s (stager busy); retry",
            )
        eng.kv_handoff.bytes_in += bytes_in
        return web.json_response({
            "blocks_attached": attached,
            "tokens": len(tokens),
            "bytes": bytes_in,
        })

    async def kv_summary(self, request: web.Request) -> web.Response:
        """The cluster KV directory's scrape: fold the server-reported
        fleet sharing counts into local eviction economics, then return
        this replica's bounded prefix-key summary (conversation-hash →
        resident block depth + deepest RAM chain key) re-checked
        against BOTH cache tiers right now.

        Body (all optional): ``{"sharing": {hash: replica_count},
        "max_keys": n}``. One round-trip carries both directions."""
        eng = self.engine
        cache = eng.host_kv_cache
        conv = getattr(eng, "kv_conv", None)
        if cache is None or conv is None:
            return _error(404, "engine has no host KV cache")
        try:
            body = await request.json() if request.can_read_body else {}
        except json.JSONDecodeError:
            return _error(400, "invalid JSON body")
        sharing = body.get("sharing") or {}
        if not isinstance(sharing, dict):
            return _error(400, "'sharing' must be an object")
        from gpustack_tpu.engine.kv_fabric import DEFAULT_SUMMARY_KEYS

        try:
            max_keys = int(body.get("max_keys") or DEFAULT_SUMMARY_KEYS)
        except (TypeError, ValueError):
            return _error(400, "'max_keys' must be an integer")
        loop = asyncio.get_running_loop()

        def scrape():
            boosted = conv.apply_sharing(cache, sharing)
            summary = conv.summary(cache, max_keys=max(1, max_keys))
            summary["sharing_boosted"] = boosted
            return summary

        return web.json_response(
            await loop.run_in_executor(None, scrape)
        )

    async def kv_pull(self, request: web.Request) -> web.Response:
        """Background prefetch trigger (the fleet fabric's low-priority
        warm-ahead): pull a conversation's block chain from a peer
        replica by its directory-advertised tail chain key. Returns 202
        immediately — the pull runs as a background task so the caller
        (the server's prefetcher) never blocks on transfer time, and a
        dead/slow source degrades to "stayed cold", counted."""
        eng = self.engine
        cache = eng.host_kv_cache
        if cache is None:
            return _error(404, "engine has no host KV cache")
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _error(400, "invalid JSON body")
        source = str(body.get("source") or "")
        tail_key = str(body.get("tail_key") or "")
        if not source or not tail_key:
            return _error(400, "missing 'source' or 'tail_key'")
        auth = str(body.get("auth") or "")
        task = asyncio.get_running_loop().create_task(
            self._kv_pull_chain(source, auth, tail_key)
        )
        self._kv_pulls.add(task)
        task.add_done_callback(self._kv_pulls.discard)
        return web.json_response({"accepted": True}, status=202)

    async def _kv_pull_chain(
        self, source: str, auth: str, tail_key: str
    ) -> None:
        """The prefetch pull itself: stream the peer's chain export,
        land it through the stager. Failures are counted + logged,
        never raised — prefetch is advisory."""
        import aiohttp

        eng = self.engine
        cache = eng.host_kv_cache
        from gpustack_tpu.engine.kv_transfer import (
            FrameDecoder,
            prepare_import,
        )

        timeout = self._handoff_timeout()
        t0 = time.perf_counter()
        loop = asyncio.get_running_loop()
        try:
            if self._kv_session is None or self._kv_session.closed:
                self._kv_session = aiohttp.ClientSession()
            headers = {"Authorization": auth} if auth else {}
            decoder = FrameDecoder()
            frames: list = []
            async with self._kv_session.post(
                source,
                json={"tail_key": tail_key},
                headers=headers,
                timeout=aiohttp.ClientTimeout(total=timeout),
            ) as resp:
                if resp.status != 200:
                    raise RuntimeError(f"peer answered HTTP {resp.status}")
                async for chunk in resp.content.iter_any():
                    frames.extend(decoder.feed(chunk))
            if not frames:
                raise RuntimeError("peer exported no blocks")
            tokens, prepared, bytes_in = await loop.run_in_executor(
                None, prepare_import, cache, frames
            )
            fut = await loop.run_in_executor(
                None, eng.kv_import_prepared, tokens, prepared
            )
            blocks = await asyncio.wait_for(
                asyncio.wrap_future(fut),
                max(0.5, timeout - (time.perf_counter() - t0)),
            )
            eng.kv_handoff.bytes_in += bytes_in
            self.prefetch_ok += 1
            logger.info(
                "kv prefetch from %s landed %d block(s) (%d bytes)",
                source, blocks, bytes_in,
            )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — advisory: stay cold
            self.prefetch_failed += 1
            logger.warning(
                "kv prefetch from %s failed (replica stays cold): %s",
                source, str(e) or type(e).__name__,
            )

    async def _kv_prefetch(
        self, request: web.Request, source: str, prompt_ids
    ) -> None:
        """Pull the prompt's radix prefix blocks from a peer replica
        before submitting the generation — the decode half of the
        disaggregated handoff. Never fails the request: a dead peer, a
        truncated stream or a slow transfer degrades to a cold (or
        partial-prefix) prefill, with the failure counted and traced.
        Complete frames that arrived before a mid-stream death are
        still imported — a radix cache can always use the intact
        prefix."""
        import aiohttp

        eng = self.engine
        cache = eng.host_kv_cache
        stats = eng.kv_handoff
        bt = cache.block_tokens
        want_tokens = (len(prompt_ids) - 1) // bt * bt
        if want_tokens <= 0:
            return
        loop = asyncio.get_running_loop()
        have = await loop.run_in_executor(
            None, cache.prefix_keys, prompt_ids
        )
        if len(have) * bt >= want_tokens:
            return  # the full run is already local
        from gpustack_tpu.engine.kv_transfer import (
            FrameDecoder,
            prepare_import,
        )

        trace = request.get("trace")
        timeout = self._handoff_timeout()
        t0 = time.perf_counter()
        stats.pulls += 1
        frames: list = []
        failed = ""
        try:
            if self._kv_session is None or self._kv_session.closed:
                self._kv_session = aiohttp.ClientSession()
            headers = {}
            auth = request.headers.get("X-GPUStack-KV-Source-Auth", "")
            if auth:
                headers["Authorization"] = auth
            decoder = FrameDecoder()
            async with self._kv_session.post(
                source,
                json={
                    "prompt_ids": [int(t) for t in prompt_ids],
                    "have": have,
                    "prefill": True,
                },
                headers=headers,
                timeout=aiohttp.ClientTimeout(total=timeout),
            ) as resp:
                if resp.status != 200:
                    raise RuntimeError(f"peer answered HTTP {resp.status}")
                async for chunk in resp.content.iter_any():
                    frames.extend(decoder.feed(chunk))
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — any peer fault → cold
            failed = str(e) or type(e).__name__
        imported = 0
        bytes_in = 0
        if frames:
            try:
                tokens, prepared, bytes_in = await loop.run_in_executor(
                    None, prepare_import, cache, frames
                )
                # the stager submit can block on its two-slot bound:
                # off the event loop, like the convert above
                fut = await loop.run_in_executor(
                    None, eng.kv_import_prepared, tokens, prepared
                )
                imported = await asyncio.wait_for(
                    asyncio.wrap_future(fut),
                    max(0.5, timeout - (time.perf_counter() - t0)),
                )
                stats.bytes_in += bytes_in
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                failed = failed or (str(e) or type(e).__name__)
        dur = time.perf_counter() - t0
        stats.seconds.observe(dur)
        if failed:
            stats.failures += 1
            logger.warning(
                "kv handoff from %s failed after %.3fs (%d block(s) "
                "landed; continuing cold): %s",
                source, dur, imported, failed,
            )
        if trace is not None:
            # the engine hop's kv_handoff phase: transfer + import wait
            trace.add_phase("kv_handoff", dur)
            attrs = dict(source=source, blocks=imported, bytes=bytes_in)
            if failed:
                attrs["failed"] = failed
            trace.event("kv_handoff", **attrs)

    async def completions(self, request: web.Request) -> web.StreamResponse:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _error(400, "invalid JSON body")
        prompt = body.get("prompt")
        if prompt is None:
            return _error(400, "missing 'prompt'")
        if isinstance(prompt, list):
            prompt = prompt[0] if prompt else ""
        prompt_ids = self.engine.tokenizer.encode(str(prompt))
        return await self._run(request, body, prompt_ids, chat=False)

    async def chat_completions(self, request: web.Request) -> web.StreamResponse:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _error(400, "invalid JSON body")
        messages = body.get("messages")
        if not isinstance(messages, list) or not messages:
            return _error(400, "missing 'messages'")
        if getattr(self.engine, "kv_conv", None) is not None:
            # same rolling message-prefix hashes the proxy's affinity
            # map and the cluster KV directory key on — recorded at
            # finish (with the generated ids) via _record_conv
            from gpustack_tpu.server.resilience import conversation_chain

            request["conv_chain"] = conversation_chain(
                self.model_name, messages
            )

        tools = body.get("tools") or []
        tool_choice = body.get("tool_choice", "auto")
        tools_active = bool(tools) and tool_choice != "none"
        msgs = list(messages)

        has_images = any(
            isinstance(m.get("content"), list)
            and any(
                isinstance(p, dict) and p.get("type") == "image_url"
                for p in m["content"]
            )
            for m in msgs
        )
        vision = getattr(self.engine, "vision", None)
        if has_images and vision is None:
            return _error(
                400,
                f"model {self.model_name!r} does not accept image input",
            )

        # tool_choice forcing rides an extra system instruction so it
        # works uniformly across template-native and fallback rendering
        if tools_active:
            forced = forced_function(tool_choice)
            if forced:
                msgs.append({
                    "role": "system",
                    "content": f'You MUST call the function "{forced}".',
                })
            elif tool_choice == "required":
                msgs.append({
                    "role": "system",
                    "content": "You MUST call one of the available functions.",
                })

        rf = body.get("response_format") or {}
        json_mode = isinstance(rf, dict) and rf.get("type") in (
            "json_object", "json_schema"
        )
        schema = None
        if json_mode:
            instruction = JSON_MODE_INSTRUCTION
            schema = (rf.get("json_schema") or {}).get("schema")
            if schema:
                # a broken schema is a client error: reject now instead
                # of burning two generations that can only fail
                import jsonschema

                if not isinstance(schema, (dict, bool)):
                    return _error(
                        400, "json_schema.schema must be an object"
                    )
                try:
                    jsonschema.validators.validator_for(
                        schema
                    ).check_schema(schema)
                except jsonschema.SchemaError as e:
                    return _error(400, f"invalid json_schema: {e.message}")
                instruction += (
                    " The object must conform to this JSON schema: "
                    + json.dumps(schema)
                )
            msgs.append({"role": "system", "content": instruction})

        def reencode_with_feedback(attempt_text: str, error: str):
            """Retry prompt for schema-validation failure: the failed
            attempt + the validator's error, re-templated."""
            retry_msgs = msgs + [
                {"role": "assistant", "content": attempt_text},
                {
                    "role": "system",
                    "content": (
                        "Your JSON failed schema validation: "
                        f"{error[:400]}. Respond again with ONLY a "
                        "corrected JSON object."
                    ),
                },
            ]
            return self.engine.tokenizer.apply_chat_template(retry_msgs)

        embeds_override = None
        if has_images:
            from gpustack_tpu.engine.tokenizer import _inject_tools_fallback
            from gpustack_tpu.models.vlm import build_mm_prompt

            # the multimodal template can't take the tools= kwarg, so the
            # function schemas ride the same system-block fallback the
            # text path uses for non-template tokenizers
            if tools_active:
                msgs = _inject_tools_fallback(msgs, tools)
            loop = asyncio.get_running_loop()
            try:
                # PIL decode + (first-call) jit compile + ViT forward are
                # seconds of work — off the event loop, like TTS synthesis
                prompt_ids, embeds, mask = await loop.run_in_executor(
                    None,
                    lambda: build_mm_prompt(
                        self.engine.tokenizer, msgs, vision
                    ),
                )
            except ValueError as e:
                return _error(400, str(e))
            embeds_override = (embeds, mask)
        else:
            try:
                prompt_ids = self.engine.tokenizer.apply_chat_template(
                    msgs, tools=tools if tools_active else None
                )
            except Exception as e:  # tokenizer/template errors: client's
                return _error(400, f"chat template failed: {e}")
        return await self._run(
            request, body, prompt_ids, chat=True,
            tools_active=tools_active, json_mode=json_mode,
            embeds_override=embeds_override,
            schema=schema, reencode=reencode_with_feedback,
        )

    async def rerank(self, request: web.Request) -> web.Response:
        """Jina/Cohere-style rerank: query + documents → ranked scores.

        v1 scoring is embedding cosine similarity (bi-encoder) over the
        served model's pooled representations — the reference exposes
        rerank through its engine registry (gateway/utils.py
        openai_model_prefixes); a cross-encoder head is the planned
        upgrade for dedicated reranker checkpoints.
        """
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _error(400, "invalid JSON body")
        query = body.get("query")
        documents = body.get("documents")
        if not isinstance(query, str) or not query:
            return _error(400, "missing 'query'")
        if not isinstance(documents, list) or not documents or not all(
            isinstance(d, str) for d in documents
        ):
            return _error(400, "'documents' must be non-empty strings")
        try:
            top_n = int(body.get("top_n") or len(documents))
        except (TypeError, ValueError):
            return _error(400, "'top_n' must be an integer")
        if top_n <= 0:
            return _error(400, "'top_n' must be positive")
        loop = asyncio.get_running_loop()

        def encode_and_embed():
            # tokenization stays off the event loop too: hundreds of
            # long documents would stall every other request
            batch = [self.engine.tokenizer.encode(query)] + [
                self.engine.tokenizer.encode(d) for d in documents
            ]
            if any(not ids for ids in batch):
                raise ValueError(
                    "query/documents must tokenize non-empty"
                )
            return batch, self.engine.embed(batch)

        try:
            batch, vecs = await loop.run_in_executor(
                None, encode_and_embed
            )
        except ValueError as e:
            return _error(400, str(e))
        import numpy as _np

        q = _np.asarray(vecs[0])
        docs = _np.asarray(vecs[1:])
        # embed() l2-normalizes, so dot == cosine
        scores = docs @ q
        order = _np.argsort(-scores)[:top_n]
        return web.json_response(
            {
                "model": self.model_name,
                "object": "rerank",
                "results": [
                    {
                        "index": int(i),
                        "relevance_score": float(scores[i]),
                        "document": {"text": documents[int(i)]},
                    }
                    for i in order
                ],
                "usage": {
                    "total_tokens": sum(len(ids) for ids in batch)
                },
            }
        )

    async def embeddings(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _error(400, "invalid JSON body")
        inputs = body.get("input")
        if inputs is None:
            return _error(400, "missing 'input'")
        if isinstance(inputs, str):
            inputs = [inputs]
        # OpenAI also allows a bare token array / list of token arrays
        if inputs and all(isinstance(x, int) for x in inputs):
            inputs = [inputs]
        if not isinstance(inputs, list) or not inputs:
            return _error(400, "'input' must be a string or list")
        batch_ids = []
        total_tokens = 0
        for item in inputs:
            if isinstance(item, str):
                ids = self.engine.tokenizer.encode(item)
            elif isinstance(item, list) and all(
                isinstance(t, int) for t in item
            ):
                ids = list(item)           # pre-tokenized input
            else:
                return _error(
                    400,
                    "'input' items must be strings or token-id arrays",
                )
            if not ids:
                return _error(400, "'input' items must be non-empty")
            batch_ids.append(ids)
            total_tokens += len(ids)
        dimensions = body.get("dimensions")
        if dimensions is not None:
            if isinstance(dimensions, bool) or not isinstance(
                dimensions, int
            ):
                return _error(400, "'dimensions' must be an integer")
            if dimensions < 1:
                return _error(400, "'dimensions' must be positive")
        encoding_format = body.get("encoding_format", "float")
        if encoding_format not in ("float", "base64"):
            return _error(
                400, "'encoding_format' must be float or base64"
            )
        loop = asyncio.get_running_loop()
        try:
            vecs = await loop.run_in_executor(
                None, self.engine.embed, batch_ids
            )
        except ValueError as e:
            return _error(400, str(e))
        if dimensions is not None:
            if dimensions > len(vecs[0]):
                return _error(
                    400,
                    f"'dimensions' {dimensions} exceeds the model's "
                    f"embedding size {len(vecs[0])}",
                )
            # matryoshka-style truncation + renormalize (OpenAI
            # 'dimensions' semantics; vLLM does the same)
            import math

            def shrink(vec):
                cut = vec[:dimensions]
                norm = math.sqrt(sum(x * x for x in cut)) or 1.0
                return [x / norm for x in cut]

            vecs = [shrink(v) for v in vecs]

        def render(vec):
            if encoding_format == "base64":
                import base64
                import struct

                return base64.b64encode(
                    struct.pack(f"<{len(vec)}f", *vec)
                ).decode()
            return vec

        data = [
            {"object": "embedding", "index": i, "embedding": render(vec)}
            for i, vec in enumerate(vecs)
        ]
        return web.json_response(
            {
                "object": "list",
                "data": data,
                "model": self.model_name,
                "usage": {
                    "prompt_tokens": total_tokens,
                    "total_tokens": total_tokens,
                },
            }
        )

    # ---- core -----------------------------------------------------------

    def _gen_request(
        self, body: Dict[str, Any], prompt_ids, *,
        chat: bool = True, json_mode: bool = False,
    ) -> GenRequest:
        stop = body.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        stop_texts = tuple(str(s) for s in stop if s)
        max_tokens = int(
            body.get("max_tokens") or body.get("max_completion_tokens") or 128
        )
        if max_tokens < 1:
            raise ValueError(f"max_tokens must be >= 1, got {max_tokens}")
        seed = body.get("seed")
        if seed is not None:
            seed = int(seed)
        logit_bias = body.get("logit_bias")
        if logit_bias is not None:
            if not isinstance(logit_bias, dict):
                raise ValueError("logit_bias must be {token_id: bias}")
            logit_bias = {
                int(k): float(v) for k, v in logit_bias.items()
            }
        # chat: logprobs is a bool + top_logprobs count; legacy
        # completions: logprobs is the alternatives count itself
        if chat:
            want_logprobs = bool(body.get("logprobs"))
            top_lp = int(body.get("top_logprobs") or 0)
        else:
            raw = body.get("logprobs")
            want_logprobs = raw is not None and raw is not False
            top_lp = int(raw or 0) if not isinstance(raw, bool) else 0
        if top_lp < 0 or top_lp > MAX_TOP_LOGPROBS:
            raise ValueError(
                f"top_logprobs must be 0..{MAX_TOP_LOGPROBS}, got {top_lp}"
            )
        if body.get("temperature") is None:
            # A speculative deployment is greedy-only; the OpenAI default
            # of 1.0 would reject every request that simply leaves
            # temperature unset. Explicitly-set temperatures still reach
            # the engine and get its clear rejection.
            temperature = (
                0.0 if getattr(self.engine, "speculative", "") else 1.0
            )
        else:
            temperature = float(body.get("temperature"))
        return GenRequest(
            prompt_ids=prompt_ids,
            max_tokens=max_tokens,
            temperature=temperature,
            top_k=int(body.get("top_k") or 0),
            top_p=float(body.get("top_p") or 1.0),
            seed=seed,
            logit_bias=logit_bias,
            stop_texts=stop_texts,
            logprobs=want_logprobs,
            top_logprobs=top_lp,
            json_mode=json_mode,
            request_id=str(uuid.uuid4()),
        )

    def _make_gens(
        self, body: Dict[str, Any], prompt_ids, chat: bool, json_mode: bool,
        embeds_override=None,
    ) -> List[GenRequest]:
        n = int(body.get("n") or 1)
        if n < 1 or n > MAX_N:
            raise ValueError(f"n must be 1..{MAX_N}, got {n}")
        gens = []
        for i in range(n):
            gen = self._gen_request(
                body, list(prompt_ids), chat=chat, json_mode=json_mode
            )
            gen.embeds_override = embeds_override
            if gen.seed is not None and i > 0:
                # per-choice seeds must differ or every choice is the
                # same sequence; derive deterministically from the base
                gen.seed = gen.seed + i
            gens.append(gen)
        return gens

    def _finish_reason(self, gen: GenRequest, had_tool_calls: bool) -> str:
        return "tool_calls" if had_tool_calls else gen.finish_reason

    async def _validate_schema(
        self, body, gen: GenRequest, schema, reencode, loop,
        remaining_s: float, allow_retry: bool,
    ):
        """Validate a completed generation against the request's JSON
        schema; one guided retry on failure (the failed attempt + the
        validator's error re-enter the prompt). Returns (winning
        GenRequest, ``passed``/``failed: ...`` verdict, retry-or-None —
        the retry rides back for usage accounting).

        Divergence from the reference's vLLM backends (which enforce
        schemas with token-level grammars): this is validate-and-retry —
        the verdict is ALWAYS reported on the non-streaming choice so a
        failure can't pass silently (streams skip validation and say
        so)."""
        import jsonschema

        def verdict_of(text):
            try:
                jsonschema.validate(json.loads(text), schema)
                return "passed"
            except json.JSONDecodeError as e:
                return f"failed: not valid JSON ({e})"
            except jsonschema.ValidationError as e:
                return f"failed: {e.message}"

        verdict = verdict_of(gen.output_text)
        if verdict == "passed" or not allow_retry or remaining_s < 30:
            return gen, verdict, None
        try:
            # reencode runs a chat template; some family templates
            # reject assistant→system sequences — a failed retry
            # RENDERING must degrade to the original verdict, not a 500
            retry_ids = reencode(gen.output_text, verdict)
            retry = self._gen_request(
                body, retry_ids, chat=True, json_mode=True
            )
            self.engine.submit(retry)
            await loop.run_in_executor(
                None, retry.done.wait, remaining_s
            )
        except Exception as e:
            logger.warning("schema retry not possible: %s", e)
            return gen, verdict, None
        if not retry.done.is_set():
            # the orphan finishes at max_tokens on its own; bounded
            logger.warning("schema retry timed out; keeping original")
            return gen, verdict, retry
        return retry, verdict_of(retry.output_text), retry

    async def _run(
        self, request: web.Request, body: Dict[str, Any], prompt_ids,
        chat: bool, tools_active: bool = False, json_mode: bool = False,
        embeds_override=None, schema=None, reencode=None,
    ) -> web.StreamResponse:
        try:
            gens = self._make_gens(
                body, prompt_ids, chat, json_mode, embeds_override
            )
        except (TypeError, ValueError) as e:
            return _error(400, f"bad sampling params: {e}")
        # disaggregated handoff: the proxy names the peer replica that
        # already holds this conversation's radix prefix (or the
        # prefill-role replica that should compute it) — pull its
        # blocks before admission so _start_request prefix-hits them
        source = request.headers.get("X-GPUStack-KV-Source", "")
        if source and self.engine.host_kv_cache is not None and (
            embeds_override is None
        ):
            await self._kv_prefetch(request, source, prompt_ids)
        if body.get("stream"):
            return await self._stream(
                request, gens, chat, tools_active,
                schema_active=schema is not None,
            )
        loop = asyncio.get_running_loop()
        try:
            for gen in gens:
                self.engine.submit(gen)
        except ValueError as e:
            return _error(400, str(e))
        deadline = loop.time() + 600
        for gen in gens:
            remaining = max(0.1, deadline - loop.time())
            await loop.run_in_executor(None, gen.done.wait, remaining)
            if not gen.done.is_set():
                return _error(504, "generation timed out")
        self._trace_kv(request, gens)
        self._record_conv(request, gens)
        rid = f"{'chatcmpl' if chat else 'cmpl'}-{gens[0].request_id}"
        # usage is billed on what the CLIENT sent + everything actually
        # generated (incl. discarded schema-retry attempts) — a swapped
        # gen must not rewrite prompt_tokens or vanish output tokens
        usage = _usage(gens)
        verdicts: List[Optional[str]] = [None] * len(gens)
        if chat and schema is not None and reencode is not None:
            for i in range(len(gens)):
                # a tool-call turn is not a schema violation: the JSON
                # contract applies to the final content answer, not to
                # tool-call markup — skip validation entirely
                if tools_active and parse_tool_calls(
                    gens[i].output_text
                )[1]:
                    continue
                # multimodal retries would drop the images (the retry
                # prompt re-templates without the vision path), and a
                # length-truncated attempt would only truncate again:
                # validate only, never retry, in those cases
                allow_retry = (
                    len(gens) == 1
                    and embeds_override is None
                    and gens[i].finish_reason != "length"
                )
                gens[i], verdicts[i], retry = (
                    await self._validate_schema(
                        body, gens[i], schema, reencode, loop,
                        max(0.0, deadline - loop.time()), allow_retry,
                    )
                )
                if retry is not None:
                    usage["completion_tokens"] += len(retry.output_ids)
                    usage["total_tokens"] += len(retry.output_ids)
        choices = []
        for i, gen in enumerate(gens):
            text = gen.output_text
            if chat:
                tool_calls: List[Dict[str, Any]] = []
                content: Optional[str] = text
                if tools_active:
                    content, tool_calls = parse_tool_calls(text)
                    content = content or None
                message: Dict[str, Any] = {
                    "role": "assistant", "content": content,
                }
                if tool_calls:
                    message["tool_calls"] = tool_calls
                choice = {
                    "index": i,
                    "message": message,
                    "finish_reason": self._finish_reason(
                        gen, bool(tool_calls)
                    ),
                }
                if gen.logprobs:
                    choice["logprobs"] = _chat_logprobs(
                        gen, self.engine.tokenizer
                    )
                if verdicts[i] is not None:
                    # always reported: schema conformance is validated,
                    # not grammar-guaranteed (see _validate_schema)
                    choice["x_schema_validation"] = verdicts[i]
            else:
                choice = {
                    "index": i,
                    "text": text,
                    "finish_reason": gen.finish_reason,
                }
                if gen.logprobs:
                    choice["logprobs"] = _completion_logprobs(
                        gen, self.engine.tokenizer, gen.top_logprobs
                    )
            choices.append(choice)
        payload = {
            "id": rid,
            "object": "chat.completion" if chat else "text_completion",
            "created": int(time.time()),
            "model": self.model_name,
            "choices": choices,
            "usage": usage,
        }
        if gens[0].seed is not None:
            payload["system_fingerprint"] = SYSTEM_FINGERPRINT
        return web.json_response(payload)

    async def _stream(
        self, request: web.Request, gens: List[GenRequest], chat: bool,
        tools_active: bool = False, schema_active: bool = False,
    ) -> web.StreamResponse:
        loop = asyncio.get_running_loop()
        rid = f"{'chatcmpl' if chat else 'cmpl'}-{gens[0].request_id}"
        obj = "chat.completion.chunk" if chat else "text_completion"
        for gen in gens:
            gen.stream = queue.Queue()
        # submit before committing to a 200/SSE response: rejections must
        # surface as real HTTP errors, not in-band stream events
        try:
            for gen in gens:
                self.engine.submit(gen)
        except ValueError as e:
            return _error(400, str(e))
        resp = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
            }
        )
        await resp.prepare(request)

        def chunk_for(index: int, delta_or_text, finish=None) -> dict:
            body_ = (
                {"delta": delta_or_text} if chat
                else {"text": delta_or_text}
            )
            payload = {
                "id": rid, "object": obj, "created": int(time.time()),
                "model": self.model_name,
                "choices": [{"index": index, **body_,
                             "finish_reason": finish}],
            }
            if gens[0].seed is not None:
                payload["system_fingerprint"] = SYSTEM_FINGERPRINT
            return payload

        async def write(payload: dict) -> None:
            await resp.write(f"data: {json.dumps(payload)}\n\n".encode())

        if chat:
            for i in range(len(gens)):
                await write(chunk_for(
                    i, {"role": "assistant", "content": ""}
                ))

        # merge the per-choice token queues into one ordered SSE stream
        merged: asyncio.Queue = asyncio.Queue()
        _empty = object()

        def _bounded_get(gen: GenRequest):
            # a plain .get() would pin its executor thread until the
            # engine produces a token — uncancellable after a client
            # disconnect; bound it so threads notice the abort promptly
            try:
                return gen.stream.get(timeout=0.5)
            except queue.Empty:
                return _empty

        async def pump(i: int, gen: GenRequest) -> None:
            while True:
                item = await loop.run_in_executor(None, _bounded_get, gen)
                if item is _empty:
                    if gen.aborted.is_set():
                        return
                    continue
                await merged.put((i, item))
                if item is None:
                    return

        pumps = [
            asyncio.ensure_future(pump(i, g)) for i, g in enumerate(gens)
        ]
        holdbacks = [
            ToolCallHoldback() if (chat and tools_active) else None
            for _ in gens
        ]
        try:
            open_streams = len(gens)
            while open_streams:
                i, item = await merged.get()
                if item is None:
                    open_streams -= 1
                    continue
                _tok, piece = item
                hb = holdbacks[i]
                if hb is not None:
                    piece = hb.filter(piece)
                if piece:
                    await write(chunk_for(
                        i, {"content": piece} if chat else piece
                    ))
        finally:
            # On a client disconnect resp.write raises mid-loop; abort
            # the in-flight generations so the engine frees the slots at
            # its next delivery instead of decoding to max_tokens for
            # nobody. (Completed requests are already finished — setting
            # the flag then is a no-op.) The bounded queue.get above lets
            # the executor threads drain within ~0.5 s.
            for gen in gens:
                gen.abort()
            for p in pumps:
                p.cancel()

        for i, gen in enumerate(gens):
            had_calls = False
            hb = holdbacks[i]
            if hb is not None:
                if hb.in_call:
                    # parse only the HELD region: the text before the
                    # block already streamed, so re-parsing the full
                    # output would duplicate it. Unparseable blocks and
                    # content after the call come back as held_content —
                    # nothing the model produced is ever dropped.
                    held_content, calls = parse_tool_calls(hb.pending)
                    if calls:
                        had_calls = True
                        # whole-call deltas: one chunk per call carrying
                        # the full name+arguments (incremental argument
                        # streaming is a non-goal; clients accumulate by
                        # index)
                        await write(chunk_for(i, {
                            "tool_calls": [
                                {
                                    "index": ci,
                                    "id": c["id"],
                                    "type": "function",
                                    "function": c["function"],
                                }
                                for ci, c in enumerate(calls)
                            ]
                        }))
                    if held_content:
                        await write(chunk_for(i, {"content": held_content}))
                else:
                    tail = hb.flush()
                    if tail:
                        await write(chunk_for(i, {"content": tail}))
            final = chunk_for(
                i, {} if chat else "",
                self._finish_reason(gen, had_calls),
            )
            if schema_active:
                # streams can't be validated retro-actively; say so
                # instead of implying conformance
                final["choices"][0]["x_schema_validation"] = (
                    "skipped (stream)"
                )
            if gen.logprobs:
                # streaming logprobs ride the final chunk (per-piece
                # logprobs would need token-aligned streaming)
                final["choices"][0]["logprobs"] = (
                    _chat_logprobs(gen, self.engine.tokenizer) if chat
                    else _completion_logprobs(
                        gen, self.engine.tokenizer, gen.top_logprobs
                    )
                )
            if i == len(gens) - 1:
                final["usage"] = _usage(gens)
            await write(final)
        await resp.write(b"data: [DONE]\n\n")
        self._trace_kv(request, gens)
        self._record_conv(request, gens)
        return resp

    def _record_conv(
        self, request: web.Request, gens: List[GenRequest]
    ) -> None:
        """Feed the conversation index (engine/kv_fabric.ConvIndex) at
        chat finish: the message-prefix hash chain (stashed on the
        request by chat_completions) plus the token sequence whose KV
        blocks now live in the cache (prompt + generated — what turn
        N+1 will prefix-match)."""
        chain = request.get("conv_chain")
        conv = getattr(self.engine, "kv_conv", None)
        if not chain or conv is None:
            return
        g = gens[0]
        try:
            conv.record(chain, list(g.prompt_ids) + list(g.output_ids))
        except Exception:  # noqa: BLE001 — accounting must never 500
            logger.exception("conversation-index record failed")

    @staticmethod
    def _trace_kv(request: web.Request, gens: List[GenRequest]) -> None:
        """Attach host-KV-cache phases to this hop's trace: the
        ``kv_upload`` span (host→device re-materialization of matched
        prefix blocks, measured by the engine scheduler) plus a
        prefix-hit event carrying the reused-token count."""
        trace = request.get("trace")
        if trace is None:
            return
        upload_s = sum(g.kv_upload_s for g in gens)
        if upload_s > 0:
            trace.add_phase("kv_upload", upload_s)
        reused = sum(g.prefix_tokens_reused for g in gens)
        if reused:
            trace.event("kv_prefix_hit", tokens_reused=reused)


def _error(status: int, message: str) -> web.Response:
    return web.json_response(
        {"error": {"message": message, "type": "invalid_request_error"}},
        status=status,
    )


# ---------------------------------------------------------------------------
# Process entrypoint (what the worker's serve manager launches)
# ---------------------------------------------------------------------------


def build_engine_from_args(args) -> LLMEngine:
    # Hermetic-test hook: the serve manager sets GPUSTACK_TPU_PLATFORM=cpu
    # so engine subprocesses run on the CPU backend. jax.config wins over
    # env vars even against TPU-plugin sitecustomize overrides.
    forced = os.environ.get("GPUSTACK_TPU_PLATFORM")
    import jax

    if forced:
        jax.config.update("jax_platforms", forced)

    # Multi-host replica: rendezvous through the JAX distributed
    # coordinator (the serve manager sets these from the placement — the
    # TPU replacement for the reference's Ray bootstrap,
    # worker/backends/vllm.py:258-328). After initialize(), jax.devices()
    # spans every host of the slice and the mesh plan tiles all of them.
    coordinator = os.environ.get("GPUSTACK_TPU_COORDINATOR")
    if coordinator:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=int(
                os.environ.get("GPUSTACK_TPU_NUM_PROCESSES", "1")
            ),
            process_id=int(os.environ.get("GPUSTACK_TPU_PROCESS_ID", "0")),
        )

    from gpustack_tpu.models import init_params
    from gpustack_tpu.models.config import get_config, load_hf_config
    from gpustack_tpu.models.quant import quantize_params
    from gpustack_tpu.models.vlm import VLM_PRESETS, get_vlm_config
    from gpustack_tpu.parallel.mesh import MeshPlan, plan_mesh

    vlm_cfg = None
    if args.model_dir:
        from gpustack_tpu.engine.gguf import config_from_gguf
        from gpustack_tpu.engine.weights import checkpoint_source

        # shared precedence helper: config and weights always come from
        # the SAME checkpoint in a mixed directory
        kind, path = checkpoint_source(args.model_dir)
        if kind == "gguf":
            cfg = config_from_gguf(path, name=args.served_name or "")
        else:
            cfg = load_hf_config(args.model_dir)
    elif args.preset in VLM_PRESETS:
        # vision-language preset: the language half runs in the normal
        # engine; the tower+projector attach as engine.vision below
        vlm_cfg = get_vlm_config(args.preset)
        cfg = vlm_cfg.language
    else:
        cfg = get_config(args.preset)

    if args.mesh_plan:
        plan = MeshPlan.parse(args.mesh_plan)
    else:
        plan = plan_mesh(
            min(len(jax.devices()), args.num_devices or len(jax.devices())),
            cfg.num_kv_heads,
            cfg.num_experts,
        )

    from gpustack_tpu.engine.weights import load_or_init_params

    params = load_or_init_params(cfg, args.model_dir, seed=0)
    if getattr(args, "lora", None):
        # merge BEFORE quantization: deltas apply to bf16 base weights
        from gpustack_tpu.engine.weights import merge_lora_adapters

        params = merge_lora_adapters(cfg, params, args.lora)
    if args.quantization == "int8":
        params = quantize_params(params)

    draft_cfg = draft_params = None
    if args.speculative == "draft":
        source = getattr(args, "draft_source", "")
        if not source:
            raise ValueError("--speculative draft needs --draft-source")
        if os.path.isdir(source):
            draft_cfg = load_hf_config(source)
            draft_params = load_or_init_params(draft_cfg, source, seed=0)
        else:
            draft_cfg = get_config(source)
            draft_params = load_or_init_params(draft_cfg, None, seed=0)

    # the decode batch is dp-sharded, so the slot count must be a
    # multiple of the mesh's dp degree; round capacity UP rather than
    # crash in device_put when the auto-planner picks dp > max_slots
    # (e.g. a small --max-slots on a many-chip host)
    max_slots = args.max_slots
    if max_slots % plan.dp:
        rounded = (max_slots // plan.dp + 1) * plan.dp
        logger.warning(
            "max_slots=%d not divisible by mesh dp=%d; rounding up to %d",
            max_slots, plan.dp, rounded,
        )
        max_slots = rounded

    # dispatch-ahead depth: argv (per-model knob) > env (Config
    # engine_pipeline_depth — engine subprocesses inherit the worker's
    # environment) > built-in default 2
    pipeline_depth = getattr(args, "pipeline_depth", -1)
    if pipeline_depth is None or pipeline_depth < 0:
        pipeline_depth = int(
            os.environ.get("GPUSTACK_TPU_ENGINE_PIPELINE_DEPTH") or 2
        )

    engine = LLMEngine(
        cfg,
        params,
        model_dir=args.model_dir,
        max_slots=max_slots,
        max_seq_len=args.max_seq_len,
        plan=plan,
        speculative=args.speculative,
        spec_tokens=args.spec_tokens,
        draft_cfg=draft_cfg,
        draft_params=draft_params,
        host_kv_cache_mb=getattr(args, "host_kv_cache_mb", 0),
        kv_block_tokens=getattr(args, "kv_block_tokens", 0),
        kv_cache_int8=getattr(args, "kv_cache_int8", False),
        prefill_chunk=getattr(args, "prefill_chunk", 0),
        pipeline_depth=pipeline_depth,
        kv_role=getattr(args, "kv_role", ""),
        kv_spill_mb=getattr(args, "kv_spill_mb", 0),
        kv_spill_dir=getattr(args, "kv_spill_dir", ""),
    )
    if vlm_cfg is not None:
        from gpustack_tpu.models.vlm import VisionBundle, init_vision_params

        engine.vision = VisionBundle(
            vlm_cfg, init_vision_params(vlm_cfg, jax.random.key(1))
        )

    # Multi-host replica: multi-controller JAX is SPMD, so the leader
    # broadcasts every device op and followers replay it
    # (engine/multihost.py). Wired here, after the engine owns its
    # runner, so the engine itself stays topology-agnostic.
    n_procs = int(os.environ.get("GPUSTACK_TPU_NUM_PROCESSES", "1"))
    if n_procs > 1:
        if getattr(engine, "vision", None) is not None:
            # the vision encode runs leader-only and its spliced-prefill
            # op is not in the broadcast vocabulary — image requests on
            # a multi-host replica would kill the scheduling loop
            logger.warning(
                "vision tower disabled: VLM serving is single-host only"
            )
            engine.vision = None
        from gpustack_tpu.engine.multihost import (
            BroadcastingRunner,
            CommandLeader,
            FollowerLoop,
        )

        cmd_addr = os.environ["GPUSTACK_TPU_CMD_ADDRESS"]
        proc_id = int(os.environ.get("GPUSTACK_TPU_PROCESS_ID", "0"))
        if proc_id == 0:
            leader = CommandLeader(
                int(cmd_addr.rsplit(":", 1)[1]), n_procs - 1
            )
            engine.runner = BroadcastingRunner(engine.runner, leader)
        else:
            engine.follower_loop = FollowerLoop(
                engine.runner, cmd_addr, state=engine._state
            )
    return engine


def main(argv=None) -> None:
    p = argparse.ArgumentParser("gpustack-tpu engine API server")
    p.add_argument("--model-dir", default="")
    p.add_argument("--preset", default="llama3-8b")
    p.add_argument("--served-name", default="")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9000)
    p.add_argument("--max-slots", type=int, default=8)
    p.add_argument("--max-seq-len", type=int, default=2048)
    p.add_argument(
        "--prefill-chunk", type=int, default=0,
        help="chunked prefill: process prompts in chunks of this many "
        "tokens, interleaving decode between chunks (0 = off)",
    )
    p.add_argument(
        "--pipeline-depth", type=int, default=-1,
        help="decode-fetch pipeline depth (dispatch-ahead overlap): "
        "0 = serial reference mode, -1 = inherit "
        "GPUSTACK_TPU_ENGINE_PIPELINE_DEPTH (default 2) — "
        "docs/ENGINE_PIPELINE.md",
    )
    p.add_argument("--quantization", choices=["", "int8"], default="")
    p.add_argument(
        "--speculative", choices=["", "ngram", "draft"], default=""
    )
    p.add_argument("--spec-tokens", type=int, default=4)
    p.add_argument(
        "--draft-source", default="",
        help="draft model for speculative=draft: preset name or local "
        "checkpoint dir",
    )
    p.add_argument("--mesh-plan", default="", help="e.g. dp1xsp1xep1xtp4")
    p.add_argument("--num-devices", type=int, default=0)
    p.add_argument(
        "--host-kv-cache-mb", type=int, default=0,
        help="host-RAM block KV cache budget (extended-KV-cache role): "
        "finished sequences are cached block-granular and shared "
        "across requests via radix prefix matching",
    )
    p.add_argument(
        "--kv-block-tokens", type=int, default=0,
        help="host KV cache block granularity in tokens (0 = default "
        "256); smaller blocks match shorter shared prefixes at more "
        "per-block overhead",
    )
    p.add_argument(
        "--kv-role", choices=["", "prefill", "decode"], default="",
        help="disaggregated-serving role tag (ModelSpec "
        "prefill_replicas/decode_replicas): prefill replicas compute "
        "prompt KV and export it at POST /kv/export; decode replicas "
        "pull handed-off blocks and own the token loop. Empty = "
        "colocated (both roles)",
    )
    p.add_argument(
        "--kv-spill-mb", type=int, default=0,
        help="disk spill tier budget under the host KV cache (MiB): "
        "blocks evicted from host RAM spill to one content-addressed "
        "file each and fault back on a later prefix hit; 0 disables",
    )
    p.add_argument(
        "--kv-spill-dir", default="",
        help="spill-tier directory (default: a per-process tmp dir; "
        "reusing a directory across restarts keeps the tier warm)",
    )
    p.add_argument(
        "--kv-cache-int8", action="store_true",
        help="quantize host-tier KV blocks to int8 (per-block scales, "
        "dequantized on upload) — ~2x cache capacity per byte",
    )
    p.add_argument(
        "--lora", action="append", default=[],
        help="PEFT LoRA adapter dir merged into the base weights "
        "(repeatable)",
    )
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    engine = build_engine_from_args(args)
    follower = getattr(engine, "follower_loop", None)
    if follower is not None:
        # follower host of a multi-host replica: no scheduling loop —
        # replay the leader's op stream; the HTTP surface stays up for
        # liveness but receives no inference traffic (the server proxies
        # to the leader's port only)
        follower.start()
    else:
        engine.start()
    server = OpenAIServer(engine, model_name=args.served_name or None)

    async def on_startup(app):
        async def watchdog():
            # a dead scheduling loop is terminal for this process: exit
            # so the serve manager's process-exit watch drives the
            # crash/restart state machine (a 503 healthz alone is only
            # checked during startup)
            while True:
                await asyncio.sleep(2.0)
                if getattr(engine, "_fatal", ""):
                    logging.getLogger(__name__).error(
                        "terminating: %s", engine._fatal
                    )
                    os._exit(13)

        app["engine_watchdog"] = asyncio.create_task(watchdog())

    server.app.on_startup.append(on_startup)
    web.run_app(server.app, host=args.host, port=args.port)


if __name__ == "__main__":
    main()
