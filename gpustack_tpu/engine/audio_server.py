"""Audio transcription server: OpenAI ``/v1/audio/transcriptions``.

The VoxBox role of the reference (worker/backends/vox_box.py:23 — audio
models served behind the same OpenAI surface). One process owns a
Whisper-class model (models/whisper.py); requests carry WAV audio as
multipart form data; transcription runs encode + jitted greedy decode on
the accelerator. Launched by the worker's serve manager exactly like the
LLM engine (worker/backends.py picks this entrypoint for audio-category
models) and fronted by the same authenticated worker proxy.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import time
import uuid

from aiohttp import web

logger = logging.getLogger(__name__)


class AudioEngine:
    """Owns model params + a serialized synthesis/transcription executor.

    One process serves one audio model: STT (Whisper-class,
    ``modality="stt"``) or TTS (FastSpeech-class, ``modality="tts"``) —
    together covering the reference's VoxBox role
    (worker/backends/vox_box.py:23 does both)."""

    def __init__(self, cfg, params, model_dir: str = "", modality: str = "stt"):
        self.cfg = cfg
        self.params = params
        self.model_dir = model_dir
        self.modality = modality
        self.tokenizer = self._load_tokenizer(model_dir)
        self._lock = asyncio.Lock()
        self.requests = 0
        self.audio_seconds = 0.0

    @staticmethod
    def _load_tokenizer(model_dir: str):
        if model_dir:
            try:
                from transformers import AutoTokenizer

                return AutoTokenizer.from_pretrained(model_dir)
            except Exception:
                logger.warning(
                    "no HF tokenizer in %s; using byte fallback", model_dir
                )
        from gpustack_tpu.engine.tokenizer import ByteTokenizer

        return ByteTokenizer()

    def _task_prompt_ids(self, task: str, language: str = "") -> tuple:
        """Whisper task/language conditioning: force ``<|xx|>`` (the
        OpenAI ``language`` form field, ISO 639-1) and ``<|translate|>``
        tokens after start-of-transcript (reference VoxBox serves both
        /v1/audio endpoints through the same model). Tokenizers without
        whisper task tokens (hermetic byte fallback) condition nothing."""
        convert = getattr(
            getattr(self.tokenizer, "_tok", None),
            "convert_tokens_to_ids", None,
        )
        if convert is None:
            if language:
                raise ValueError(
                    f"this model's tokenizer has no language tokens; "
                    f"cannot honor language={language!r}"
                )
            return ()
        unk = getattr(self.tokenizer._tok, "unk_token_id", None)

        def tid_of(token: str):
            tid = convert(token)
            return tid if tid is not None and tid != unk else None

        ids = []
        if language:
            lang_tid = tid_of(f"<|{language.lower()}|>")
            if lang_tid is None:
                # an unhonorable hint must not be silently dropped —
                # the client would believe it was applied
                raise ValueError(
                    f"unsupported language {language!r} (ISO 639-1 "
                    "code the model's tokenizer knows, e.g. 'en')"
                )
            ids.append(lang_tid)
        if task == "translate":
            tr = tid_of("<|translate|>")
            if tr is not None:
                ids.append(tr)
        elif ids:
            # whisper's canonical conditioning is sot→language→task:
            # with a forced language the task token must be forced too,
            # or greedy decode may pick <|translate|> on its own
            tr = tid_of("<|transcribe|>")
            if tr is not None:
                ids.append(tr)
        return tuple(ids)

    async def transcribe(
        self, wav_bytes: bytes, task: str = "transcribe",
        language: str = "",
    ) -> dict:
        from gpustack_tpu.models.audio import decode_wav, features_for_model
        from gpustack_tpu.models.whisper import greedy_transcribe

        audio = decode_wav(wav_bytes)
        mel = features_for_model(audio, self.cfg)
        prompt_ids = self._task_prompt_ids(task, language)
        start = time.monotonic()
        # one transcription at a time per process: decode is a tight
        # jitted loop; concurrency comes from replicas
        async with self._lock:
            ids = await asyncio.get_event_loop().run_in_executor(
                None,
                lambda: greedy_transcribe(
                    self.params, self.cfg, mel, prompt_ids=prompt_ids
                ),
            )
        text = self.tokenizer.decode(ids)
        self.requests += 1
        self.audio_seconds += len(audio) / 16000.0
        return {
            "text": text,
            "duration_s": round(len(audio) / 16000.0, 2),
            "latency_ms": round((time.monotonic() - start) * 1e3, 1),
        }

    async def speak(
        self, text: str, voice: str = "", speed: float = 1.0
    ) -> bytes:
        """Text → WAV bytes via the jitted synth + host Griffin-Lim."""
        from gpustack_tpu.models.tts import (
            pcm_to_wav_bytes,
            synthesize,
            voice_index,
        )

        ids = self.tokenizer.encode(text)
        if not ids:
            raise ValueError("input text is empty")
        async with self._lock:
            audio = await asyncio.get_event_loop().run_in_executor(
                None,
                lambda: synthesize(
                    self.params, self.cfg, ids,
                    voice=voice_index(voice, self.cfg), speed=speed,
                ),
            )
        self.requests += 1
        self.audio_seconds += len(audio) / self.cfg.sample_rate
        return pcm_to_wav_bytes(audio, self.cfg.sample_rate)


class AudioServer:
    def __init__(self, engine: AudioEngine, model_name: str = ""):
        self.engine = engine
        self.model_name = model_name or engine.cfg.name
        self.app = web.Application(client_max_size=256 * 2**20)
        self.app.add_routes(
            [
                web.post(
                    "/v1/audio/transcriptions", self.transcriptions
                ),
                web.post(
                    "/v1/audio/translations", self.transcriptions
                ),
                web.post("/v1/audio/speech", self.speech),
                web.get("/healthz", self.healthz),
                web.get("/metrics", self.metrics),
            ]
        )

    async def healthz(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "status": "ok",
                "model": self.model_name,
                "modality": f"audio/{self.engine.modality}",
                "requests": self.engine.requests,
            }
        )

    async def speech(self, request: web.Request) -> web.Response:
        """OpenAI ``/v1/audio/speech``: JSON {input, voice, speed} → WAV
        bytes (reference VoxBox serves TTS on the same path)."""
        if self.engine.modality != "tts":
            return web.json_response(
                {"error": f"model {self.model_name} is not a TTS model"},
                status=400,
            )
        try:
            body = await request.json()
        except (json.JSONDecodeError, UnicodeDecodeError):
            return web.json_response(
                {"error": "invalid JSON body"}, status=400
            )
        text = body.get("input")
        if not isinstance(text, str) or not text.strip():
            return web.json_response(
                {"error": "missing 'input'"}, status=400
            )
        fmt = body.get("response_format") or "wav"
        if fmt not in ("wav", "pcm"):
            return web.json_response(
                {"error": f"unsupported response_format {fmt!r}; this "
                 "engine produces wav/pcm"}, status=400
            )
        speed = body.get("speed")
        if speed is None:
            speed = 1.0
        if isinstance(speed, bool) or not isinstance(speed, (int, float)):
            return web.json_response(
                {"error": "'speed' must be a number"}, status=400
            )
        if not 0.25 <= speed <= 4.0:
            return web.json_response(
                {"error": "'speed' must be between 0.25 and 4.0"},
                status=400,
            )
        try:
            wav = await self.engine.speak(
                text, voice=str(body.get("voice") or ""), speed=speed
            )
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
        if fmt == "pcm":
            # strip the 44-byte RIFF header: raw 16-bit mono PCM
            return web.Response(
                body=wav[44:], content_type="application/octet-stream"
            )
        return web.Response(body=wav, content_type="audio/wav")

    async def metrics(self, request: web.Request) -> web.Response:
        return web.Response(
            text=(
                "# TYPE gpustack_tpu_audio_requests_total counter\n"
                f"gpustack_tpu_audio_requests_total {self.engine.requests}\n"
                "# TYPE gpustack_tpu_audio_seconds_total counter\n"
                f"gpustack_tpu_audio_seconds_total "
                f"{self.engine.audio_seconds:.2f}\n"
            )
        )

    async def transcriptions(self, request: web.Request) -> web.Response:
        if self.engine.modality != "stt":
            return web.json_response(
                {"error": f"model {self.model_name} is not an STT model"},
                status=400,
            )
        if not request.content_type.startswith("multipart/"):
            return web.json_response(
                {"error": "multipart/form-data with a 'file' part required"},
                status=400,
            )
        wav = None
        fmt = "json"
        language = ""
        async for part in await request.multipart():
            if part.name == "file":
                wav = await part.read(decode=False)
            elif part.name == "response_format":
                fmt = (await part.text()).strip() or "json"
            elif part.name == "language":
                language = (await part.text()).strip()
        if not wav:
            return web.json_response(
                {"error": "missing 'file' part"}, status=400
            )
        import wave as _wave

        task = (
            "translate" if request.path.endswith("/translations")
            else "transcribe"
        )
        try:
            result = await self.engine.transcribe(
                wav, task=task, language=language
            )
        except ValueError as e:
            # covers undecodable audio AND unhonorable language hints —
            # the exception message says which
            return web.json_response({"error": str(e)}, status=400)
        except (_wave.Error, EOFError) as e:
            return web.json_response(
                {"error": f"invalid audio: {e}"}, status=400
            )
        if fmt == "text":
            return web.Response(text=result["text"])
        return web.json_response(
            {
                "id": f"transcr-{uuid.uuid4().hex[:12]}",
                "object": (
                    "audio.translation" if task == "translate"
                    else "audio.transcription"
                ),
                "model": self.model_name,
                **result,
            }
        )


def build_audio_engine_from_args(args) -> AudioEngine:
    forced = os.environ.get("GPUSTACK_TPU_PLATFORM")
    import jax

    if forced:
        jax.config.update("jax_platforms", forced)

    from gpustack_tpu.models.tts import TTS_PRESETS, init_tts_params
    from gpustack_tpu.models.whisper import (
        WHISPER_PRESETS,
        config_from_hf_whisper,
        init_whisper_params,
    )

    if args.model_dir:
        with open(os.path.join(args.model_dir, "config.json")) as f:
            hf_cfg = json.load(f)
        if hf_cfg.get("model_type") in ("tts", "fastspeech"):
            # our own checkpoint format for the in-repo TTS: config.json
            # names a preset; params load from a .npz next to it
            from gpustack_tpu.engine.weights import load_npz_params

            cfg = TTS_PRESETS[hf_cfg.get("preset", "tts-base")]
            params = load_npz_params(
                os.path.join(args.model_dir, "params.npz"),
                lambda: init_tts_params(cfg, jax.random.key(0)),
            )
            return AudioEngine(
                cfg, params, model_dir=args.model_dir, modality="tts"
            )
        cfg = config_from_hf_whisper(hf_cfg)
        from gpustack_tpu.engine.weights import load_whisper_params

        params = load_whisper_params(cfg, args.model_dir)
        return AudioEngine(cfg, params, model_dir=args.model_dir)
    if args.preset in TTS_PRESETS:
        cfg = TTS_PRESETS[args.preset]
        params = init_tts_params(cfg, jax.random.key(0))
        return AudioEngine(cfg, params, modality="tts")
    cfg = WHISPER_PRESETS[args.preset]
    params = init_whisper_params(cfg, jax.random.key(0))
    return AudioEngine(cfg, params)


def main(argv=None) -> None:
    p = argparse.ArgumentParser("gpustack-tpu audio server")
    p.add_argument("--model-dir", default="")
    p.add_argument("--preset", default="whisper-large-v3")
    p.add_argument("--served-name", default="")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9000)
    # accepted for launcher compatibility; unused by the audio engine
    p.add_argument("--max-slots", type=int, default=1)
    p.add_argument("--max-seq-len", type=int, default=448)
    p.add_argument("--quantization", default="")
    p.add_argument("--mesh-plan", default="")
    args, _ = p.parse_known_args(argv)

    logging.basicConfig(level=logging.INFO)
    engine = build_audio_engine_from_args(args)
    server = AudioServer(engine, model_name=args.served_name or None)
    web.run_app(server.app, host=args.host, port=args.port)


if __name__ == "__main__":
    main()
