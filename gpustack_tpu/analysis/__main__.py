"""CLI: ``python -m gpustack_tpu.analysis [options]``.

Exit codes: 0 = clean (baseline-frozen findings allowed), 1 = new
findings, 2 = usage error. ``--update-baseline`` rewrites the ratchet
file from the current findings (review the diff — the baseline must
stay empty for blocking-in-async and state-machine).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def _changed_files(root):
    """Repo-relative .py paths touched vs. HEAD (staged, unstaged, and
    untracked). Returns None when git is unavailable — callers fall
    back to a full scan rather than silently analyzing nothing."""
    paths = set()
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
        status = subprocess.run(
            ["git", "status", "--porcelain"],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
        if diff.returncode != 0 or status.returncode != 0:
            return None
    except (OSError, subprocess.SubprocessError):
        return None
    for line in diff.stdout.splitlines():
        paths.add(line.strip())
    for line in status.stdout.splitlines():
        entry = line[3:].strip()
        if " -> " in entry:  # rename: keep the new path
            entry = entry.split(" -> ", 1)[1]
        paths.add(entry.strip('"'))
    return {p for p in paths if p.endswith(".py")}


def _finding_dict(f):
    return {
        "rule": f.rule,
        "path": f.path,
        "line": f.line,
        "message": f.message,
        "severity": f.severity,
        "key": f.key,
    }


def main(argv=None) -> int:
    from gpustack_tpu.analysis import core, rules

    parser = argparse.ArgumentParser(
        prog="python -m gpustack_tpu.analysis",
        description="Project-native static analysis (docs/ANALYSIS.md)",
    )
    parser.add_argument(
        "--root",
        default=os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ),
        help="repo root (default: auto-detected from this package)",
    )
    parser.add_argument(
        "--rule",
        action="append",
        metavar="ID",
        help="run only this rule (repeatable); default: all",
    )
    parser.add_argument(
        "--baseline",
        default=core.DEFAULT_BASELINE,
        help="baseline ratchet file",
    )
    parser.add_argument(
        "--update-baseline",
        action="store_true",
        help="freeze current findings into the baseline file",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rule ids"
    )
    parser.add_argument(
        "-q", "--quiet", action="store_true",
        help="summary line only",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="machine-readable report on stdout (findings, summary)",
    )
    parser.add_argument(
        "--changed-only", action="store_true",
        help="scope the scan to files changed vs. HEAD (staged, "
        "unstaged, untracked) — a fast pre-commit screen; the full "
        "run remains the gate",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in rules.get_rules():
            print(f"{rule.id:20s} {rule.description}")
        return 0

    try:
        selected = rules.get_rules(args.rule)
    except KeyError as e:
        print(e.args[0], file=sys.stderr)
        return 2

    only = None
    if args.changed_only:
        only = _changed_files(args.root)
        if only is None:
            print(
                "note: --changed-only needs git; scanning the full "
                "tree",
                file=sys.stderr,
            )
        elif not only:
            print("analysis: no changed .py files; nothing to scan")
            return 0

    t0 = time.monotonic()
    result = core.run_analysis(
        args.root, rules=selected, baseline_path=args.baseline,
        only=only,
    )
    elapsed = time.monotonic() - t0

    if args.as_json:
        report = {
            "ok": result.ok,
            "new": [_finding_dict(f) for f in result.new],
            "frozen": [_finding_dict(f) for f in result.frozen],
            "stale_baseline_keys": result.stale_baseline_keys,
            "rules_run": result.rules_run,
            "files_scanned": result.files_scanned,
            "cache_hits": result.cache_hits,
            "elapsed_s": round(elapsed, 3),
            "changed_only": args.changed_only,
        }
        print(json.dumps(report, indent=2))
        return 1 if result.new else 0

    if args.update_baseline:
        # a partial run (--rule) must not erase other rules' frozen
        # entries — carry them over verbatim
        ran = {r.id for r in selected}
        preserve = {
            key: count
            for key, count in core.load_baseline(args.baseline).items()
            if key.split("::", 1)[0] not in ran
        }
        core.save_baseline(
            result.new + result.frozen, args.baseline, preserve=preserve
        )
        print(
            f"baseline updated: {len(result.new) + len(result.frozen)} "
            f"finding(s) frozen in {args.baseline}"
            + (f" ({len(preserve)} entries from unrun rules kept)"
               if preserve else "")
        )
        return 0

    if not args.quiet:
        for f in result.new:
            print(f.render())
        for f in result.frozen:
            print(f"{f.render()}  [baselined]")
        for key in result.stale_baseline_keys:
            print(
                f"note: stale baseline entry (violation fixed — run "
                f"--update-baseline to ratchet down): {key}"
            )
    print(
        f"analysis: {len(result.new)} new, {len(result.frozen)} "
        f"baselined finding(s); {len(result.rules_run)} rule(s) over "
        f"{result.files_scanned} files in {elapsed:.2f}s"
    )
    return 1 if result.new else 0


if __name__ == "__main__":
    sys.exit(main())
