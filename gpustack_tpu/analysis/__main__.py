"""CLI: ``python -m gpustack_tpu.analysis [options]``.

Exit codes: 0 = clean (baseline-frozen findings allowed), 1 = new
findings, 2 = usage error. ``--update-baseline`` rewrites the ratchet
file from the current findings (review the diff — the baseline must
stay empty for blocking-in-async and state-machine).
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def main(argv=None) -> int:
    from gpustack_tpu.analysis import core, rules

    parser = argparse.ArgumentParser(
        prog="python -m gpustack_tpu.analysis",
        description="Project-native static analysis (docs/ANALYSIS.md)",
    )
    parser.add_argument(
        "--root",
        default=os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ),
        help="repo root (default: auto-detected from this package)",
    )
    parser.add_argument(
        "--rule",
        action="append",
        metavar="ID",
        help="run only this rule (repeatable); default: all",
    )
    parser.add_argument(
        "--baseline",
        default=core.DEFAULT_BASELINE,
        help="baseline ratchet file",
    )
    parser.add_argument(
        "--update-baseline",
        action="store_true",
        help="freeze current findings into the baseline file",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rule ids"
    )
    parser.add_argument(
        "-q", "--quiet", action="store_true",
        help="summary line only",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in rules.get_rules():
            print(f"{rule.id:20s} {rule.description}")
        return 0

    try:
        selected = rules.get_rules(args.rule)
    except KeyError as e:
        print(e.args[0], file=sys.stderr)
        return 2

    t0 = time.monotonic()
    result = core.run_analysis(
        args.root, rules=selected, baseline_path=args.baseline
    )
    elapsed = time.monotonic() - t0

    if args.update_baseline:
        # a partial run (--rule) must not erase other rules' frozen
        # entries — carry them over verbatim
        ran = {r.id for r in selected}
        preserve = {
            key: count
            for key, count in core.load_baseline(args.baseline).items()
            if key.split("::", 1)[0] not in ran
        }
        core.save_baseline(
            result.new + result.frozen, args.baseline, preserve=preserve
        )
        print(
            f"baseline updated: {len(result.new) + len(result.frozen)} "
            f"finding(s) frozen in {args.baseline}"
            + (f" ({len(preserve)} entries from unrun rules kept)"
               if preserve else "")
        )
        return 0

    if not args.quiet:
        for f in result.new:
            print(f.render())
        for f in result.frozen:
            print(f"{f.render()}  [baselined]")
        for key in result.stale_baseline_keys:
            print(
                f"note: stale baseline entry (violation fixed — run "
                f"--update-baseline to ratchet down): {key}"
            )
    print(
        f"analysis: {len(result.new)} new, {len(result.frozen)} "
        f"baselined finding(s); {len(result.rules_run)} rule(s) over "
        f"{result.files_scanned} files in {elapsed:.2f}s"
    )
    return 1 if result.new else 0


if __name__ == "__main__":
    sys.exit(main())
