"""Analysis framework: findings, suppressions, project model, baseline.

Design (in the spirit of flake8-async's blocking-call rules, but
project-native): each :class:`Rule` walks the repo through a shared
:class:`Project` (parsed-AST cache, so five rules pay one parse) and
yields :class:`Finding`\\ s. A finding is silenced either by an inline
``# analysis: ignore[rule-id]`` comment at (or directly above) the
flagged line, or by the checked-in baseline ratchet
(``gpustack_tpu/analysis/baseline.json``): keys present in the baseline
are *frozen* — reported but non-fatal — while anything new fails. The
baseline stores occurrence counts per key, so adding a second instance
of an already-baselined violation still fails.
"""

from __future__ import annotations

import ast
import collections
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

# paths never scanned: analyzer fixtures contain deliberate violations.
# Matched per path SEGMENT (or segment-prefix for the multi-segment
# entry), never by substring — a module merely *containing* one of
# these words must not silently escape the gate.
EXCLUDED_SEGMENTS = ("__pycache__", "fixtures")
EXCLUDED_PREFIXES = ("tests/analysis/",)

SUPPRESS_RE = re.compile(
    r"#\s*analysis:\s*ignore(?:\[([A-Za-z0-9_,\- ]+)\])?"
)

ALL_RULES_MARKER = "*"


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, posix separators
    line: int
    message: str
    severity: str = "error"

    @property
    def key(self) -> str:
        """Baseline identity: line numbers churn on unrelated edits, so
        the key is (rule, path, message) — stable across reflows."""
        return f"{self.rule}::{self.path}::{self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Rule:
    """One checker. Subclasses set ``id``/``description`` and implement
    :meth:`check`. Rules must only report through ``Finding`` so the
    suppression and baseline layers apply uniformly."""

    id: str = ""
    description: str = ""
    # True for rules whose findings are only meaningful against the
    # WHOLE tree (docs cross-checked against every emitter/field).
    # Scoped --changed-only runs skip them: on a slice, every
    # out-of-scope emitter reads as drift.
    whole_program: bool = False

    def check(self, project: "Project") -> Iterator[Finding]:
        raise NotImplementedError

    def finding(
        self, path: str, line: int, message: str, severity: str = "error"
    ) -> Finding:
        return Finding(self.id, path, line, message, severity)


class SourceFile:
    """A parsed python file: text, AST (with ``.parent`` back-links),
    and the per-line suppression table."""

    def __init__(self, root: str, rel: str):
        self.root = root
        self.rel = rel
        with open(os.path.join(root, rel), encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self._tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        self._suppressions: Optional[Dict[int, Set[str]]] = None

    @property
    def tree(self) -> Optional[ast.AST]:
        if self._tree is None and self.parse_error is None:
            try:
                self._tree = ast.parse(self.text, filename=self.rel)
            except SyntaxError as e:
                self.parse_error = f"syntax error: {e.msg} (line {e.lineno})"
                return None
            for node in ast.walk(self._tree):
                for child in ast.iter_child_nodes(node):
                    child.parent = node  # type: ignore[attr-defined]
        return self._tree

    @property
    def suppressions(self) -> Dict[int, Set[str]]:
        """line number -> rule ids silenced there ('*' = every rule).
        A trailing comment silences its own line; a standalone comment
        line silences the next line (so multi-line statements can carry
        the marker above them)."""
        if self._suppressions is None:
            table: Dict[int, Set[str]] = {}
            for i, line in enumerate(self.lines, start=1):
                m = SUPPRESS_RE.search(line)
                if not m:
                    continue
                rules = (
                    {r.strip() for r in m.group(1).split(",") if r.strip()}
                    if m.group(1)
                    else {ALL_RULES_MARKER}
                )
                target = (
                    i + 1 if line.strip().startswith("#") else i
                )
                table.setdefault(target, set()).update(rules)
            self._suppressions = table
        return self._suppressions

    def suppressed(self, rule_id: str, line: int) -> bool:
        rules = self.suppressions.get(line, set())
        return ALL_RULES_MARKER in rules or rule_id in rules


class Project:
    """Shared view of the repo for all rules: file discovery plus a
    parse cache. ``root`` is the repo root (the directory holding
    ``gpustack_tpu/``, ``docs/``, ``tests/``)."""

    def __init__(self, root: str, only: Optional[Set[str]] = None):
        self.root = os.path.abspath(root)
        self._files: Dict[str, SourceFile] = {}
        self._listing: Dict[str, List[str]] = {}
        # scope filter (repo-relative paths) for --changed-only runs;
        # None = whole tree
        self.only = only
        # parse-cache hits: every request for an already-parsed file.
        # N rules over one tree should pay ~1 parse per file — the
        # analysis test suite asserts this stays hot.
        self.cache_hits = 0

    # ---- discovery ------------------------------------------------------

    def py_files(self, prefix: str = "gpustack_tpu") -> List[str]:
        """Repo-relative paths of .py files under ``prefix``, sorted,
        minus excluded parts (fixtures, caches). Memoized — every rule
        asks for the same listing."""
        if prefix in self._listing:
            return self._listing[prefix]
        out: List[str] = []
        base = os.path.join(self.root, prefix)
        if os.path.isfile(base) and prefix.endswith(".py"):
            return [prefix]
        for dirpath, dirnames, filenames in os.walk(base):
            rel_dir = os.path.relpath(dirpath, self.root).replace(
                os.sep, "/"
            )
            if self._excluded(rel_dir + "/"):
                dirnames[:] = []
                continue
            dirnames.sort()
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                rel = f"{rel_dir}/{name}"
                if not self._excluded(rel):
                    out.append(rel)
        if self.only is not None:
            out = [r for r in out if r in self.only]
        self._listing[prefix] = out
        return out

    @staticmethod
    def _excluded(rel: str) -> bool:
        if rel.startswith(EXCLUDED_PREFIXES):
            return True
        return any(
            seg in EXCLUDED_SEGMENTS
            for seg in rel.rstrip("/").split("/")
        )

    # ---- access ---------------------------------------------------------

    def source(self, rel: str) -> Optional[SourceFile]:
        rel = rel.replace(os.sep, "/")
        if rel not in self._files:
            if not os.path.exists(os.path.join(self.root, rel)):
                return None
            self._files[rel] = SourceFile(self.root, rel)
        else:
            self.cache_hits += 1
        return self._files[rel]

    def read_text(self, rel: str) -> Optional[str]:
        path = os.path.join(self.root, rel)
        if not os.path.exists(path):
            return None
        with open(path, encoding="utf-8") as f:
            return f.read()


# ---- baseline ratchet ---------------------------------------------------

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json"
)


def load_baseline(path: str = DEFAULT_BASELINE) -> Dict[str, int]:
    """Baseline file -> {finding key: frozen occurrence count}."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    counts: Dict[str, int] = {}
    for entry in data.get("findings", []):
        counts[entry["key"]] = int(entry.get("count", 1))
    return counts


def save_baseline(
    findings: Iterable[Finding],
    path: str,
    preserve: Optional[Dict[str, int]] = None,
) -> None:
    """Write the ratchet file. ``preserve`` carries existing entries to
    keep verbatim — used when only a subset of rules ran, so a partial
    ``--update-baseline`` can't silently erase other rules' freezes."""
    counter = collections.Counter(f.key for f in findings)
    for key, count in (preserve or {}).items():
        counter[key] = max(counter[key], count) if key in counter \
            else count
    payload = {
        "comment": (
            "Frozen pre-existing findings (ratchet): entries here are "
            "reported but non-fatal; anything new fails. Regenerate "
            "with `python -m gpustack_tpu.analysis --update-baseline`. "
            "Must stay EMPTY for blocking-in-async and state-machine."
        ),
        "findings": [
            {"key": k, "count": n} for k, n in sorted(counter.items())
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")


@dataclasses.dataclass
class AnalysisResult:
    new: List[Finding]
    frozen: List[Finding]
    stale_baseline_keys: List[str]
    rules_run: List[str]
    files_scanned: int
    cache_hits: int = 0

    @property
    def ok(self) -> bool:
        return not self.new


def run_analysis(
    root: str,
    rules: Optional[Iterable[Rule]] = None,
    baseline: Optional[Dict[str, int]] = None,
    baseline_path: str = DEFAULT_BASELINE,
    only: Optional[Set[str]] = None,
) -> AnalysisResult:
    """Run ``rules`` (default: all registered) over ``root`` and split
    findings into new vs. baseline-frozen. ``only`` scopes the scan to
    a set of repo-relative paths (--changed-only) and skips
    ``whole_program`` rules — docs-vs-codebase drift checks can only
    produce noise on a slice. Scoped runs are a fast pre-commit
    screen, not the gate."""
    if rules is None:
        from gpustack_tpu.analysis.rules import get_rules

        rules = get_rules()
    if baseline is None:
        baseline = load_baseline(baseline_path)

    project = Project(root, only=only)
    findings: List[Finding] = []
    rule_ids: List[str] = []
    for rule in rules:
        if only is not None and rule.whole_program:
            continue
        rule_ids.append(rule.id)
        for f in rule.check(project):
            src = project.source(f.path)
            if src is not None and src.suppressed(f.rule, f.line):
                continue
            findings.append(f)

    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    budget = dict(baseline)
    new: List[Finding] = []
    frozen: List[Finding] = []
    for f in findings:
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
            frozen.append(f)
        else:
            new.append(f)
    stale = sorted(k for k, n in budget.items() if n > 0)
    if only is not None:
        # a scoped run cannot prove a baseline entry fixed — the file
        # holding it may simply be out of scope
        stale = []
    return AnalysisResult(
        new=new,
        frozen=frozen,
        stale_baseline_keys=stale,
        rules_run=rule_ids,
        files_scanned=len(project.py_files()),
        cache_hits=project.cache_hits,
    )
