"""Shared AST helpers: alias-aware name resolution and async scopes.

The rules reason about *lexical* async scope: the statements that run on
the event loop inside an ``async def``, excluding nested ``def``/
``lambda`` bodies (those are plain callables — typically handed to
``asyncio.to_thread``/``run_in_executor`` — and do not execute on the
loop at that point)."""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

_SCOPE_BARRIERS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def import_aliases(tree: ast.AST) -> Dict[str, str]:
    """Local name -> canonical dotted origin, from every import in the
    module (``import time as _time`` -> ``_time: time``; ``from time
    import sleep`` -> ``sleep: time.sleep``)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` expression -> "a.b.c"; None for anything fancier."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve_call(
    call: ast.Call, aliases: Dict[str, str]
) -> Optional[str]:
    """Canonical dotted name of a call target, import aliases applied
    to the leading segment."""
    name = dotted_name(call.func)
    if name is None:
        return None
    head, _, rest = name.partition(".")
    origin = aliases.get(head)
    if origin:
        name = origin + ("." + rest if rest else "")
    return name


def async_functions(tree: ast.AST) -> Iterator[ast.AsyncFunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef):
            yield node


def scope_walk(fn: ast.AsyncFunctionDef) -> Iterator[ast.AST]:
    """Every node lexically inside ``fn`` that executes on the event
    loop: nested function/lambda bodies are skipped (they run wherever
    they are later called — to_thread'd helpers must not be flagged),
    but nodes keep their ``.parent`` links for context checks."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, _SCOPE_BARRIERS):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def contains_await(node: ast.AST) -> bool:
    """Does an ``await`` execute within this statement's own scope
    (nested def/lambda bodies excluded)?"""
    stack: List[ast.AST] = [node]
    while stack:
        n = stack.pop()
        if n is not node and isinstance(n, _SCOPE_BARRIERS):
            continue
        if isinstance(n, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
            return True
        stack.extend(ast.iter_child_nodes(n))
    return False


def enclosing(
    node: ast.AST, kinds: Tuple[type, ...]
) -> Optional[ast.AST]:
    """Nearest ancestor of one of ``kinds`` (needs .parent links)."""
    cur = getattr(node, "parent", None)
    while cur is not None:
        if isinstance(cur, kinds):
            return cur
        cur = getattr(cur, "parent", None)
    return None


def string_constants(tree: ast.AST) -> Iterator[Tuple[int, str]]:
    """(line, value) for every string literal, f-string fragments
    included."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node.lineno, node.value


def open_handle_names(fn: ast.AsyncFunctionDef) -> Set[str]:
    """Names bound to a sync file handle inside ``fn``'s loop scope:
    ``with open(...) as f`` and ``f = open(...)`` (io.open/gzip.open
    count too)."""
    opens: Set[str] = set()
    for node in scope_walk(fn):
        if isinstance(node, ast.withitem):
            call = node.context_expr
            if (
                isinstance(call, ast.Call)
                and dotted_name(call.func)
                in ("open", "io.open", "gzip.open")
                and isinstance(node.optional_vars, ast.Name)
            ):
                opens.add(node.optional_vars.id)
        elif isinstance(node, ast.Assign):
            if (
                isinstance(node.value, ast.Call)
                and dotted_name(node.value.func)
                in ("open", "io.open", "gzip.open")
            ):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        opens.add(tgt.id)
    return opens
