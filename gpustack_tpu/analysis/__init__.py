"""Project-native static analysis (AST lint specialized to this repo).

Five checkers turn this codebase's real hazard classes — blocking calls
inside the asyncio control plane, sync locks held across ``await``,
undeclared ``ModelInstanceState`` transitions, config/doc drift, and
metric-name drift — into deterministic findings. Wired into tier-1 via
``tests/analysis/test_codebase_clean.py``; run directly with
``python -m gpustack_tpu.analysis`` or ``make analyze``.

See docs/ANALYSIS.md for rule descriptions, the suppression-comment
syntax (``# analysis: ignore[rule-id]``), and the baseline ratchet.
"""

from gpustack_tpu.analysis.core import (  # noqa: F401
    AnalysisResult,
    Finding,
    Project,
    Rule,
    load_baseline,
    run_analysis,
)
from gpustack_tpu.analysis.rules import ALL_RULES, get_rules  # noqa: F401
