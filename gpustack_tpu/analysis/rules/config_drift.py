"""config-doc-drift: Config fields <-> docs/CONFIG.md <-> env reads.

Three invariants:

1. every ``Config`` field in ``config.py`` is documented in
   ``docs/CONFIG.md`` — as ``GPUSTACK_TPU_<FIELD>`` or the table's
   ``_<FIELD>`` continuation shorthand;
2. every ``GPUSTACK_TPU_*`` variable named in the docs is either a
   ``Config`` field or a literal actually read somewhere in the code
   (the "operational knobs" read directly from the environment) — a
   doc row that matches neither is a stale name;
3. env-prefix consistency: any environment key starting with
   ``GPUSTACK`` read in code must carry the full ``GPUSTACK_TPU_``
   prefix, and every directly-read ``GPUSTACK_TPU_*`` knob must be
   documented.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from gpustack_tpu.analysis import astutil
from gpustack_tpu.analysis.core import Finding, Project, Rule

CONFIG_PATH = "gpustack_tpu/config.py"
DOC_PATH = "docs/CONFIG.md"
ENV_PREFIX = "GPUSTACK_TPU_"

DOC_TOKEN = re.compile(r"GPUSTACK_TPU_([A-Z0-9_]+)")
ENV_READ_FUNCS = {
    "os.environ.get", "environ.get", "os.getenv", "getenv",
    "os.environ.pop", "environ.pop",
    "os.environ.setdefault", "environ.setdefault",
}


class ConfigDocDriftRule(Rule):
    id = "config-doc-drift"
    description = (
        "Config fields, docs/CONFIG.md rows, and env reads must agree "
        "(names and GPUSTACK_TPU_ prefix)"
    )
    whole_program = True

    def check(self, project: Project) -> Iterator[Finding]:
        fields = self._config_fields(project)
        if fields is None:
            yield self.finding(
                CONFIG_PATH, 1, "Config class not found or unparseable"
            )
            return
        doc = project.read_text(DOC_PATH)
        if doc is None:
            yield self.finding(DOC_PATH, 1, f"{DOC_PATH} is missing")
            return

        env_reads = list(self._env_reads(project))
        code_literals = self._env_literals(project)

        # 1. every field documented. Whole-token match, not substring:
        # GPUSTACK_TPU_WORKER_PORT documenting itself must not also
        # count as documentation for GPUSTACK_TPU_PORT.
        doc_full_tokens = {
            ENV_PREFIX + m.group(1) for m in DOC_TOKEN.finditer(doc)
        }
        doc_short_tokens = set(
            re.findall(r"`_([A-Z0-9_]+)`", doc)
        )
        for field, line in sorted(fields.items()):
            token = ENV_PREFIX + field.upper()
            if (
                token not in doc_full_tokens
                and field.upper() not in doc_short_tokens
            ):
                yield self.finding(
                    CONFIG_PATH, line,
                    f"Config field '{field}' is not documented in "
                    f"{DOC_PATH} (expected {token})",
                )

        # 2. every documented variable exists
        field_tokens = {f.upper() for f in fields}
        for i, doc_line in enumerate(doc.splitlines(), start=1):
            for m in DOC_TOKEN.finditer(doc_line):
                suffix = m.group(1)
                if suffix in field_tokens:
                    continue
                if ENV_PREFIX + suffix in code_literals:
                    continue
                yield self.finding(
                    DOC_PATH, i,
                    f"documented variable GPUSTACK_TPU_{suffix} is "
                    f"neither a Config field nor read anywhere in the "
                    f"code (stale name?)",
                )

        # 3a. prefix consistency on env reads
        for rel, line, key in env_reads:
            if key.startswith("GPUSTACK") and not key.startswith(
                ENV_PREFIX
            ):
                yield self.finding(
                    rel, line,
                    f"env read of '{key}' does not use the "
                    f"{ENV_PREFIX} prefix",
                )

        # 3b. directly-read operational knobs must be documented
        seen: Set[str] = set()
        for rel, line, key in env_reads:
            if not key.startswith(ENV_PREFIX) or key in seen:
                continue
            seen.add(key)
            suffix = key[len(ENV_PREFIX):]
            if suffix.lower() in fields:
                continue  # reachable via Config.load's generic env layer
            if key not in doc_full_tokens:
                yield self.finding(
                    rel, line,
                    f"operational env knob {key} is read here but not "
                    f"documented in {DOC_PATH}",
                )

    # ---- extraction -----------------------------------------------------

    def _config_fields(
        self, project: Project
    ) -> Optional[Dict[str, int]]:
        src = project.source(CONFIG_PATH)
        tree = src.tree if src else None
        if tree is None:
            return None
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and node.name == "Config":
                return {
                    stmt.target.id: stmt.lineno
                    for stmt in node.body
                    if isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)
                    and not stmt.target.id.startswith("_")
                }
        return None

    def _env_reads(
        self, project: Project
    ) -> Iterator[Tuple[str, int, str]]:
        """(file, line, key) for every literal-keyed environ access."""
        for rel in project.py_files("gpustack_tpu"):
            src = project.source(rel)
            tree = src.tree if src else None
            if tree is None:
                continue
            for node in ast.walk(tree):
                key: Optional[str] = None
                if isinstance(node, ast.Call):
                    name = astutil.dotted_name(node.func)
                    if name in ENV_READ_FUNCS and node.args:
                        arg = node.args[0]
                        if isinstance(arg, ast.Constant) and isinstance(
                            arg.value, str
                        ):
                            key = arg.value
                elif isinstance(node, ast.Subscript):
                    base = astutil.dotted_name(node.value)
                    if base in ("os.environ", "environ") and isinstance(
                        node.slice, ast.Constant
                    ) and isinstance(node.slice.value, str):
                        key = node.slice.value
                if key is not None:
                    yield rel, node.lineno, key

    def _env_literals(self, project: Project) -> Set[str]:
        """Every GPUSTACK_TPU_* string literal in the code tree (covers
        injection sites like subprocess env dicts, not just reads)."""
        out: Set[str] = set()
        for rel in project.py_files("gpustack_tpu"):
            src = project.source(rel)
            if src is None:
                continue
            out.update(
                m.group(0) for m in re.finditer(
                    r"GPUSTACK_TPU_[A-Z0-9_]+", src.text
                )
            )
        return out
