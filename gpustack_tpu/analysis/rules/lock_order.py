"""lock-order: static lock acquisition graph + cycle detection.

Two threads acquiring the same two locks in opposite orders is a
deadlock waiting for a scheduler interleaving — the classic ABBA hang,
invisible in single-threaded tests and fatal the first time the
overlapped engine runs on real parallelism. This rule extracts the
static acquisition-order graph and fails on any cycle.

Edges come from two shapes (shared parse cache, whole tree):

- **nested ``with`` blocks**: ``with self._a: ... with self._b:``
  within one function adds the edge ``_a → _b`` (multiple items in one
  ``with a, b:`` count left-to-right);
- **cross-function calls**: a call made while holding a lock adds an
  edge to every lock the callee (resolved within the same module —
  ``self.helper()`` / bare ``helper()``) acquires anywhere, computed
  transitively with memoization, so ``with self._a: self.f()`` where
  ``f`` calls ``g`` and ``g`` takes ``self._b`` still yields
  ``_a → _b``.

Lock expressions are recognized by name: the last dotted segment must
look lock-like (``_mu``, ``_lock``, ``_wake``, ``_cv``, ``mutex``,
``*_sem``, ``_cond``, case-insensitive). Nodes are labeled
``<path>::<Class>.<attr>`` (or ``<path>::<name>`` for module-level
locks), so two classes' same-named locks stay distinct edges; the
runtime lockdep harness (gpustack_tpu/testing/lockdep.py) merges this
graph with observed acquisition edges after normalizing labels.

A genuinely ordered-by-construction pair that the rule cannot see
(e.g. ids sorted before acquisition) takes
``# analysis: ignore[lock-order]`` on the inner acquisition line.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from gpustack_tpu.analysis import astutil
from gpustack_tpu.analysis.core import Finding, Project, Rule

LOCK_NAME = re.compile(
    r"(^|_)(r?lock|mu|mutex|sem|cond(ition)?|cv|wake)$", re.I
)

_FUNCTION_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

# (src label, dst label) -> (path, line) of the first inner acquisition
EdgeMap = Dict[Tuple[str, str], Tuple[str, int]]


def _lock_label(
    expr: ast.AST, rel: str, cls_name: str
) -> Optional[str]:
    name = astutil.dotted_name(expr)
    if not name:
        return None
    last = name.rsplit(".", 1)[-1]
    if not LOCK_NAME.search(last):
        return None
    if name.startswith("self."):
        prefix = f"{cls_name}." if cls_name else ""
        return f"{rel}::{prefix}{last}"
    if "." in name:
        return None  # foreign object's lock: unresolvable statically
    return f"{rel}::{last}"


class _ModuleGraph:
    """Per-module extraction: function index, per-function acquired
    lock sets (transitive over same-module calls), and edges."""

    def __init__(self, rel: str, tree: ast.Module):
        self.rel = rel
        self.tree = tree
        # "Class.method" and "method" and "func" -> function node
        self.functions: Dict[str, ast.AST] = {}
        self._acquires: Dict[str, Set[str]] = {}
        self._index()

    def _index(self) -> None:
        for node in self.tree.body:
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                self.functions.setdefault(node.name, node)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        self.functions[f"{node.name}.{sub.name}"] = sub
                        self.functions.setdefault(sub.name, sub)

    def _cls_of(self, fn: ast.AST) -> str:
        cls = astutil.enclosing(fn, (ast.ClassDef,))
        return cls.name if cls is not None else ""

    def _resolve_call(self, call: ast.Call, cls_name: str) -> List[str]:
        """Keys into ``self.functions`` for a same-module call."""
        name = astutil.dotted_name(call.func)
        if not name:
            return []
        if name.startswith("self."):
            meth = name[len("self."):]
            if "." in meth:
                return []
            qualified = f"{cls_name}.{meth}"
            if qualified in self.functions:
                return [qualified]
            return [meth] if meth in self.functions else []
        if "." not in name and name in self.functions:
            return [name]
        return []

    def acquired_by(
        self, key: str, _visiting: Optional[Set[str]] = None
    ) -> Set[str]:
        """Every lock label ``key``'s function may acquire, same-module
        callees included (memoized, cycle-guarded)."""
        if key in self._acquires:
            return self._acquires[key]
        visiting = _visiting if _visiting is not None else set()
        if key in visiting:
            return set()
        visiting.add(key)
        fn = self.functions.get(key)
        out: Set[str] = set()
        if fn is not None:
            cls_name = self._cls_of(fn)
            for node in self._scope_walk(fn):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        label = _lock_label(
                            item.context_expr, self.rel, cls_name
                        )
                        if label:
                            out.add(label)
                elif isinstance(node, ast.Call):
                    for callee in self._resolve_call(node, cls_name):
                        out |= self.acquired_by(callee, visiting)
        visiting.discard(key)
        self._acquires[key] = out
        return out

    @staticmethod
    def _scope_walk(fn: ast.AST) -> Iterator[ast.AST]:
        """Nodes lexically in ``fn``, nested def/lambda bodies skipped
        (a closure runs on whatever thread later calls it)."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, _FUNCTION_KINDS):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def edges(self) -> EdgeMap:
        out: EdgeMap = {}
        seen_fns = {id(fn): fn for fn in self.functions.values()}
        for fn in seen_fns.values():
            cls_name = self._cls_of(fn)
            self._edges_under(fn, [], cls_name, out)
        return out

    def _edges_under(
        self,
        node: ast.AST,
        held: List[str],
        cls_name: str,
        out: EdgeMap,
    ) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNCTION_KINDS):
                continue
            acquired: List[str] = []
            if isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    label = _lock_label(
                        item.context_expr, self.rel, cls_name
                    )
                    if label:
                        for h in held + acquired:
                            if h != label:
                                out.setdefault(
                                    (h, label),
                                    (self.rel, child.lineno),
                                )
                        acquired.append(label)
            elif isinstance(child, ast.Call) and held:
                for callee in self._resolve_call(child, cls_name):
                    for label in self.acquired_by(callee):
                        for h in held:
                            if h != label:
                                out.setdefault(
                                    (h, label),
                                    (self.rel, child.lineno),
                                )
            self._edges_under(child, held + acquired, cls_name, out)


def acquisition_edges(project: Project) -> EdgeMap:
    """The whole tree's static acquisition graph — shared with the
    runtime lockdep harness, which merges observed edges into it."""
    edges: EdgeMap = {}
    for rel in project.py_files("gpustack_tpu"):
        src = project.source(rel)
        tree = src.tree if src else None
        if tree is None:
            continue
        edges.update(_ModuleGraph(rel, tree).edges())
    return edges


def find_cycles(
    edges: Set[Tuple[str, str]]
) -> List[List[str]]:
    """Elementary cycles, each rotated to start at its smallest label
    and deduplicated — deterministic across runs."""
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    cycles: Set[Tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: List[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                i = path.index(min(path))
                cycles.add(tuple(path[i:] + path[:i]))
            elif nxt not in path and nxt > start:
                # only explore labels > start: each cycle is found
                # exactly once, rooted at its smallest node
                dfs(start, nxt, path + [nxt])

    for start in sorted(graph):
        dfs(start, start, [start])
    return [list(c) for c in sorted(cycles)]


class LockOrderRule(Rule):
    id = "lock-order"
    description = (
        "cycle in the static lock acquisition-order graph (nested "
        "`with` blocks + same-module call chains) — an ABBA deadlock "
        "waiting for an interleaving"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        edges = acquisition_edges(project)
        for cycle in find_cycles(set(edges)):
            ring = cycle + [cycle[0]]
            locations = []
            for a, b in zip(ring, ring[1:]):
                loc = edges.get((a, b))
                if loc is not None:
                    locations.append(loc)
            path, line = min(locations) if locations else ("", 0)
            yield self.finding(
                path,
                line,
                "lock acquisition cycle: "
                + " -> ".join(ring)
                + " (some thread can hold each lock while wanting "
                "the next)",
            )
