"""held-across-await: sync primitives held through a suspension point.

A ``with threading.Lock()`` (or an ORM session) held across an
``await`` deadlocks the loop the moment a second coroutine reaches the
same lock: the holder is suspended, the waiter blocks the whole thread,
and the holder can never resume to release. Only *sync* ``with`` is
flagged — ``async with asyncio.Lock()`` is the correct pattern and
parses as a different node. Matched context managers:

- calls to ``threading.Lock/RLock/Condition/Semaphore/BoundedSemaphore``
- names/attributes whose last segment looks lock-like (``lock``,
  ``_lock``, ``mutex``, ``rlock``) or session-like (``session``,
  ``*_session``)
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from gpustack_tpu.analysis import astutil
from gpustack_tpu.analysis.core import Finding, Project, Rule

LOCK_FACTORIES = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
}

LOCKLIKE_NAME = re.compile(r"(^|_)(r?lock|mutex|session)$", re.I)


class HeldAcrossAwaitRule(Rule):
    id = "held-across-await"
    description = (
        "sync lock/session `with` block containing an await "
        "(suspension while holding a thread-blocking primitive)"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        for rel in project.py_files("gpustack_tpu"):
            src = project.source(rel)
            tree = src.tree if src else None
            if tree is None:
                continue
            aliases = astutil.import_aliases(tree)
            for fn in astutil.async_functions(tree):
                for node in astutil.scope_walk(fn):
                    if not isinstance(node, ast.With):
                        continue
                    held = self._lock_expr(node, aliases)
                    if held and any(
                        astutil.contains_await(stmt)
                        for stmt in node.body
                    ):
                        yield self.finding(
                            rel,
                            node.lineno,
                            f"sync '{held}' held across await in "
                            f"async def {fn.name}()",
                        )

    def _lock_expr(self, node: ast.With, aliases) -> str:
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                name = astutil.resolve_call(expr, aliases)
                if name in LOCK_FACTORIES:
                    return f"{name}()"
                expr_name = name
            else:
                expr_name = astutil.dotted_name(expr)
            if expr_name and LOCKLIKE_NAME.search(
                expr_name.rsplit(".", 1)[-1]
            ):
                return expr_name
        return ""
