"""held-across-await: sync primitives held through a suspension point.

A ``with threading.Lock()`` (or an ORM session) held across an
``await`` deadlocks the loop the moment a second coroutine reaches the
same lock: the holder is suspended, the waiter blocks the whole thread,
and the holder can never resume to release. Only *sync* ``with`` is
flagged — ``async with asyncio.Lock()`` is the correct pattern and
parses as a different node. Matched context managers:

- calls to ``threading.Lock/RLock/Condition/Semaphore/BoundedSemaphore``
- names/attributes whose last segment looks lock-like (``lock``,
  ``_lock``, ``mutex``, ``rlock``) or session-like (``session``,
  ``*_session``)
- calls to a same-module helper whose body takes such a lock — one
  level of resolution, so ``with self._entries_view():`` where the
  ``@contextmanager`` helper does ``with self._lock: yield`` is still
  flagged. An innocuously named helper that holds no lock stays quiet.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, Optional

from gpustack_tpu.analysis import astutil
from gpustack_tpu.analysis.core import Finding, Project, Rule

LOCK_FACTORIES = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
}

LOCKLIKE_NAME = re.compile(r"(^|_)(r?lock|mutex|session)$", re.I)


class HeldAcrossAwaitRule(Rule):
    id = "held-across-await"
    description = (
        "sync lock/session `with` block containing an await "
        "(suspension while holding a thread-blocking primitive)"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        for rel in project.py_files("gpustack_tpu"):
            src = project.source(rel)
            tree = src.tree if src else None
            if tree is None:
                continue
            aliases = astutil.import_aliases(tree)
            helpers = _local_functions(tree)
            for fn in astutil.async_functions(tree):
                for node in astutil.scope_walk(fn):
                    if not isinstance(node, ast.With):
                        continue
                    held = self._lock_expr(node, aliases, helpers)
                    if held and any(
                        astutil.contains_await(stmt)
                        for stmt in node.body
                    ):
                        yield self.finding(
                            rel,
                            node.lineno,
                            f"sync '{held}' held across await in "
                            f"async def {fn.name}()",
                        )

    def _lock_expr(
        self,
        node: ast.With,
        aliases,
        helpers: Dict[str, ast.AST],
    ) -> str:
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                name = astutil.resolve_call(expr, aliases)
                if name in LOCK_FACTORIES:
                    return f"{name}()"
                inner = self._helper_lock(expr, aliases, helpers)
                if inner:
                    return inner
                expr_name = name
            else:
                expr_name = astutil.dotted_name(expr)
            if expr_name and LOCKLIKE_NAME.search(
                expr_name.rsplit(".", 1)[-1]
            ):
                return expr_name
        return ""

    @staticmethod
    def _helper_lock(
        call: ast.Call, aliases, helpers: Dict[str, ast.AST]
    ) -> Optional[str]:
        """One level of same-module resolution: a `with helper():`
        whose body takes a sync lock is as held as the lock itself."""
        dotted = astutil.dotted_name(call.func)
        if not dotted:
            return None
        fn = helpers.get(dotted.rsplit(".", 1)[-1])
        if fn is None:
            return None
        for sub in astutil.scope_walk(fn):
            lockname: Optional[str] = None
            if isinstance(sub, ast.With):
                for it in sub.items:
                    expr = it.context_expr
                    if isinstance(expr, ast.Call):
                        n = astutil.resolve_call(expr, aliases)
                        if n in LOCK_FACTORIES:
                            lockname = f"{n}()"
                    else:
                        n = astutil.dotted_name(expr)
                        if n and LOCKLIKE_NAME.search(
                            n.rsplit(".", 1)[-1]
                        ):
                            lockname = n
            elif (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "acquire"
            ):
                n = astutil.dotted_name(sub.func.value)
                if n and LOCKLIKE_NAME.search(n.rsplit(".", 1)[-1]):
                    lockname = n
            if lockname:
                return f"{dotted}() (acquires {lockname})"
        return None


def _local_functions(tree: ast.Module) -> Dict[str, ast.AST]:
    """Same-module callables by bare name — top-level defs and class
    methods — for one-level helper resolution."""
    out: Dict[str, ast.AST] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    out.setdefault(sub.name, sub)
    return out
