"""thread-boundary: declared thread-owned state vs. the asyncio loop.

The async-loop/scheduler-thread seam is where the overlapped engine's
wakeup bugs lived: state owned by a worker thread mutated from an
``async def`` body (or loop-owned state mutated from a thread entry
point) races without any lock to point at. Modules declare the seam as
module-level literals::

    THREAD_OWNED = ("_slots", "_detok_batch")   # worker/scheduler
                                                # thread state
    LOOP_OWNED = ("_hb", "_status")             # event-loop state

and the rule flags, on ``self.<attr>`` (or module-global bare-name)
accesses:

- a ``THREAD_OWNED`` attribute touched lexically inside an
  ``async def`` body (nested ``def``/``lambda`` bodies excluded —
  those run wherever they are called, typically a thread pool);
- a ``LOOP_OWNED`` attribute touched inside a function used as a
  thread entry point — any function the module passes as ``target=``
  to ``threading.Thread(...)``.

``__init__`` is exempt (construction happens-before thread start). A
reviewed crossing (e.g. a racy-tolerant gauge read for an HTTP
handler) takes ``# analysis: ignore[thread-boundary]``.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from gpustack_tpu.analysis import astutil
from gpustack_tpu.analysis.core import Finding, Project, Rule

THREAD_DECL = "THREAD_OWNED"
LOOP_DECL = "LOOP_OWNED"

_FUNCTION_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _declared_tuple(tree: ast.Module, name: str) -> Set[str]:
    out: Set[str] = set()
    for node in tree.body:
        if not (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == name
        ):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, str
                ):
                    out.add(elt.value)
    return out


def _thread_targets(tree: ast.Module, aliases) -> Set[str]:
    """Function names the module hands to ``threading.Thread(target=)``
    — the thread entry points."""
    targets: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if astutil.resolve_call(node, aliases) != "threading.Thread":
            continue
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            name = astutil.dotted_name(kw.value)
            if name:
                targets.add(name.rsplit(".", 1)[-1])
    return targets


def _accesses(node: ast.AST, attrs: Set[str], bare: Set[str]):
    """(line, attr) for every self.<attr>/bare-name access in scope."""
    if isinstance(node, ast.Attribute):
        if (
            isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr in attrs
        ):
            yield node.lineno, node.attr
    elif isinstance(node, ast.Name) and node.id in bare:
        yield node.lineno, node.id


class ThreadBoundaryRule(Rule):
    id = "thread-boundary"
    description = (
        "THREAD_OWNED attribute touched from an `async def` body, or "
        "LOOP_OWNED attribute touched from a thread entry point "
        "(the async-loop/worker-thread seam)"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        for rel in project.py_files("gpustack_tpu"):
            src = project.source(rel)
            tree = src.tree if src else None
            if tree is None:
                continue
            thread_owned = _declared_tuple(tree, THREAD_DECL)
            loop_owned = _declared_tuple(tree, LOOP_DECL)
            if not thread_owned and not loop_owned:
                continue
            aliases = astutil.import_aliases(tree)
            module_names = {
                n
                for n in (thread_owned | loop_owned)
                if n in _module_level_assigns(tree)
            }
            # thread-owned state in async bodies
            for fn in astutil.async_functions(tree):
                for node in astutil.scope_walk(fn):
                    for line, attr in _accesses(
                        node, thread_owned,
                        thread_owned & module_names,
                    ):
                        yield self.finding(
                            rel,
                            line,
                            f"thread-owned '{attr}' touched from "
                            f"async def {fn.name}() — loop code must "
                            f"not reach across the thread boundary",
                        )
            # loop-owned state in thread entry points
            entries = _thread_targets(tree, aliases)
            if not (entries and loop_owned):
                continue
            for fn in ast.walk(tree):
                if not isinstance(
                    fn, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if fn.name not in entries or isinstance(
                    fn, ast.AsyncFunctionDef
                ):
                    continue
                for node in self._sync_scope_walk(fn):
                    for line, attr in _accesses(
                        node, loop_owned, loop_owned & module_names
                    ):
                        yield self.finding(
                            rel,
                            line,
                            f"loop-owned '{attr}' touched from "
                            f"thread entry point {fn.name}() — "
                            f"thread code must not reach across the "
                            f"loop boundary",
                        )

    @staticmethod
    def _sync_scope_walk(fn: ast.AST) -> Iterator[ast.AST]:
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, _FUNCTION_KINDS):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))


def _module_level_assigns(tree: ast.Module) -> Set[str]:
    names: Set[str] = set()
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name):
                names.add(t.id)
    return names
