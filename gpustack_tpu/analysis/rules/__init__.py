"""Rule registry. Rule ids are stable API: they appear in suppression
comments and baseline keys — never rename one casually."""

from __future__ import annotations

from typing import Iterable, List, Optional

from gpustack_tpu.analysis.core import Rule
from gpustack_tpu.analysis.rules.blocking import BlockingInAsyncRule
from gpustack_tpu.analysis.rules.locks import HeldAcrossAwaitRule
from gpustack_tpu.analysis.rules.state_machine import StateMachineRule
from gpustack_tpu.analysis.rules.config_drift import ConfigDocDriftRule
from gpustack_tpu.analysis.rules.metrics_drift import MetricsDriftRule
from gpustack_tpu.analysis.rules.sync_dispatch import SyncInDispatchRule
from gpustack_tpu.analysis.rules.route_auth import RouteAuthRule
from gpustack_tpu.analysis.rules.guarded_by import GuardedByRule
from gpustack_tpu.analysis.rules.lock_order import LockOrderRule
from gpustack_tpu.analysis.rules.thread_boundary import ThreadBoundaryRule

ALL_RULES = (
    BlockingInAsyncRule,
    HeldAcrossAwaitRule,
    StateMachineRule,
    ConfigDocDriftRule,
    MetricsDriftRule,
    SyncInDispatchRule,
    RouteAuthRule,
    GuardedByRule,
    LockOrderRule,
    ThreadBoundaryRule,
)


def get_rules(ids: Optional[Iterable[str]] = None) -> List[Rule]:
    rules = [cls() for cls in ALL_RULES]
    if ids is None:
        return rules
    wanted = set(ids)
    known = {r.id for r in rules}
    unknown = wanted - known
    if unknown:
        raise KeyError(
            f"unknown rule id(s): {', '.join(sorted(unknown))}; "
            f"known: {', '.join(sorted(known))}"
        )
    return [r for r in rules if r.id in wanted]
