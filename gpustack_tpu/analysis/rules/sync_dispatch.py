"""sync-in-dispatch: device-sync calls on the engine dispatch path.

The overlapped engine's speed rests on one property: the scheduler
thread DISPATCHES device work and never waits for it — sampled tokens
are fetched ``pipeline_depth`` steps behind, detokenization rides a
worker thread, KV uploads stage on the copy executor. One stray
``np.asarray`` on a device array (or ``.item()``, or
``jax.block_until_ready``) inside the dispatch path silently
re-serializes the whole pipeline, and nothing crashes — throughput just
quietly drops. This rule makes that a deterministic test failure.

A module opts in by declaring, at module level, the functions that form
its dispatch path::

    DISPATCH_SYNC_FREE = ("step", "_admit", "_decode_once", ...)

Inside those functions (nested ``def``/``lambda`` bodies excluded —
they run on worker threads or executors), any call to the device-sync
vocabulary is flagged:

- ``np.asarray(...)`` (``numpy.asarray`` after alias resolution) — a
  device→host copy when handed a device array;
- ``.item()`` — a device scalar sync;
- ``jax.block_until_ready(...)`` / ``jax.device_get(...)``;
- blocking file I/O — ``open(...)``, ``os.replace(...)``,
  ``os.unlink(...)``, and ``.read_bytes()``/``.write_bytes()``/
  ``.read_text()``/``.write_text()`` (the ``pathlib`` spellings): the
  disk spill tier's store/load path must never run on the scheduler —
  residency probes (``DiskKVSpill.has``/``size``) and the host cache's
  match path declare themselves sync-free, keeping a disk seek off
  every step.

Host syncs belong in the module's designated fetch/drain helpers
(simply not listed in ``DISPATCH_SYNC_FREE``); a genuinely host-only
``np.asarray`` in a listed function takes
``# analysis: ignore[sync-in-dispatch]``. The rule only checks the
listed functions' direct bodies — designated helpers are the escape
hatch, which is exactly the declared contract.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from gpustack_tpu.analysis import astutil
from gpustack_tpu.analysis.core import Finding, Project, Rule

DECLARATION = "DISPATCH_SYNC_FREE"

SYNC_CALLS = {
    "numpy.asarray": "device→host copy np.asarray()",
    "jax.block_until_ready": "jax.block_until_ready()",
    "jax.device_get": "jax.device_get()",
    # PR 16 spill tier: dispatch must never touch the filesystem — a
    # disk seek on the scheduler re-serializes the pipeline exactly
    # like a device sync does
    "open": "blocking file I/O open()",
    "io.open": "blocking file I/O io.open()",
    "os.replace": "blocking file I/O os.replace()",
    "os.unlink": "blocking file I/O os.unlink()",
}

# argless pathlib-style sync methods (``p.read_bytes()``), matched by
# attribute like ``.item()`` is
SYNC_METHODS = {
    "item": "device scalar sync .item()",
    "read_bytes": "blocking file I/O .read_bytes()",
    "write_bytes": "blocking file I/O .write_bytes()",
    "read_text": "blocking file I/O .read_text()",
    "write_text": "blocking file I/O .write_text()",
}


def _declared(tree: ast.AST) -> Set[str]:
    """Names listed in the module-level DISPATCH_SYNC_FREE literal
    (tuple/list of string constants); empty when undeclared."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == DECLARATION
            for t in targets
        ):
            continue
        if isinstance(value, (ast.Tuple, ast.List)):
            for elt in value.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, str
                ):
                    names.add(elt.value)
    return names


class SyncInDispatchRule(Rule):
    id = "sync-in-dispatch"
    description = (
        "device-sync call (np.asarray/.item()/block_until_ready/"
        "device_get) inside a declared DISPATCH_SYNC_FREE function — "
        "host syncs belong in designated fetch/drain helpers"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        for rel in project.py_files("gpustack_tpu"):
            src = project.source(rel)
            tree = src.tree if src else None
            if tree is None:
                continue
            declared = _declared(tree)
            if not declared:
                continue
            aliases = astutil.import_aliases(tree)
            for fn in ast.walk(tree):
                if not isinstance(
                    fn, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if fn.name not in declared:
                    continue
                for node in astutil.scope_walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    msg = self._classify(node, aliases)
                    if msg:
                        yield self.finding(
                            rel,
                            node.lineno,
                            f"{msg} in dispatch-path function "
                            f"{fn.name}() (host syncs belong in a "
                            f"designated fetch/drain helper)",
                        )

    @staticmethod
    def _classify(call: ast.Call, aliases) -> Optional[str]:
        # alias resolution canonicalizes every import spelling:
        # `import numpy as np` → numpy.asarray, `from jax import
        # block_until_ready` → jax.block_until_ready
        name = astutil.resolve_call(call, aliases)
        if name in SYNC_CALLS:
            return SYNC_CALLS[name]
        if isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            msg = SYNC_METHODS.get(attr)
            # `.item()`/`.read_*()` must be argless to count (keeps
            # dict-ish `.item(key)` lookalikes out); the pathlib
            # write methods take their payload argument
            if msg and (
                attr.startswith("write_")
                or (not call.args and not call.keywords)
            ):
                return msg
        return None
