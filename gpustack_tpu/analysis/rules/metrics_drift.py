"""metrics-drift: metric names stay unique, well-formed, and real.

The production tree is the vocabulary of record: every ``# TYPE name
kind`` declaration and every metric-shaped string literal under
``gpustack_tpu/`` defines what actually exists on the wire. Checks:

1. declarations — no duplicate ``# TYPE`` for a name within one file,
   no kind conflict for a name across files, every declared name
   ``snake_case`` (one optional ``namespace:`` colon, as in
   ``gpustack_tpu:requests_running`` or engine-native ``vllm:*``).
   Declarations come from literal ``# TYPE name kind`` strings AND
   from ``METRIC_FAMILIES`` dict literals (observability/metrics.py
   renders its families from that declared vocabulary);
2. histogram families — ``_bucket``/``_sum``/``_count`` are series of
   ONE declared base family, never metrics of their own: declaring
   ``# TYPE foo_seconds_bucket gauge`` next to a ``foo_seconds``
   histogram is three drifting metrics wearing one name;
3. the normalization table (``worker/metrics_map.py`` METRIC_MAP) —
   no duplicate keys (silent last-wins in a dict literal!), every
   value under the ``gpustack_tpu:`` namespace;
4. references — metric-shaped names mentioned in ``docs/*.md``,
   ``README.md`` and ``tests/**`` must exist in the production
   vocabulary (histogram ``_bucket``/``_sum``/``_count`` suffixes
   allowed); a rename that orphans a dashboard/doc/test name fails
   here.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Set, Tuple

from gpustack_tpu.analysis import astutil
from gpustack_tpu.analysis.core import Finding, Project, Rule

METRICS_MAP_PATH = "gpustack_tpu/worker/metrics_map.py"
NORMALIZED_PREFIX = "gpustack_tpu:"

TYPE_DECL = re.compile(
    r"#\s*TYPE\s+([A-Za-z_:][A-Za-z0-9_:]*)\s+"
    r"(counter|gauge|histogram|summary|untyped)"
)
# a well-formed name: snake_case with at most one namespace colon
WELL_FORMED = re.compile(r"^[a-z][a-z0-9_]*(:[a-z][a-z0-9_]*)?$")
# candidate metric tokens in docs/tests (filtered against vocabulary)
REF_TOKEN = re.compile(
    r"\b(?:gpustack|vllm|sglang)[a-z0-9]*[_:][A-Za-z0-9_:]+"
)
HISTO_SUFFIXES = ("_bucket", "_sum", "_count")
VALID_KINDS = ("counter", "gauge", "histogram", "summary", "untyped")
# dict literals that declare metric vocabularies (name -> kind):
# METRIC_FAMILIES (observability/metrics.py render vocabulary) and
# NORMALIZED_FAMILIES (worker/metrics_map.py normalized namespace)
FAMILY_DICT_NAMES = ("METRIC_FAMILIES", "NORMALIZED_FAMILIES")
NORMALIZED_FAMILIES_NAME = "NORMALIZED_FAMILIES"


class MetricsDriftRule(Rule):
    id = "metrics-drift"
    description = (
        "metric names unique/snake_case in emitters; docs and tests "
        "reference only names the code can emit"
    )
    whole_program = True

    def check(self, project: Project) -> Iterator[Finding]:
        decls: List[Tuple[str, str, str, int]] = []  # name,kind,file,line
        vocab: Set[str] = set()
        for rel in project.py_files("gpustack_tpu"):
            if rel.startswith("gpustack_tpu/analysis/"):
                # the analyzers' docstrings/examples must not keep dead
                # metric names alive in the vocabulary
                continue
            src = project.source(rel)
            tree = src.tree if src else None
            if tree is None:
                continue
            for line, value in astutil.string_constants(tree):
                for m in TYPE_DECL.finditer(value):
                    decls.append((m.group(1), m.group(2), rel, line))
                vocab.update(
                    t.rstrip("_:") for t in REF_TOKEN.findall(value)
                )
            family_decls, bad_kinds = self._family_decls(tree, rel)
            decls.extend(family_decls)
            yield from bad_kinds

        yield from self._declaration_checks(decls)
        yield from self._family_checks(decls)
        yield from self._map_checks(project)
        yield from self._reference_checks(project, vocab)

    # ---- 0. METRIC_FAMILIES dict declarations --------------------------

    @staticmethod
    def _dict_literal_items(tree, names):
        """Yield ``(var_name, key_node, value_node)`` for every
        string-keyed entry of module-level dict literals assigned to
        one of ``names`` — both plain (``X = {}``) and annotated
        (``X: Dict[str, str] = {}``) assignments (the annotated form is
        what the production files actually use)."""
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                targets = [
                    t.id for t in node.targets
                    if isinstance(t, ast.Name)
                ]
                value = node.value
            elif isinstance(node, ast.AnnAssign):
                targets = (
                    [node.target.id]
                    if isinstance(node.target, ast.Name) else []
                )
                value = node.value
            else:
                continue
            name = next((t for t in targets if t in names), None)
            if name is None or not isinstance(value, ast.Dict):
                continue
            for k, v in zip(value.keys, value.values):
                if not (
                    isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)
                ):
                    continue
                yield name, k, v

    def _family_decls(
        self, tree, rel: str
    ) -> Tuple[List[Tuple[str, str, str, int]], List[Finding]]:
        """``METRIC_FAMILIES = {"name": "kind", ...}`` (and
        ``NORMALIZED_FAMILIES``) literals declare metrics the same way
        a ``# TYPE`` string does (the renderers emit their TYPE lines
        from those vocabularies at runtime, so the static view must
        read the same source of truth)."""
        decls: List[Tuple[str, str, str, int]] = []
        findings: List[Finding] = []
        for dict_name, k, v in self._dict_literal_items(
            tree, FAMILY_DICT_NAMES
        ):
            if v.value not in VALID_KINDS:
                findings.append(self.finding(
                    rel, k.lineno,
                    f"{dict_name} kind '{v.value}' for "
                    f"'{k.value}' is not one of {VALID_KINDS}",
                ))
                continue
            decls.append((k.value, v.value, rel, k.lineno))
        return decls, findings

    # ---- 1. TYPE declarations ------------------------------------------

    def _declaration_checks(self, decls) -> Iterator[Finding]:
        per_file: Dict[Tuple[str, str], int] = {}
        kinds: Dict[str, Tuple[str, str, int]] = {}
        for name, kind, rel, line in decls:
            if not WELL_FORMED.match(name):
                yield self.finding(
                    rel, line,
                    f"metric name '{name}' is not snake_case "
                    f"(optionally 'namespace:name')",
                )
            # messages deliberately omit the other site's line number:
            # Finding.key embeds the message, and a line number there
            # would churn baseline keys on unrelated edits
            seen_at = per_file.get((rel, name))
            if seen_at is not None and seen_at != line:
                yield self.finding(
                    rel, line,
                    f"duplicate # TYPE declaration for '{name}' "
                    f"in this file",
                )
            per_file.setdefault((rel, name), line)
            prev = kinds.get(name)
            if prev is not None and prev[0] != kind:
                yield self.finding(
                    rel, line,
                    f"metric '{name}' declared {kind} here but "
                    f"{prev[0]} in {prev[1]}",
                )
            kinds.setdefault(name, (kind, rel, line))

    # ---- 1b. histogram family integrity --------------------------------

    def _family_checks(self, decls) -> Iterator[Finding]:
        """A histogram's ``_bucket``/``_sum``/``_count`` series belong
        to the ONE declared base family — a separate declaration for a
        suffixed name next to its base means the exporter is emitting
        the family and something else is emitting a same-named metric
        (three drifting metrics wearing one histogram's name)."""
        declared: Dict[str, str] = {}
        for name, kind, _rel, _line in decls:
            declared.setdefault(name, kind)
        for name, kind, rel, line in decls:
            for suffix in HISTO_SUFFIXES:
                if not name.endswith(suffix):
                    continue
                base = name[: -len(suffix)]
                base_kind = declared.get(base)
                if base_kind in ("histogram", "summary"):
                    yield self.finding(
                        rel, line,
                        f"'{name}' declared {kind} but it is a series "
                        f"of the declared {base_kind} family '{base}' "
                        f"— emit the family, not its parts",
                    )
                break

    # ---- 2. normalization map ------------------------------------------

    def _map_checks(self, project: Project) -> Iterator[Finding]:
        src = project.source(METRICS_MAP_PATH)
        tree = src.tree if src else None
        if tree is None:
            return
        # the declared normalized vocabulary: every METRIC_MAP value
        # must be a member, so a gpustack_tpu:* typo in the map fails
        # here instead of minting an undeclared series on the wire
        normalized_vocab = {
            k.value
            for _n, k, _v in self._dict_literal_items(
                tree, (NORMALIZED_FAMILIES_NAME,)
            )
        }
        seen: Dict[str, int] = {}
        for _name, k, v in self._dict_literal_items(
            tree, ("METRIC_MAP",)
        ):
            if k.value in seen:
                yield self.finding(
                    METRICS_MAP_PATH, k.lineno,
                    f"duplicate METRIC_MAP key '{k.value}' (a "
                    f"dict literal silently keeps the last)",
                )
            seen.setdefault(k.value, k.lineno)
            if not v.value.startswith(NORMALIZED_PREFIX):
                yield self.finding(
                    METRICS_MAP_PATH, v.lineno,
                    f"METRIC_MAP value '{v.value}' must live under "
                    f"the {NORMALIZED_PREFIX} namespace",
                )
            elif not WELL_FORMED.match(v.value):
                yield self.finding(
                    METRICS_MAP_PATH, v.lineno,
                    f"METRIC_MAP value '{v.value}' is not "
                    f"snake_case",
                )
            elif (
                normalized_vocab
                and v.value not in normalized_vocab
            ):
                yield self.finding(
                    METRICS_MAP_PATH, v.lineno,
                    f"METRIC_MAP value '{v.value}' is not declared "
                    f"in {NORMALIZED_FAMILIES_NAME} (typo, or add "
                    f"the family to the normalized vocabulary)",
                )

    # ---- 3. doc/test references ----------------------------------------

    def _reference_checks(
        self, project: Project, vocab: Set[str]
    ) -> Iterator[Finding]:
        targets: List[str] = ["README.md"]
        import os

        docs_dir = os.path.join(project.root, "docs")
        if os.path.isdir(docs_dir):
            targets += [
                f"docs/{n}" for n in sorted(os.listdir(docs_dir))
                if n.endswith(".md")
            ]
        targets += project.py_files("tests")
        for rel in targets:
            text = project.read_text(rel)
            if text is None:
                continue
            for i, line in enumerate(text.splitlines(), start=1):
                for m in REF_TOKEN.finditer(line):
                    token = m.group(0).rstrip("_:")
                    if self._known(token, vocab):
                        continue
                    yield self.finding(
                        rel, i,
                        f"reference to metric-like name '{token}' that "
                        f"no production code emits or maps",
                    )

    @staticmethod
    def _known(token: str, vocab: Set[str]) -> bool:
        if token in vocab:
            return True
        for suffix in HISTO_SUFFIXES:
            if token.endswith(suffix) and token[: -len(suffix)] in vocab:
                return True
        return False
