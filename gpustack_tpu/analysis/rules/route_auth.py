"""route-auth: every route registered under ``routes/`` resolves a
principal, or is explicitly declared public.

The auth middleware (api/middlewares.py) guarantees *authentication*
for every non-public path, but *authorization* is per-handler: a new
route that never looks at ``request.get("principal")`` (directly or
through a guard like ``require_admin``/``worker_principal``/the crud
factory's ``check_read``/``check_write``, or the tenancy admission
helper) silently serves every authenticated caller the same data —
the exact bug class that turns one leaked low-privilege key into a
cluster-wide read. This rule makes that a deterministic CI failure:

- every ``app.router.add_*(path, handler)`` registration in
  ``gpustack_tpu/routes/*.py`` must either
    * name a path in the middleware's literal ``PUBLIC_PATHS``
      allowlist (truly unauthenticated surfaces: login, SSO
      callbacks, worker registration), or
    * name a path in this rule's own literal ``EXEMPT_PATHS``
      (authenticated-but-principal-agnostic handlers, each justified
      inline), or
    * reach a principal resolution marker somewhere in the handler's
      same-module call graph (transitive, fixpoint over local calls).

Like blocking-in-async, the baseline for this rule must stay EMPTY
forever — new findings are fixed or explicitly exempted with review,
never frozen.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from gpustack_tpu.analysis.core import Finding, Project, Rule

MIDDLEWARES_PATH = "gpustack_tpu/api/middlewares.py"
ROUTES_PREFIX = "gpustack_tpu/routes"

ADD_METHODS = {
    "add_get", "add_post", "add_put", "add_patch", "add_delete",
    "add_head", "add_options", "add_route",
}

# Authenticated routes whose handlers deliberately never inspect the
# principal beyond the middleware's authentication gate. Every entry
# needs a justification — this list is reviewed like code, and the
# rule's empty-baseline contract means additions can't hide.
EXEMPT_PATHS = {
    # clears the session cookie; acting on an absent/expired session
    # is the desired behavior for logout
    "/auth/logout",
    # read-only catalog of deployable model presets — the same static
    # JSON for every authenticated management principal, by design
    # (deploys themselves go through the admin-gated deploy route)
    "/v2/model-catalog",
}

# resolution markers: a call/reference to any of these names counts as
# resolving (or guarding on) the request's principal
GUARD_NAMES = {"require_admin", "worker_principal", "_admit_tenant"}


class RouteAuthRule(Rule):
    id = "route-auth"
    description = (
        "every route registered under routes/ resolves a principal "
        "(or is declared public in a literal allowlist)"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        public = self._public_paths(project)
        if public is None:
            yield self.finding(
                MIDDLEWARES_PATH, 1,
                "PUBLIC_PATHS literal not found (route-auth needs the "
                "middleware's public allowlist to judge routes)",
            )
            return
        for rel in project.py_files(ROUTES_PREFIX):
            src = project.source(rel)
            tree = src.tree if src else None
            if tree is None:
                continue
            funcs = self._function_map(tree)
            resolved = self._resolve_fixpoint(funcs)
            for line, path, handler in self._registrations(tree):
                if path is not None and (
                    path in public or path in EXEMPT_PATHS
                ):
                    continue
                if handler is None:
                    continue  # non-name handler: nothing to judge
                nodes = funcs.get(handler)
                if nodes is None:
                    continue  # defined elsewhere (cross-module factory)
                if not any(resolved.get(id(n)) for n in nodes):
                    where = path if path is not None else "<dynamic>"
                    yield self.finding(
                        rel, line,
                        f"route {where!r} handler '{handler}' never "
                        f"resolves a principal (no "
                        f"request.get(\"principal\") / require_admin / "
                        f"guard in its call graph) and is not in "
                        f"PUBLIC_PATHS or the route-auth EXEMPT_PATHS "
                        f"allowlist",
                    )

    # ---- inputs ---------------------------------------------------------

    def _public_paths(self, project: Project) -> Optional[Set[str]]:
        src = project.source(MIDDLEWARES_PATH)
        tree = src.tree if src else None
        if tree is None:
            return None
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            if not any(
                isinstance(t, ast.Name) and t.id == "PUBLIC_PATHS"
                for t in node.targets
            ):
                continue
            if isinstance(node.value, (ast.Set, ast.List, ast.Tuple)):
                out = set()
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(
                        elt.value, str
                    ):
                        out.add(elt.value)
                return out
        return None

    # ---- per-module analysis --------------------------------------------

    @staticmethod
    def _function_map(tree) -> Dict[str, List[ast.AST]]:
        """name -> every (possibly nested) function def with that name.
        Handlers live inside ``add_*_routes`` factory closures, so
        nested defs must be first-class here."""
        out: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                out.setdefault(node.name, []).append(node)
        return out

    @staticmethod
    def _direct_and_calls(fn) -> Tuple[bool, Set[str]]:
        """(resolves directly?, names of locally-called functions)."""
        direct = False
        calls: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                func = node.func
                if isinstance(func, ast.Name):
                    calls.add(func.id)
                    if func.id in GUARD_NAMES:
                        direct = True
                elif isinstance(func, ast.Attribute):
                    # request.get("principal") / request.get("trace")…
                    if (
                        func.attr == "get"
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and node.args[0].value == "principal"
                    ):
                        direct = True
                    if func.attr in GUARD_NAMES:
                        direct = True
                        calls.add(func.attr)
            elif isinstance(node, ast.Subscript):
                # request["principal"]
                if isinstance(node.slice, ast.Constant) and (
                    node.slice.value == "principal"
                ):
                    direct = True
            elif isinstance(node, ast.Name) and node.id in GUARD_NAMES:
                direct = True
        return direct, calls

    def _resolve_fixpoint(
        self, funcs: Dict[str, List[ast.AST]]
    ) -> Dict[int, bool]:
        """id(fn node) -> does the function reach a principal marker
        through same-module calls (fixpoint over the local call
        graph)."""
        info: Dict[int, Tuple[bool, Set[str]]] = {}
        for nodes in funcs.values():
            for fn in nodes:
                info[id(fn)] = self._direct_and_calls(fn)
        resolved = {key: direct for key, (direct, _) in info.items()}
        changed = True
        while changed:
            changed = False
            for nodes in funcs.values():
                for fn in nodes:
                    if resolved[id(fn)]:
                        continue
                    _, calls = info[id(fn)]
                    for name in calls:
                        if any(
                            resolved.get(id(callee))
                            for callee in funcs.get(name, [])
                        ):
                            resolved[id(fn)] = True
                            changed = True
                            break
        return resolved

    # ---- registrations --------------------------------------------------

    @staticmethod
    def _registrations(tree):
        """Yield ``(line, path|None, handler_name|None)`` for every
        ``<x>.router.add_*(path, handler)`` call (path None when not a
        string literal — dynamic paths are judged on the handler
        alone, with no public exemption possible)."""
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not (
                isinstance(func, ast.Attribute)
                and func.attr in ADD_METHODS
                and isinstance(func.value, ast.Attribute)
                and func.value.attr == "router"
            ):
                continue
            args = node.args
            if func.attr == "add_route":
                args = args[1:]
            if len(args) < 2:
                continue
            path_node, handler_node = args[0], args[1]
            path = (
                path_node.value
                if isinstance(path_node, ast.Constant)
                and isinstance(path_node.value, str)
                else None
            )
            handler = (
                handler_node.id
                if isinstance(handler_node, ast.Name) else None
            )
            yield node.lineno, path, handler
