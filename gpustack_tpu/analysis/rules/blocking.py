"""blocking-in-async: sync calls that stall the event loop.

The control plane is one asyncio loop per process — a single
``time.sleep`` or sync HTTP call inside an ``async def`` in the proxy
path stalls *every* in-flight request on that process. Flagged inside
async scope (nested def/lambda bodies excluded — those are the bodies
handed to ``asyncio.to_thread``/``run_in_executor``):

- known blockers by dotted name (``time.sleep``, ``requests.*``,
  ``subprocess.run``/``check_*``/``Popen``, ``os.system``,
  ``urllib.request.urlopen``, ``sqlite3.connect``, heavy ``shutil``
  tree ops);
- sync file I/O: ``.read()``/``.write()``/etc. on a handle bound by
  ``open(...)`` in the same async scope, and ``json``/``yaml``
  (de)serialization given such a handle.

Fix by wrapping in ``asyncio.to_thread`` / ``run_in_executor`` or
moving the work off the hot path; genuinely-safe cases (e.g. tiny
procfs reads) take ``# analysis: ignore[blocking-in-async]``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from gpustack_tpu.analysis import astutil
from gpustack_tpu.analysis.core import Finding, Project, Rule

BLOCKING_CALLS = {
    "time.sleep",
    "os.system",
    "urllib.request.urlopen",
    "sqlite3.connect",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.Popen",
    "shutil.rmtree",
    "shutil.copytree",
    "shutil.move",
    "shutil.copy",
    "shutil.copy2",
    "shutil.copyfile",
    # directory scans: unbounded work on big dirs / networked FS.
    # (single-inode ops — stat/unlink/rename — are deliberately NOT
    # listed: they are microsecond-scale and flagging them would bury
    # the real stalls in noise)
    "os.listdir",
    "os.walk",
    "os.scandir",
    "glob.glob",
    "glob.iglob",
}

# any attribute call on these modules blocks (sync HTTP clients)
BLOCKING_MODULES = ("requests", "httpx_sync")

FILE_METHODS = {
    "read", "readline", "readlines", "write", "writelines", "flush"
}

# serializers that drive a passed-in handle synchronously
HANDLE_CONSUMERS = {
    "json.load", "json.dump", "yaml.safe_load", "yaml.safe_dump",
    "yaml.load", "yaml.dump", "pickle.load", "pickle.dump",
}


class BlockingInAsyncRule(Rule):
    id = "blocking-in-async"
    description = (
        "sync blocking call (sleep/HTTP/subprocess/file I/O) inside "
        "async def without to_thread/run_in_executor"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        for rel in project.py_files("gpustack_tpu"):
            src = project.source(rel)
            tree = src.tree if src else None
            if tree is None:
                continue
            aliases = astutil.import_aliases(tree)
            for fn in astutil.async_functions(tree):
                handles = astutil.open_handle_names(fn)
                for node in astutil.scope_walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    msg = self._classify(node, aliases, handles)
                    if msg:
                        yield self.finding(
                            rel,
                            node.lineno,
                            f"{msg} in async def {fn.name}()",
                        )

    def _classify(self, call, aliases, handles):
        name = astutil.resolve_call(call, aliases)
        if name is None:
            # open(...).read() style: receiver is itself an open() call
            if isinstance(call.func, ast.Attribute):
                recv = call.func.value
                if (
                    isinstance(recv, ast.Call)
                    and astutil.dotted_name(recv.func) == "open"
                    and call.func.attr in FILE_METHODS
                ):
                    return f"sync file .{call.func.attr}() on open(...)"
            return None
        if name in BLOCKING_CALLS:
            return f"blocking call {name}()"
        head = name.split(".", 1)[0]
        if head in BLOCKING_MODULES and "." in name:
            return f"sync HTTP call {name}()"
        if name in HANDLE_CONSUMERS and any(
            isinstance(a, ast.Name) and a.id in handles
            for a in list(call.args) + [k.value for k in call.keywords]
        ):
            return f"sync file (de)serialization {name}()"
        head_tail = name.rsplit(".", 1)
        if (
            len(head_tail) == 2
            and head_tail[1] in FILE_METHODS
            and head_tail[0] in handles
        ):
            return f"sync file .{head_tail[1]}() on handle " \
                f"'{head_tail[0]}'"
        return None
