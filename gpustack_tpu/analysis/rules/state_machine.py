"""state-machine: ModelInstanceState writes vs. the declared graph.

``schemas/models.py`` declares the authoritative lifecycle next to the
enum itself: ``INSTANCE_STATE_INITIAL``, ``INSTANCE_STATE_TRANSITIONS``
(state -> allowed successors; terminal states map to an empty set) and
``INSTANCE_STATE_WRITERS`` (module path suffix -> states that module is
allowed to write). This rule parses those declarations (pure AST — no
imports) and enforces:

1. the declarations exist and cover the enum exactly — adding a state
   (like PR 2's DRAINING) without declaring its transitions fails;
2. every state is reachable from the initial state and every declared
   successor is a real enum member;
3. every static write site — ``inst.update(state=ModelInstanceState.X)``,
   ``ModelInstance(... state=X)``, ``self._set_state(id, X, ...)``,
   ``inst.state = X`` — targets a state the graph can actually produce,
   from a module declared as a writer of that state. Read sites
   (``filter(state=...)``, comparisons) are ignored.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from gpustack_tpu.analysis import astutil
from gpustack_tpu.analysis.core import Finding, Project, Rule

SCHEMAS_PATH = "gpustack_tpu/schemas/models.py"
ENUM_NAME = "ModelInstanceState"
TRANSITIONS_NAME = "INSTANCE_STATE_TRANSITIONS"
INITIAL_NAME = "INSTANCE_STATE_INITIAL"
WRITERS_NAME = "INSTANCE_STATE_WRITERS"
# disaggregated-serving role tags: assigned once at instance creation
# from the spec's role deficit — the declared writer set (path
# suffixes) lives next to the state declarations
ROLE_WRITERS_NAME = "INSTANCE_ROLE_WRITERS"
KNOWN_ROLES = {"", "prefill", "decode"}

# read idioms: a `state=` keyword on these call targets is a filter
READ_FUNCS = {"filter", "find", "first", "get", "all", "model_validate"}
WRITE_FUNCS = {"update"}
SETTER_FUNCS = {"_set_state", "set_state"}


def _state_attr(node: ast.AST) -> Optional[str]:
    """``ModelInstanceState.X`` attribute -> "X" (``.value`` access and
    plain names return None)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == ENUM_NAME
    ):
        return node.attr
    return None


class StateMachineRule(Rule):
    id = "state-machine"
    description = (
        "ModelInstanceState transition-graph completeness and "
        "write-site conformance"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        src = project.source(SCHEMAS_PATH)
        tree = src.tree if src else None
        if tree is None:
            yield self.finding(
                SCHEMAS_PATH, 1, f"cannot parse {SCHEMAS_PATH}"
            )
            return
        members = self._enum_members(tree)
        if not members:
            yield self.finding(
                SCHEMAS_PATH, 1, f"enum {ENUM_NAME} not found"
            )
            return

        decls, problems = self._declarations(tree, members)
        for line, msg in problems:
            yield self.finding(SCHEMAS_PATH, line, msg)
        if decls is None:
            return
        initial, transitions, writers = decls

        yield from self._graph_checks(members, initial, transitions)
        yield from self._write_site_checks(
            project, members, initial, transitions, writers
        )
        yield from self._role_write_checks(project, tree)

    # ---- declaration parsing -------------------------------------------

    def _enum_members(self, tree: ast.AST) -> Set[str]:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and node.name == ENUM_NAME:
                return {
                    t.id
                    for stmt in node.body
                    if isinstance(stmt, ast.Assign)
                    for t in stmt.targets
                    if isinstance(t, ast.Name)
                }
        return set()

    def _declarations(
        self, tree: ast.AST, members: Set[str]
    ) -> Tuple[
        Optional[Tuple[str, Dict[str, Set[str]], Dict[str, Set[str]]]],
        List[Tuple[int, str]],
    ]:
        initial: Optional[str] = None
        transitions: Optional[Dict[str, Set[str]]] = None
        writers: Optional[Dict[str, Set[str]]] = None
        problems: List[Tuple[int, str]] = []

        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            names = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            if INITIAL_NAME in names:
                initial = _state_attr(node.value)
                if initial is None:
                    problems.append(
                        (node.lineno,
                         f"{INITIAL_NAME} must be {ENUM_NAME}.<member>")
                    )
            elif TRANSITIONS_NAME in names:
                transitions, errs = self._parse_state_dict(
                    node, key_is_state=True
                )
                problems.extend(errs)
            elif WRITERS_NAME in names:
                writers, errs = self._parse_state_dict(
                    node, key_is_state=False
                )
                problems.extend(errs)

        missing = [
            n
            for n, v in (
                (INITIAL_NAME, initial),
                (TRANSITIONS_NAME, transitions),
                (WRITERS_NAME, writers),
            )
            if v is None
        ]
        if missing:
            problems.append(
                (1, "missing declaration(s): " + ", ".join(missing))
            )
            return None, problems
        return (initial, transitions, writers), problems

    def _parse_state_dict(
        self, node: ast.Assign, key_is_state: bool
    ) -> Tuple[Optional[Dict[str, Set[str]]], List[Tuple[int, str]]]:
        problems: List[Tuple[int, str]] = []
        value = node.value
        if not isinstance(value, ast.Dict):
            return None, [(node.lineno, "declaration must be a dict "
                           "literal (parsed statically, not imported)")]
        out: Dict[str, Set[str]] = {}
        for key, val in zip(value.keys, value.values):
            if key_is_state:
                k = _state_attr(key)
            else:
                k = (
                    key.value
                    if isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                    else None
                )
            if k is None:
                problems.append(
                    (getattr(key, "lineno", node.lineno),
                     "unparseable key in state declaration")
                )
                continue
            states: Set[str] = set()
            elts = None
            if isinstance(val, (ast.Set, ast.Tuple, ast.List)):
                elts = val.elts
            elif isinstance(val, ast.Call) and astutil.dotted_name(
                val.func
            ) in ("set", "frozenset"):
                # there is no empty-set literal: `set()` / `frozenset()`
                # (optionally around a container literal) declares one
                if not val.args:
                    elts = []
                elif isinstance(
                    val.args[0], (ast.Set, ast.Tuple, ast.List)
                ):
                    elts = val.args[0].elts
            if elts is None:
                problems.append(
                    (getattr(val, "lineno", node.lineno),
                     f"value for {k} must be a set/tuple/list of "
                     f"{ENUM_NAME} members")
                )
                continue
            for e in elts:
                s = _state_attr(e)
                if s is None:
                    problems.append(
                        (getattr(e, "lineno", node.lineno),
                         f"non-{ENUM_NAME} entry in value for {k}")
                    )
                else:
                    states.add(s)
            if k in out:
                problems.append(
                    (getattr(key, "lineno", node.lineno),
                     f"duplicate key {k} in state declaration")
                )
            out[k] = states
        return out, problems

    # ---- graph checks ---------------------------------------------------

    def _graph_checks(
        self,
        members: Set[str],
        initial: str,
        transitions: Dict[str, Set[str]],
    ) -> Iterator[Finding]:
        if initial not in members:
            yield self.finding(
                SCHEMAS_PATH, 1,
                f"initial state {initial} is not an enum member",
            )
        for state in sorted(members - set(transitions)):
            yield self.finding(
                SCHEMAS_PATH, 1,
                f"state {state} has no entry in {TRANSITIONS_NAME} "
                f"(declare its successors, or an empty set if terminal)",
            )
        for state in sorted(set(transitions) - members):
            yield self.finding(
                SCHEMAS_PATH, 1,
                f"{TRANSITIONS_NAME} declares unknown state {state}",
            )
        for state, succs in sorted(transitions.items()):
            for s in sorted(succs - members):
                yield self.finding(
                    SCHEMAS_PATH, 1,
                    f"transition {state} -> {s} targets unknown state",
                )
        # reachability from the initial state
        seen = {initial}
        frontier = [initial]
        while frontier:
            cur = frontier.pop()
            for nxt in transitions.get(cur, ()):  # pragma: no branch
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        for state in sorted(members - seen):
            yield self.finding(
                SCHEMAS_PATH, 1,
                f"state {state} is unreachable from {initial} in the "
                f"declared transition graph",
            )

    # ---- write sites ----------------------------------------------------

    def _write_site_checks(
        self,
        project: Project,
        members: Set[str],
        initial: str,
        transitions: Dict[str, Set[str]],
        writers: Dict[str, Set[str]],
    ) -> Iterator[Finding]:
        producible = {initial} | {
            s for succs in transitions.values() for s in succs
        }
        for rel in project.py_files("gpustack_tpu"):
            if rel == SCHEMAS_PATH or rel.startswith(
                "gpustack_tpu/analysis/"
            ):
                continue
            src = project.source(rel)
            tree = src.tree if src else None
            if tree is None:
                continue
            allowed = self._allowed_for(rel, writers)
            for line, state, how in self._write_sites(tree):
                if state not in members:
                    yield self.finding(
                        rel, line,
                        f"write of unknown state {state} ({how})",
                    )
                    continue
                if state not in producible:
                    yield self.finding(
                        rel, line,
                        f"state {state} written ({how}) but no declared "
                        f"transition produces it — update "
                        f"{TRANSITIONS_NAME} in {SCHEMAS_PATH}",
                    )
                if allowed is None:
                    yield self.finding(
                        rel, line,
                        f"state write ({how} -> {state}) in a module "
                        f"not declared in {WRITERS_NAME}",
                    )
                elif state not in allowed:
                    yield self.finding(
                        rel, line,
                        f"module is not declared to write {state} "
                        f"({how}) — update {WRITERS_NAME} in "
                        f"{SCHEMAS_PATH}",
                    )

    # ---- role writes (disaggregated serving) ----------------------------

    def _role_write_checks(
        self, project: Project, schemas_tree: ast.AST
    ) -> Iterator[Finding]:
        """``ModelInstance(... role=...)`` constructor writes must come
        from a module declared in ``INSTANCE_ROLE_WRITERS`` (a role is
        assigned exactly once, at creation, from the spec's role
        deficit), and literal role values must be known tags. Scoped to
        the constructor idiom: ``role`` is too common a keyword to flag
        on arbitrary calls."""
        declared: Optional[List[str]] = None
        for node in ast.walk(schemas_tree):
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == ROLE_WRITERS_NAME
                for t in node.targets
            ):
                if isinstance(node.value, (ast.Tuple, ast.List)):
                    declared = [
                        e.value for e in node.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                    ]
        if declared is None:
            yield self.finding(
                SCHEMAS_PATH, 1,
                f"missing declaration: {ROLE_WRITERS_NAME} (tuple of "
                f"module path suffixes allowed to write ModelInstance "
                f"role tags)",
            )
            return
        for rel in project.py_files("gpustack_tpu"):
            if rel == SCHEMAS_PATH or rel.startswith(
                "gpustack_tpu/analysis/"
            ):
                continue
            src = project.source(rel)
            tree = src.tree if src else None
            if tree is None:
                continue
            allowed = any(rel.endswith(suffix) for suffix in declared)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                func = astutil.dotted_name(node.func) or ""
                if func.rsplit(".", 1)[-1] != "ModelInstance":
                    continue
                for kw in node.keywords:
                    if kw.arg != "role":
                        continue
                    if not allowed:
                        yield self.finding(
                            rel, node.lineno,
                            f"ModelInstance role write in a module not "
                            f"declared in {ROLE_WRITERS_NAME}",
                        )
                    if (
                        isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)
                        and kw.value.value not in KNOWN_ROLES
                    ):
                        yield self.finding(
                            rel, node.lineno,
                            f"unknown role tag {kw.value.value!r} "
                            f"(known: {sorted(KNOWN_ROLES)})",
                        )

    @staticmethod
    def _allowed_for(
        rel: str, writers: Dict[str, Set[str]]
    ) -> Optional[Set[str]]:
        for suffix, states in writers.items():
            if rel.endswith(suffix):
                return states
        return None

    def _write_sites(
        self, tree: ast.AST
    ) -> Iterator[Tuple[int, str, str]]:
        """(line, state member, idiom) for every static state write."""
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and tgt.attr == "state"
                    ):
                        s = _state_attr(node.value)
                        if s is not None:
                            yield node.lineno, s, ".state assignment"
                continue
            if not isinstance(node, ast.Call):
                continue
            func = astutil.dotted_name(node.func) or ""
            tail = func.rsplit(".", 1)[-1]
            if tail in SETTER_FUNCS:
                for arg in list(node.args) + [
                    k.value for k in node.keywords
                ]:
                    s = _state_attr(arg)
                    if s is not None:
                        yield node.lineno, s, f"{tail}() call"
                continue
            if tail in READ_FUNCS:
                continue
            is_ctor = tail == "ModelInstance"
            if tail in WRITE_FUNCS or is_ctor:
                for kw in node.keywords:
                    if kw.arg == "state":
                        s = _state_attr(kw.value)
                        if s is not None:
                            yield (
                                node.lineno,
                                s,
                                "constructor" if is_ctor
                                else f"{tail}(state=...)",
                            )
