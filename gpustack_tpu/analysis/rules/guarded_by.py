"""guarded-by: declared lock/owner discipline for shared attributes.

The thread mesh built across the overlapped engine (scheduler loop,
detok worker, KV stager, kv-copy executor, HTTP exporters) shares
plain-attribute state whose safety rests on conventions the code never
declared: "this dict is only touched under ``self._mu``", "this slot
table is scheduler-thread-only". This rule makes the convention a
checked contract. A module opts in with a module-level literal::

    GUARDED_BY = {
        "_index": "_mu",                  # lock-guarded attribute
        "_slots": ("_loop", "step"),      # single-thread-owned: the
                                          # only methods that may touch
        "_waiting": OWNER_GROUP_NAME,     # value may name another
                                          # module-level tuple literal
    }

Semantics, per declared attribute (``self.<attr>`` accesses in every
class of the module; bare-``Name`` accesses too when the module assigns
the name at top level — module-global state like a store registry):

- value is a **string** → the attribute may be read or written only
  (a) lexically inside a ``with self.<lock>:`` (or module-level
  ``with <lock>:``) block within the same function — a nested
  ``def``/``lambda`` does *not* inherit the guard, it may run on any
  thread later; (b) inside a method whose name ends in ``_locked``
  (the repo's caller-holds-the-lock suffix convention); or (c) inside
  ``__init__`` (construction happens-before publication).
- value is a **tuple/list of strings** → an owner list: only those
  methods (plus ``__init__``) may touch the attribute. This is the
  declaration for single-thread-owned state (the scheduler's slot
  table, the detok worker's buffers) where a lock would be overhead.

Keys may be class-qualified (``"Stager._inflight"``) when two classes
in one module reuse an attribute name with different guards; an
unqualified key applies to every class in the module. Reviewed
cross-thread reads that tolerate a torn value (observational gauges on
a health endpoint) take ``# analysis: ignore[guarded-by]``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Set, Tuple, Union

from gpustack_tpu.analysis import astutil
from gpustack_tpu.analysis.core import Finding, Project, Rule

DECLARATION = "GUARDED_BY"

# guard: ("lock", "<lock attr>") or ("owners", frozenset of method names)
Guard = Tuple[str, Union[str, frozenset]]

_FUNCTION_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _module_level_names(tree: ast.Module) -> Set[str]:
    """Names assigned at module top level (module-global state)."""
    names: Set[str] = set()
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name):
                names.add(t.id)
    return names


def _string_tuple(node: ast.AST) -> Optional[frozenset]:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = set()
        for elt in node.elts:
            if not (
                isinstance(elt, ast.Constant)
                and isinstance(elt.value, str)
            ):
                return None
            out.add(elt.value)
        return frozenset(out)
    return None


def declared_guards(tree: ast.Module) -> Dict[str, Guard]:
    """Parse the module-level GUARDED_BY dict literal. Values may be a
    string (lock attr), a tuple/list of strings (owner methods), or a
    Name referring to a module-level tuple literal (shared owner
    group). Unparseable entries are skipped — the declaration is a
    literal contract, not code."""
    literals: Dict[str, ast.AST] = {}
    decl: Optional[ast.Dict] = None
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name):
                literals[tgt.id] = node.value
                if tgt.id == DECLARATION and isinstance(
                    node.value, ast.Dict
                ):
                    decl = node.value
    if decl is None:
        return {}
    guards: Dict[str, Guard] = {}
    for key_node, val in zip(decl.keys, decl.values):
        if not (
            isinstance(key_node, ast.Constant)
            and isinstance(key_node.value, str)
        ):
            continue
        key = key_node.value
        if isinstance(val, ast.Constant) and isinstance(val.value, str):
            guards[key] = ("lock", val.value)
            continue
        owners = _string_tuple(val)
        if owners is None and isinstance(val, ast.Name):
            owners = _string_tuple(literals.get(val.id))
        if owners is not None:
            guards[key] = ("owners", owners)
    return guards


def _with_guards(node: ast.AST, stop: ast.AST) -> Set[str]:
    """Dotted names of every ``with`` context manager between ``node``
    and its nearest enclosing function ``stop`` (exclusive). Walking
    stops at ``stop`` so a closure cannot inherit its definer's lock."""
    held: Set[str] = set()
    cur = getattr(node, "parent", None)
    while cur is not None and cur is not stop:
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                name = astutil.dotted_name(item.context_expr)
                if name:
                    held.add(name)
        cur = getattr(cur, "parent", None)
    return held


class GuardedByRule(Rule):
    id = "guarded-by"
    description = (
        "access to a GUARDED_BY-declared attribute outside its "
        "`with self.<lock>` block / `_locked`-suffix method / "
        "declared owner-method list"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        for rel in project.py_files("gpustack_tpu"):
            src = project.source(rel)
            tree = src.tree if src else None
            if tree is None:
                continue
            guards = declared_guards(tree)
            if not guards:
                continue
            module_names = _module_level_names(tree)
            yield from self._check_module(
                rel, tree, guards, module_names
            )

    def _check_module(
        self,
        rel: str,
        tree: ast.Module,
        guards: Dict[str, Guard],
        module_names: Set[str],
    ) -> Iterator[Finding]:
        bare_keys = {
            k for k in guards
            if "." not in k and k in module_names
        }
        for node in ast.walk(tree):
            attr: Optional[str] = None
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                attr = node.attr
            elif isinstance(node, ast.Name) and node.id in bare_keys:
                attr = node.id
            if attr is None:
                continue
            guard = self._guard_for(node, attr, guards)
            if guard is None:
                continue
            fn = astutil.enclosing(node, _FUNCTION_KINDS)
            if fn is None:
                continue  # module-level (import-time, single-threaded)
            fn_name = getattr(fn, "name", "<lambda>")
            if fn_name == "__init__":
                continue
            kind, spec = guard
            if kind == "owners":
                if fn_name in spec:
                    continue
                yield self.finding(
                    rel,
                    node.lineno,
                    f"'{attr}' is owned by "
                    f"{{{', '.join(sorted(spec))}}} but accessed "
                    f"from {fn_name}()",
                )
                continue
            if fn_name.endswith("_locked"):
                continue
            held = _with_guards(node, fn)
            if f"self.{spec}" in held or spec in held:
                continue
            yield self.finding(
                rel,
                node.lineno,
                f"'{attr}' is guarded by '{spec}' but accessed "
                f"outside `with self.{spec}` in {fn_name}()",
            )

    @staticmethod
    def _guard_for(
        node: ast.AST, attr: str, guards: Dict[str, Guard]
    ) -> Optional[Guard]:
        """Class-qualified key wins over an unqualified one."""
        cls = astutil.enclosing(node, (ast.ClassDef,))
        if cls is not None:
            qualified = guards.get(f"{cls.name}.{attr}")
            if qualified is not None:
                return qualified
        return guards.get(attr)
