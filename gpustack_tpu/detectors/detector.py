"""TPU + system detection.

Replaces the reference's detector stack (fastfetch binary wrapper +
gpustack-runtime NVML probing, reference detectors/detector_factory.py):
on a TPU-VM the source of truth is environment metadata
(``TPU_ACCELERATOR_TYPE`` like "v5litepod-8", ``TPU_TOPOLOGY`` like
"2x4", ``TPU_WORKER_ID``) plus ``/dev/accel*`` device nodes; system info
comes straight from /proc (the C++ ``sysinfo`` tool in native/ provides
the same JSON contract for non-Python consumers).
"""

from __future__ import annotations

import glob
import json
import logging
import os
import platform
from typing import Dict, Optional

from gpustack_tpu.schemas.workers import SliceTopology, TPUChip, WorkerStatus

logger = logging.getLogger(__name__)

# HBM per chip by generation (GiB)
CHIP_HBM_GIB: Dict[str, int] = {
    "v4": 32,
    "v5e": 16,
    "v5p": 95,
    "v6e": 32,
}

_ACCEL_ALIASES = {
    "v5litepod": "v5e",
    "v5lite": "v5e",
    "v5p": "v5p",
    "v6e": "v6e",
    "v4": "v4",
}


def parse_accelerator_type(accel: str):
    """'v5litepod-8' -> ('v5e', 8); 'v4-32' -> ('v4', 32)."""
    if not accel or "-" not in accel:
        return None
    gen_raw, _, count = accel.rpartition("-")
    gen = _ACCEL_ALIASES.get(gen_raw.strip().lower())
    try:
        return (gen, int(count)) if gen else None
    except ValueError:
        return None


class TPUDetector:
    """Detect TPU chips + slice topology on this host."""

    def detect(self) -> WorkerStatus:
        status = WorkerStatus(
            cpu_count=os.cpu_count() or 0,
            os=platform.system(),
            kernel=platform.release(),
            arch=platform.machine(),
        )
        self._fill_memory(status)
        self._fill_tpu(status)
        self._fill_versions(status)
        return status

    def _fill_memory(self, status: WorkerStatus) -> None:
        try:
            info = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    key, _, rest = line.partition(":")
                    info[key.strip()] = rest.strip()
            total = int(info.get("MemTotal", "0 kB").split()[0]) * 1024
            avail = int(info.get("MemAvailable", "0 kB").split()[0]) * 1024
            status.memory_total_bytes = total
            status.memory_used_bytes = max(0, total - avail)
        except (OSError, ValueError, IndexError):
            pass

    def _fill_tpu(self, status: WorkerStatus) -> None:
        accel = os.environ.get("TPU_ACCELERATOR_TYPE", "")
        parsed = parse_accelerator_type(accel)
        devices = sorted(glob.glob("/dev/accel*")) or sorted(
            glob.glob("/dev/vfio/*")
        )
        if parsed is None and not devices:
            return
        if parsed:
            gen, total_chips = parsed
        else:
            gen, total_chips = "v5e", len(devices)
        topology = os.environ.get("TPU_TOPOLOGY", "")
        num_hosts = max(
            1, int(os.environ.get("TPU_WORKER_COUNT", "0") or 0)
        )
        host_index = int(os.environ.get("TPU_WORKER_ID", "0") or 0)
        hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        if num_hosts == 1 and hostnames:
            num_hosts = max(1, len(hostnames.split(",")))
        chips_here = (
            len(devices) if devices else total_chips // num_hosts or 1
        )
        hbm = CHIP_HBM_GIB.get(gen, 16) * 2**30
        status.chips = [
            TPUChip(index=i, chip_type=gen, hbm_bytes=hbm)
            for i in range(chips_here)
        ]
        status.slice = SliceTopology(
            topology=topology,
            chips_per_host=chips_here,
            num_hosts=num_hosts,
            host_index=host_index,
            ici_domain=os.environ.get("TPU_SLICE_NAME", "")
            or (accel if num_hosts > 1 else ""),
        )

    def _fill_versions(self, status: WorkerStatus) -> None:
        try:
            import jax

            status.jax_version = jax.__version__
        except Exception:
            pass
        try:
            import importlib.metadata as md

            status.libtpu_version = md.version("libtpu")
        except Exception:
            pass


class FakeDetector:
    """Fixture-driven detector (tests / simulated fleets)."""

    def __init__(self, fixture_path: str):
        self.fixture_path = fixture_path

    def detect(self) -> WorkerStatus:
        with open(self.fixture_path) as f:
            return WorkerStatus.model_validate(json.load(f))


def create_detector(fake_fixture: Optional[str] = None):
    if fake_fixture:
        return FakeDetector(fake_fixture)
    return TPUDetector()
