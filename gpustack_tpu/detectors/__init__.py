"""Hardware detection (reference gpustack/detectors + gpustack-runtime's
device probing, re-targeted at TPU hosts).

``TPUDetector`` reads TPU-VM environment metadata + /dev/accel* +
/proc; ``FakeDetector`` loads a fixture JSON (the test/fleet-simulation
path, mirroring the reference's fixture-driven worker corpus,
tests/fixtures/workers/*)."""

from gpustack_tpu.detectors.detector import (
    FakeDetector,
    TPUDetector,
    create_detector,
)

__all__ = ["TPUDetector", "FakeDetector", "create_detector"]
