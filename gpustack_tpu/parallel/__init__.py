"""Device-mesh and sharding policies (the TPU parallelism layer).

Replaces the reference's parallelism-argument plumbing (world size =
tp*pp*pcp*dp parsed from engine flags, reference
gpustack/policies/candidate_selectors/vllm_resource_fit_selector.py:109-164;
NCCL rank tables / Ray bootstrap, reference worker/backends/vllm.py:941-1025)
with first-class JAX mesh axes over ICI/DCN.
"""

from gpustack_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_EP,
    AXIS_SP,
    AXIS_TP,
    MESH_AXES,
    MeshPlan,
    make_mesh,
    plan_mesh,
)
from gpustack_tpu.parallel.sharding import (
    activation_pspec,
    cache_pspec,
    logical_pspecs,
    param_pspecs,
    shard_params,
)

__all__ = [
    "AXIS_DP",
    "AXIS_SP",
    "AXIS_EP",
    "AXIS_TP",
    "MESH_AXES",
    "MeshPlan",
    "make_mesh",
    "plan_mesh",
    "param_pspecs",
    "activation_pspec",
    "cache_pspec",
    "logical_pspecs",
    "shard_params",
]
