"""PartitionSpec policies for the transformer params/activations/KV cache.

Two modes:

- ``inference``: Megatron-style TP (heads + FFN width over ``tp``, experts
  over ``ep``), weights replicated over ``dp``/``sp``.
- ``train``: additionally FSDP-shards every large weight over ``dp`` on a
  non-TP dimension; under jit XLA all-gathers weights before use and
  reduce-scatters grads — ZeRO-3 semantics with zero hand-written
  collectives.

The specs are written against the param tree produced by
``models.transformer.init_params`` (stacked ``[L, ...]`` leaves; the layer
axis is never sharded — it is the scan axis).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from gpustack_tpu.parallel.mesh import AXIS_DP, AXIS_EP, AXIS_SP, AXIS_TP


def _layer_rules(train: bool) -> Dict[str, P]:
    fsdp = AXIS_DP if train else None
    return {
        "attn_norm": P(None, None),
        "mlp_norm": P(None, None),
        "wq": P(None, fsdp, AXIS_TP),
        "wk": P(None, fsdp, AXIS_TP),
        "wv": P(None, fsdp, AXIS_TP),
        "wo": P(None, AXIS_TP, fsdp),
        "bq": P(None, AXIS_TP),
        "bk": P(None, AXIS_TP),
        "bv": P(None, AXIS_TP),
        # per-head-dim q/k norms (Qwen3/Gemma3) are tiny: replicate
        "q_norm": P(None, None),
        "k_norm": P(None, None),
        # gemma sandwich norms: replicated like the other norm gains
        "post_attn_norm": P(None, None),
        "post_mlp_norm": P(None, None),
        "w_gate": P(None, fsdp, AXIS_TP),
        "w_up": P(None, fsdp, AXIS_TP),
        "w_down": P(None, AXIS_TP, fsdp),
        "router": P(None, fsdp, None),
        "we_gate": P(None, AXIS_EP, fsdp, AXIS_TP),
        "we_up": P(None, AXIS_EP, fsdp, AXIS_TP),
        "we_down": P(None, AXIS_EP, AXIS_TP, fsdp),
        # DeepSeek MLA: down-projections are small (rank-sized) —
        # replicate; up-projections shard their head-concat dim over tp
        "wq_a": P(None, fsdp, None),
        "q_a_norm": P(None, None),
        "wq_b": P(None, None, AXIS_TP),
        "wkv_a": P(None, fsdp, None),
        "kv_a_norm": P(None, None),
        "wkv_b": P(None, None, AXIS_TP),
        # DeepSeek shared experts: dense-MLP-shaped, same sharding
        "ws_gate": P(None, fsdp, AXIS_TP),
        "ws_up": P(None, fsdp, AXIS_TP),
        "ws_down": P(None, AXIS_TP, fsdp),
        "shared_gate": P(None, None, None),
        "router_bias": P(None, None),
        # GPT-OSS: o-proj bias is hidden-wide (replicate with the
        # norms); sink logits are per-head tiny; expert biases shard
        # with their expert matrices (E over ep, F over tp)
        "bo": P(None, None),
        "sinks": P(None, None),
        "we_gate_b": P(None, AXIS_EP, AXIS_TP),
        "we_up_b": P(None, AXIS_EP, AXIS_TP),
        "we_down_b": P(None, AXIS_EP, None),
    }


def param_pspecs(params: Dict[str, Any], train: bool = False) -> Dict[str, Any]:
    """PartitionSpec tree matching the param tree structure."""
    fsdp = AXIS_DP if train else None
    rules = _layer_rules(train)
    specs: Dict[str, Any] = {
        "embed": P(AXIS_TP, fsdp),
        "final_norm": P(None),
        "layers": {k: rules[k] for k in params["layers"]},
    }
    if "dense_layers" in params:
        # DeepSeek first_k_dense prefix stack (models/transformer.py)
        specs["dense_layers"] = {
            k: rules[k] for k in params["dense_layers"]
        }
    if "lm_head" in params:
        specs["lm_head"] = P(fsdp, AXIS_TP)
    return specs


def activation_pspec(seq_sharded: bool = False) -> P:
    """[B, T, ...] activations: batch over dp, optionally sequence over sp."""
    return P(AXIS_DP, AXIS_SP if seq_sharded else None)


def cache_pspec(long_context: bool = False) -> P:
    """KV cache [L, B, S, H_kv, hd]: rows over dp, heads over tp; the
    sequence dim shards over sp in long-context mode (context parallelism as
    a first-class placement dimension — SURVEY.md §5)."""
    return P(
        None, AXIS_DP, AXIS_SP if long_context else None, AXIS_TP, None
    )


def logical_pspecs(
    params: Dict[str, Any],
    mesh: Mesh,
    train: bool = False,
) -> Dict[str, Any]:
    """NamedSharding tree for the params on ``mesh``."""
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        param_pspecs(params, train=train),
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(
    params: Dict[str, Any],
    mesh: Mesh,
    train: bool = False,
) -> Dict[str, Any]:
    """Place a (host-resident) param tree onto the mesh."""
    shardings = logical_pspecs(params, mesh, train=train)
    return jax.tree.map(jax.device_put, params, shardings)
