"""PartitionSpec policies for the transformer params/activations/KV cache.

The single source of truth is :class:`SpecLayout` — a frozen dataclass
naming the mesh axes and producing every PartitionSpec the serving/
training paths use (params, activations, KV cache, per-slot decode
state, host-read outputs). ``runner.py`` holds one ``SpecLayout`` per
replica so the multi-chip layout is one inspectable object
(``layout.describe()``) instead of inline specs scattered through the
engine.

Two modes:

- ``inference``: Megatron-style TP (heads + FFN width over ``tp``, experts
  over ``ep``), weights replicated over ``dp``/``sp``.
- ``train``: additionally FSDP-shards every large weight over ``dp`` on a
  non-TP dimension; under jit XLA all-gathers weights before use and
  reduce-scatters grads — ZeRO-3 semantics with zero hand-written
  collectives.

The specs are written against the param tree produced by
``models.transformer.init_params`` (stacked ``[L, ...]`` leaves; the layer
axis is never sharded — it is the scan axis).

The module-level helpers (``param_pspecs``/``cache_pspec``/…) are thin
wrappers over a default-axes ``SpecLayout``, kept for the existing call
sites.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from gpustack_tpu.parallel.mesh import AXIS_DP, AXIS_EP, AXIS_SP, AXIS_TP


@dataclasses.dataclass(frozen=True)
class SpecLayout:
    """Declarative dp/sp/ep/tp axis assignment for one model replica.

    Every PartitionSpec the runner dispatches against derives from this
    object, so "how is this replica laid out across chips" has exactly
    one answer — renderable as a dict via :meth:`describe` (served on
    the engine's health surface).
    """

    dp_axis: str = AXIS_DP
    sp_axis: str = AXIS_SP
    ep_axis: str = AXIS_EP
    tp_axis: str = AXIS_TP
    # long-context serving: the KV cache's sequence dim shards over sp
    # for the whole generation (ring-attention prefill / merged decode)
    long_context: bool = False
    # training: dp doubles as the FSDP axis for large weights
    train: bool = False

    @property
    def fsdp_axis(self) -> Optional[str]:
        """The axis large weights FSDP-shard over (None at inference —
        weights replicate across dp)."""
        return self.dp_axis if self.train else None

    # ---- params ---------------------------------------------------------

    def layer_rules(self) -> Dict[str, P]:
        fsdp, tp, ep = self.fsdp_axis, self.tp_axis, self.ep_axis
        return {
            "attn_norm": P(None, None),
            "mlp_norm": P(None, None),
            "wq": P(None, fsdp, tp),
            "wk": P(None, fsdp, tp),
            "wv": P(None, fsdp, tp),
            "wo": P(None, tp, fsdp),
            "bq": P(None, tp),
            "bk": P(None, tp),
            "bv": P(None, tp),
            # per-head-dim q/k norms (Qwen3/Gemma3) are tiny: replicate
            "q_norm": P(None, None),
            "k_norm": P(None, None),
            # gemma sandwich norms: replicated like the other norm gains
            "post_attn_norm": P(None, None),
            "post_mlp_norm": P(None, None),
            "w_gate": P(None, fsdp, tp),
            "w_up": P(None, fsdp, tp),
            "w_down": P(None, tp, fsdp),
            "router": P(None, fsdp, None),
            "we_gate": P(None, ep, fsdp, tp),
            "we_up": P(None, ep, fsdp, tp),
            "we_down": P(None, ep, tp, fsdp),
            # DeepSeek MLA: down-projections are small (rank-sized) —
            # replicate; up-projections shard their head-concat dim over tp
            "wq_a": P(None, fsdp, None),
            "q_a_norm": P(None, None),
            "wq_b": P(None, None, tp),
            "wkv_a": P(None, fsdp, None),
            "kv_a_norm": P(None, None),
            "wkv_b": P(None, None, tp),
            # DeepSeek shared experts: dense-MLP-shaped, same sharding
            "ws_gate": P(None, fsdp, tp),
            "ws_up": P(None, fsdp, tp),
            "ws_down": P(None, tp, fsdp),
            "shared_gate": P(None, None, None),
            "router_bias": P(None, None),
            # GPT-OSS: o-proj bias is hidden-wide (replicate with the
            # norms); sink logits are per-head tiny; expert biases shard
            # with their expert matrices (E over ep, F over tp)
            "bo": P(None, None),
            "sinks": P(None, None),
            "we_gate_b": P(None, ep, tp),
            "we_up_b": P(None, ep, tp),
            "we_down_b": P(None, ep, None),
        }

    def embed(self) -> P:
        return P(self.tp_axis, self.fsdp_axis)

    def lm_head(self) -> P:
        return P(self.fsdp_axis, self.tp_axis)

    def params(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """PartitionSpec tree matching the param tree structure."""
        rules = self.layer_rules()
        specs: Dict[str, Any] = {
            "embed": self.embed(),
            "final_norm": P(None),
            "layers": {k: rules[k] for k in params["layers"]},
        }
        if "dense_layers" in params:
            # DeepSeek first_k_dense prefix stack (models/transformer.py)
            specs["dense_layers"] = {
                k: rules[k] for k in params["dense_layers"]
            }
        if "lm_head" in params:
            specs["lm_head"] = self.lm_head()
        return specs

    # ---- activations / serving state ------------------------------------

    def activations(self, seq_sharded: bool = False) -> P:
        """[B, T, ...] activations: batch over dp, optionally sequence
        over sp."""
        return P(self.dp_axis, self.sp_axis if seq_sharded else None)

    def cache(self) -> P:
        """KV cache [L, B, S, H_kv, hd]: rows over dp, heads over tp;
        the sequence dim shards over sp in long-context mode (context
        parallelism as a first-class placement dimension — SURVEY.md
        §5)."""
        return P(
            None, self.dp_axis,
            self.sp_axis if self.long_context else None,
            self.tp_axis, None,
        )

    def slot_state(self) -> P:
        """Per-slot decode vectors (last_tokens/positions/active/
        sampling): tiny — replicated on every chip."""
        return P(None)

    def replicated(self) -> P:
        """Host-read outputs (sampled tokens, logprobs): forced fully
        replicated so multi-host fetches never span non-addressable
        devices."""
        return P()

    def describe(self) -> Dict[str, Any]:
        """The layout as one inspectable dict (engine health surface)."""
        return {
            "axes": {
                "dp": self.dp_axis, "sp": self.sp_axis,
                "ep": self.ep_axis, "tp": self.tp_axis,
            },
            "train": self.train,
            "long_context": self.long_context,
            "cache": str(self.cache()),
            "slot_state": str(self.slot_state()),
            "activations": str(self.activations(self.long_context)),
            "embed": str(self.embed()),
            "host_read": str(self.replicated()),
        }


def _layer_rules(train: bool) -> Dict[str, P]:
    return SpecLayout(train=train).layer_rules()


def param_pspecs(params: Dict[str, Any], train: bool = False) -> Dict[str, Any]:
    """PartitionSpec tree matching the param tree structure."""
    return SpecLayout(train=train).params(params)


def activation_pspec(seq_sharded: bool = False) -> P:
    """[B, T, ...] activations: batch over dp, optionally sequence over sp."""
    return SpecLayout().activations(seq_sharded)


def cache_pspec(long_context: bool = False) -> P:
    """KV cache [L, B, S, H_kv, hd] spec (see SpecLayout.cache)."""
    return SpecLayout(long_context=long_context).cache()


def logical_pspecs(
    params: Dict[str, Any],
    mesh: Mesh,
    train: bool = False,
) -> Dict[str, Any]:
    """NamedSharding tree for the params on ``mesh``."""
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        param_pspecs(params, train=train),
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(
    params: Dict[str, Any],
    mesh: Mesh,
    train: bool = False,
) -> Dict[str, Any]:
    """Place a (host-resident) param tree onto the mesh."""
    shardings = logical_pspecs(params, mesh, train=train)
    return jax.tree.map(jax.device_put, params, shardings)
