"""Mesh construction and auto-parallelism planning.

Canonical mesh axes (outer → inner; inner axes ride ICI, the outermost rides
DCN on multi-slice deployments):

- ``dp``: data/replica parallelism — independent request batches. Doubles as
  the FSDP weight-sharding axis in the training path.
- ``sp``: sequence/context parallelism — long-context prefill shards the
  sequence dimension here (ring attention / XLA all-gather attention).
- ``ep``: expert parallelism — MoE expert dimension.
- ``tp``: tensor parallelism — attention heads and FFN width.

This replaces the reference's flag-based world-size model
(tp×pp×pcp×dp parsed from vLLM args, reference
vllm_resource_fit_selector.py:109-164): on TPU a parallelism plan is a mesh
shape, and XLA inserts the collectives.

Pipeline parallelism is intentionally absent from the serving mesh: on TPU
slices, TP over ICI dominates PP for inference (no microbatch bubbles, no
per-stage KV replication); DCN-scale pipelining belongs to multi-slice
training, not this engine.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DP = "dp"
AXIS_SP = "sp"
AXIS_EP = "ep"
AXIS_TP = "tp"
MESH_AXES = (AXIS_DP, AXIS_SP, AXIS_EP, AXIS_TP)


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """A concrete parallelism plan: axis sizes for one model replica.

    ``chips`` (the product) is the schedulable unit the scheduler places onto
    a TPU slice — the analogue of the reference's computed world size.
    """

    dp: int = 1
    sp: int = 1
    ep: int = 1
    tp: int = 1

    @property
    def chips(self) -> int:
        return self.dp * self.sp * self.ep * self.tp

    def axis_sizes(self) -> Dict[str, int]:
        return {
            AXIS_DP: self.dp,
            AXIS_SP: self.sp,
            AXIS_EP: self.ep,
            AXIS_TP: self.tp,
        }

    def __str__(self) -> str:
        return f"dp{self.dp}xsp{self.sp}xep{self.ep}xtp{self.tp}"

    @staticmethod
    def parse(s: str) -> "MeshPlan":
        """Parse 'dp2xsp1xep1xtp4' (any subset/order of axes)."""
        sizes = {"dp": 1, "sp": 1, "ep": 1, "tp": 1}
        for part in s.lower().split("x"):
            for ax in sizes:
                if part.startswith(ax):
                    sizes[ax] = int(part[len(ax):])
                    break
            else:
                raise ValueError(f"bad mesh plan component {part!r} in {s!r}")
        return MeshPlan(**sizes)


def make_mesh(
    plan: MeshPlan, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build a named Mesh from a plan over the given (or all) devices.

    Axis order is (dp, sp, ep, tp) outer→inner so that ``tp`` — the most
    communication-heavy axis — maps to the innermost, highest-bandwidth ICI
    neighbors in the default device order.
    """
    explicit = devices is not None
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < plan.chips or (explicit and len(devices) != plan.chips):
        raise ValueError(
            f"plan {plan} needs {plan.chips} devices, got {len(devices)}"
        )
    arr = np.array(devices[: plan.chips]).reshape(
        plan.dp, plan.sp, plan.ep, plan.tp
    )
    return Mesh(arr, MESH_AXES)


def _largest_pow2_divisor(n: int, cap: int) -> int:
    best = 1
    d = 1
    while d <= cap and n % d == 0:
        best = d
        d *= 2
    return best


def plan_mesh(
    n_devices: int,
    num_kv_heads: int,
    num_experts: int = 0,
    long_context: bool = False,
) -> MeshPlan:
    """Auto-parallelism: pick a mesh shape for ``n_devices`` chips.

    Heuristic (serving-oriented):
    - MoE models reserve up to half the factor for EP (expert dimension) —
      an all-TP plan would replicate expert weights and starve HBM.
    - TP up to the KV-head count (beyond that TP replicates KV heads and
      wastes HBM — mirrors the reference's head-divisibility checks,
      base_candidate_selector.py:229-234); under ``long_context`` TP is
      capped at half the remaining factor so SP (context parallelism) gets
      the rest.
    - Any leftover goes to DP (replica throughput).
    """
    if n_devices <= 0 or n_devices & (n_devices - 1):
        raise ValueError(f"device count must be a power of two, got {n_devices}")
    rest = n_devices
    ep = 1
    if num_experts:
        ep = _largest_pow2_divisor(num_experts, max(1, rest // 2))
        rest //= ep
    if long_context and rest >= 2:
        tp = _largest_pow2_divisor(num_kv_heads, rest // 2)
        return MeshPlan(dp=1, sp=rest // tp, ep=ep, tp=tp)
    tp = _largest_pow2_divisor(num_kv_heads, rest)
    return MeshPlan(dp=rest // tp, sp=1, ep=ep, tp=tp)
