"""Functional transformer core (Llama/Qwen/Mistral dense + Mixtral-class MoE).

TPU-first design notes:

- **scan over stacked layers**: per-layer weights are stacked on a leading
  ``[L, ...]`` axis and the block loop is a ``lax.scan`` — compile time stays
  O(1) in depth (an 80-layer Llama-70B traces one block, not eighty).
- **static shapes everywhere**: prefill and decode are separate jit
  specializations over fixed ``[B, T]``; the KV cache is a preallocated
  ``[L, B, S_max, H_kv, hd]`` buffer written in place (slot model, JetStream
  style) — no dynamic shapes, so XLA tiles every matmul onto the MXU.
- **GQA without materializing repeated KV**: queries are reshaped to
  ``[B, T, H_kv, G, hd]`` and contracted against the *unexpanded* KV — saves
  HBM bandwidth, which is the decode bottleneck.
- **bf16 matmuls, fp32 softmax/norm accumulations**.

The reference (gpustack/gpustack) has no model code — its data plane is
vLLM/SGLang in containers; this module is the heart of our in-repo TPU
engine that replaces them (reference worker/backends/vllm.py role).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from gpustack_tpu.models.config import ModelConfig
from gpustack_tpu.models.quant import QuantW

Params = Dict[str, Any]


def _mm(eq: str, x: jax.Array, w) -> jax.Array:
    """Weight matmul that transparently handles int8 ``QuantW`` leaves.

    For quantized weights the contraction runs on the int8 tensor (upcast in
    the MXU feed; the dequantized weight never hits HBM) and the
    per-output-channel scale multiplies the result — valid because every
    weight einsum here puts its scale axes last in the output.
    """
    if isinstance(w, QuantW):
        return jnp.einsum(eq, x, w.q.astype(x.dtype)) * w.s.astype(x.dtype)
    return jnp.einsum(eq, x, w)


def _embed_lookup(embed, tokens: jax.Array, dtype) -> jax.Array:
    if isinstance(embed, QuantW):
        x = jnp.take(embed.q, tokens, axis=0).astype(dtype)
        return x * embed.s[tokens].astype(dtype)[..., None]
    return jnp.take(embed, tokens, axis=0).astype(dtype)


# ---------------------------------------------------------------------------
# KV cache
# ---------------------------------------------------------------------------


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    """Slot-based KV cache: ``k, v`` are ``[L, B, S_max, H_kv, head_dim]``.

    Rows (batch slots) are owned by the engine's slot allocator; positions are
    absolute token indices, so writing at ``positions`` and masking with
    ``cache_index <= query_position`` is all the bookkeeping attention needs.

    Bounds contract: writes use ``dynamic_update_slice``, which CLAMPS
    out-of-range starts instead of failing (static-shape jit semantics) —
    writing at ``position >= max_len`` silently corrupts the tail of the
    cache. Callers (the engine slot allocator) must enforce
    ``position + T <= max_len`` before dispatching a step.
    """

    k: jax.Array
    v: jax.Array

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    @staticmethod
    def create(
        cfg: ModelConfig, batch: int, max_len: int, dtype=None
    ) -> "KVCache":
        if dtype is None:
            # follow the model's compute dtype: K/V written by forward
            # must match the buffer (dynamic_update_slice is dtype-strict)
            dtype = (
                jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
            )
        shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.head_dim)
        return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------


def init_params(
    cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16
) -> Params:
    """Random init with layer weights stacked on a leading [L] axis."""
    d, f, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    keys = iter(jax.random.split(key, 32))

    def w(k, *shape, scale=None):
        scale = scale if scale is not None else 1.0 / math.sqrt(shape[-2])
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    if cfg.is_mla:
        qk = cfg.head_dim
        layers: Dict[str, jax.Array] = {
            "attn_norm": jnp.ones((L, d), dtype),
            "wkv_a": w(
                next(keys), L, d, cfg.kv_lora_rank + cfg.qk_rope_head_dim
            ),
            "kv_a_norm": jnp.ones((L, cfg.kv_lora_rank), dtype),
            "wkv_b": w(
                next(keys), L, cfg.kv_lora_rank,
                cfg.num_heads * (cfg.qk_nope_head_dim + cfg.v_head_dim),
            ),
            "wo": w(next(keys), L, cfg.num_heads * cfg.v_head_dim, d),
            "mlp_norm": jnp.ones((L, d), dtype),
        }
        if cfg.q_lora_rank:
            layers["wq_a"] = w(next(keys), L, d, cfg.q_lora_rank)
            layers["q_a_norm"] = jnp.ones((L, cfg.q_lora_rank), dtype)
            layers["wq_b"] = w(
                next(keys), L, cfg.q_lora_rank, cfg.num_heads * qk
            )
        else:
            layers["wq"] = w(next(keys), L, d, cfg.num_heads * qk)
    else:
        layers = {
            "attn_norm": jnp.ones((L, d), dtype),
            "wq": w(next(keys), L, d, cfg.q_dim),
            "wk": w(next(keys), L, d, cfg.kv_dim),
            "wv": w(next(keys), L, d, cfg.kv_dim),
            "wo": w(next(keys), L, cfg.q_dim, d),
            "mlp_norm": jnp.ones((L, d), dtype),
        }
    if cfg.qkv_bias:
        layers["bq"] = jnp.zeros((L, cfg.q_dim), dtype)
        layers["bk"] = jnp.zeros((L, cfg.kv_dim), dtype)
        layers["bv"] = jnp.zeros((L, cfg.kv_dim), dtype)
    if cfg.o_bias:
        layers["bo"] = jnp.zeros((L, d), dtype)
    if cfg.attn_sinks:
        layers["sinks"] = jnp.zeros((L, cfg.num_heads), jnp.float32)
    if cfg.norm_delta_gain:
        # gemma stores norm gains as deltas: zero == identity gain
        for name in ("attn_norm", "mlp_norm"):
            layers[name] = jnp.zeros((L, d), dtype)
    if cfg.qk_norm:
        init = jnp.zeros if cfg.norm_delta_gain else jnp.ones
        layers["q_norm"] = init((L, cfg.head_dim), dtype)
        layers["k_norm"] = init((L, cfg.head_dim), dtype)
    if cfg.post_norms:
        init = jnp.zeros if cfg.norm_delta_gain else jnp.ones
        layers["post_attn_norm"] = init((L, d), dtype)
        layers["post_mlp_norm"] = init((L, d), dtype)
    if cfg.is_moe:
        fm, E = cfg.moe_intermediate_size, cfg.num_experts
        layers["router"] = w(next(keys), L, d, E)
        layers["we_gate"] = w(next(keys), L, E, d, fm)
        layers["we_up"] = w(next(keys), L, E, d, fm)
        layers["we_down"] = w(next(keys), L, E, fm, d, scale=1.0 / math.sqrt(fm))
        if cfg.shared_expert_intermediate_size:
            fs = cfg.shared_expert_intermediate_size
            layers["ws_gate"] = w(next(keys), L, d, fs)
            layers["ws_up"] = w(next(keys), L, d, fs)
            layers["ws_down"] = w(next(keys), L, fs, d)
            if cfg.shared_expert_gated:
                layers["shared_gate"] = w(next(keys), L, d, 1)
        if cfg.moe_scoring in ("sigmoid", "softmax_topk"):
            # DeepSeek-V3 correction bias / GPT-OSS affine router
            layers["router_bias"] = jnp.zeros((L, E), jnp.float32)
        if cfg.moe_bias:
            layers["we_gate_b"] = jnp.zeros((L, E, fm), dtype)
            layers["we_up_b"] = jnp.zeros((L, E, fm), dtype)
            layers["we_down_b"] = jnp.zeros((L, E, d), dtype)
    else:
        layers["w_gate"] = w(next(keys), L, d, f)
        layers["w_up"] = w(next(keys), L, d, f)
        layers["w_down"] = w(next(keys), L, f, d)

    params: Params = {
        "embed": w(next(keys), cfg.vocab_size, d, scale=0.02),
        "layers": layers,
        "final_norm": (
            jnp.zeros if cfg.norm_delta_gain else jnp.ones
        )((d,), dtype),
    }
    if cfg.is_moe and cfg.first_k_dense:
        # split the stacked tree: a dense prefix stack (own MLP shapes)
        # + the MoE remainder (forward scans them back-to-back)
        kd = cfg.first_k_dense
        moe_keys = (
            "router", "we_gate", "we_up", "we_down",
            "ws_gate", "ws_up", "ws_down", "shared_gate",
            "router_bias", "we_gate_b", "we_up_b", "we_down_b",
        )
        dense: Dict[str, jax.Array] = {
            k: v[:kd] for k, v in layers.items() if k not in moe_keys
        }
        dense["w_gate"] = w(next(keys), kd, d, f)
        dense["w_up"] = w(next(keys), kd, d, f)
        dense["w_down"] = w(next(keys), kd, f, d)
        params["dense_layers"] = dense
        params["layers"] = {k: v[kd:] for k, v in layers.items()}
    if not cfg.tie_word_embeddings:
        params["lm_head"] = w(next(keys), d, cfg.vocab_size)
    return params


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------


def rms_norm(
    x: jax.Array, w: jax.Array, eps: float, delta_gain: bool = False
) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    n = xf * lax.rsqrt(var + eps)
    if delta_gain:
        # gemma convention: stored weight is a delta on a unit gain,
        # multiplied in fp32 before the downcast
        return (n * (1.0 + w.astype(jnp.float32))).astype(x.dtype)
    return n.astype(x.dtype) * w


def _inv_freq(theta: float, head_dim: int) -> jax.Array:
    half = head_dim // 2
    return 1.0 / (
        theta ** (jnp.arange(0, half, dtype=jnp.float32) / half)
    )


def rope_params(cfg: ModelConfig) -> Tuple[jax.Array, float]:
    """(inv_freq, attention_factor) for the main RoPE path.

    Handles HF llama3/linear/yarn scaling plus GGUF ``rope_freqs.weight``
    exports: llama.cpp ships the blended llama3 divisors as a precomputed
    per-frequency tensor instead of metadata (convert_hf_to_gguf
    generate_extra_tensors), surfaced here as ``rs["factors"]`` — those
    divisors are authoritative over the formula when present.
    attention_factor scales sin/cos (squaring into scores), matching HF's
    ``attention_scaling`` on the rotary embedding; it is 1.0 for
    non-yarn types."""
    rs = cfg.rope_scaling or {}
    rope_type = rs.get("rope_type") or rs.get("type")
    factors = rs.get("factors")
    inv = _inv_freq(cfg.rope_theta, cfg.head_dim)
    if rope_type == "yarn":
        yarn_inv, att = yarn_inv_freq(cfg.rope_theta, cfg.head_dim, rs)
        if factors is not None:
            return inv / jnp.asarray(factors, jnp.float32), att
        return yarn_inv, att
    if factors is not None:
        return inv / jnp.asarray(factors, jnp.float32), 1.0
    if rope_type == "linear":
        inv = inv / rs["factor"]
    elif rope_type == "llama3":
        # HF reference semantics: high-freq band (short wavelength) keeps
        # raw frequencies, low-freq band divides by `factor`, and the
        # medium band interpolates between the two.
        factor = rs["factor"]
        low = rs.get("low_freq_factor", 1.0)
        high = rs.get("high_freq_factor", 4.0)
        orig = rs.get("original_max_position_embeddings", 8192)
        wavelen = 2 * math.pi / inv
        smooth = (orig / wavelen - low) / (high - low)
        interpolated = (1 - smooth) * inv / factor + smooth * inv
        inv = jnp.where(
            wavelen > orig / low,
            inv / factor,
            jnp.where(wavelen < orig / high, inv, interpolated),
        )
    elif rope_type not in (None, "default"):
        raise ValueError(
            f"unsupported rope_scaling type {rope_type!r} (supported: "
            "default/linear/llama3/yarn/gguf rope_freqs)"
        )
    return inv, 1.0


def rope_inv_freq(cfg: ModelConfig) -> jax.Array:
    """Inverse RoPE frequencies with HF-compatible scaling (see
    rope_params; this back-compat wrapper drops the attention factor)."""
    return rope_params(cfg)[0]


def yarn_get_mscale(scale: float, m: float = 1.0) -> float:
    """DeepSeek's yarn_get_mscale (modeling_deepseek_v2): attention
    magnitude correction for YaRN-interpolated rope."""
    if scale <= 1:
        return 1.0
    return 0.1 * m * math.log(scale) + 1.0


def yarn_inv_freq(
    theta: float, dim: int, rs: Dict[str, Any]
) -> Tuple[jax.Array, float]:
    """YaRN NTK scaling (HF _compute_yarn_parameters semantics):
    interpolated and extrapolated frequency tables blended over a linear
    ramp between the beta correction dims; returns (inv_freq,
    attention_factor) — the factor scales sin/cos, which squares into
    the attention scores exactly like HF's freqs_cis scaling."""
    factor = float(rs["factor"])
    beta_fast = float(rs.get("beta_fast") or 32)
    beta_slow = float(rs.get("beta_slow") or 1)
    orig = int(
        rs.get("original_max_position_embeddings") or 4096
    )
    mscale = rs.get("mscale")
    mscale_all = rs.get("mscale_all_dim")
    attention_factor = rs.get("attention_factor")

    if attention_factor is None:
        if mscale and mscale_all:
            attention_factor = yarn_get_mscale(
                factor, mscale
            ) / yarn_get_mscale(factor, mscale_all)
        else:
            attention_factor = yarn_get_mscale(factor)

    def correction_dim(n_rot):
        return (
            dim * math.log(orig / (n_rot * 2 * math.pi))
        ) / (2 * math.log(theta))

    low = correction_dim(beta_fast)
    high = correction_dim(beta_slow)
    if rs.get("truncate", True):
        # HF find_correction_range: integer bounds unless the config
        # opts out (GPT-OSS ships truncate: false — fractional ramp)
        low, high = math.floor(low), math.ceil(high)
    low = max(low, 0)
    high = min(high, dim - 1)
    if low == high:
        high += 0.001
    ramp = jnp.clip(
        (jnp.arange(dim // 2, dtype=jnp.float32) - low) / (high - low),
        0.0, 1.0,
    )
    extrapolation_factor = 1.0 - ramp
    pos_freqs = theta ** (
        jnp.arange(0, dim, 2, dtype=jnp.float32) / dim
    )
    inv_extra = 1.0 / pos_freqs
    inv_interp = 1.0 / (factor * pos_freqs)
    inv = (
        inv_interp * (1 - extrapolation_factor)
        + inv_extra * extrapolation_factor
    )
    return inv, float(attention_factor)


def rope_sin_cos(
    positions: jax.Array, inv_freq: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """positions [B, T] -> (sin, cos) each [B, T, head_dim/2], fp32."""
    angles = positions[..., None].astype(jnp.float32) * inv_freq
    return jnp.sin(angles), jnp.cos(angles)


def apply_rope(x: jax.Array, sin: jax.Array, cos: jax.Array) -> jax.Array:
    """HF 'rotate_half' convention. x: [B, T, H, hd], sin/cos: [B, T, hd/2]."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    sin = sin[:, :, None, :].astype(x.dtype)
    cos = cos[:, :, None, :].astype(x.dtype)
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def apply_rope_interleaved(
    x: jax.Array, sin: jax.Array, cos: jax.Array
) -> jax.Array:
    """Interleaved-pair (complex) convention — DeepSeek's decoupled rope
    parts rotate (x[2i], x[2i+1]) pairs (transformers
    modeling_deepseek_v2.apply_rotary_emb via view_as_complex), NOT
    rotate_half. x: [B, T, H, d], sin/cos: [B, T, d/2]."""
    x1, x2 = x[..., 0::2], x[..., 1::2]
    sin = sin[:, :, None, :].astype(x.dtype)
    cos = cos[:, :, None, :].astype(x.dtype)
    o1 = x1 * cos - x2 * sin
    o2 = x1 * sin + x2 * cos
    return jnp.stack([o1, o2], axis=-1).reshape(x.shape)


def _attend(
    q: jax.Array,      # [B, T, Hkv, G, hd]
    k: jax.Array,      # [B, S, Hkv, hd]
    v: jax.Array,      # [B, S, Hkv, hd]
    mask: jax.Array,   # [B, T, S] bool (True = attend)
    scale: float,
    softcap: float = 0.0,
    sinks: Optional[jax.Array] = None,   # [Hkv, G] learned sink logits
) -> jax.Array:
    """Grouped-query attention; fp32 softmax; returns [B, T, Hkv*G*hd].

    ``sinks`` (GPT-OSS, modeling_gpt_oss eager_attention_forward): a
    per-head learned logit joins the softmax DENOMINATOR only — the
    probability mass it absorbs is dropped, softening every real score
    without a corresponding value row."""
    scores = jnp.einsum("bthgd,bshd->bhgts", q, k).astype(jnp.float32) * scale
    if softcap:
        # gemma2 attention-logit softcapping, applied before the mask
        scores = softcap * jnp.tanh(scores / softcap)
    scores = jnp.where(mask[:, None, None, :, :], scores, -1e30)
    if sinks is not None:
        sink = sinks.astype(jnp.float32)[None, :, :, None]  # [1,Hkv,G,1]
        m = jnp.maximum(jnp.max(scores, axis=-1), sink)     # [B,Hkv,G,T]
        p = jnp.exp(scores - m[..., None])
        denom = jnp.sum(p, axis=-1) + jnp.exp(sink - m)
        weights = (p / denom[..., None]).astype(q.dtype)
    else:
        weights = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhgts,bshd->bthgd", weights, v)
    b, t = out.shape[0], out.shape[1]
    return out.reshape(b, t, -1)


def _moe_mlp(
    x: jax.Array,           # [B, T, D]
    router_w: jax.Array,    # [D, E]
    we_gate: jax.Array,     # [E, D, Fm]
    we_up: jax.Array,       # [E, D, Fm]
    we_down: jax.Array,     # [E, Fm, D]
    cfg: ModelConfig,
    router_bias=None,       # [E] sigmoid-selection bias (DeepSeek-V3)
                            # or logit bias (GPT-OSS softmax_topk)
    shared=None,            # (ws_gate, ws_up, ws_down, gate_w|None)
    biases=None,            # (bg [E,Fm], bu [E,Fm], bd [E,D]) GPT-OSS
) -> jax.Array:
    """Mixtral-style top-k MoE, dense-dispatch formulation.

    Every expert runs over every token and the top-k router weights (zeroed
    elsewhere) combine the results. This is collective-free under an ``ep``
    mesh axis sharding the E dimension (each device computes its local experts
    for all tokens; the final contraction is a psum XLA inserts), trading
    FLOPs for zero token-shuffling — the right first tradeoff on TPU where
    MXU FLOPs are cheap and all-to-all is not. A capacity-based dispatch
    kernel is the planned perf upgrade for large-E models.
    """
    # Router math in fp32: top-k selection must not flip on bf16 rounding
    # (which differs between sharded and unsharded contraction orders).
    logits = jnp.einsum(
        "btd,de->bte",
        x.astype(jnp.float32),
        router_w.astype(jnp.float32),
    )
    if cfg.moe_scoring == "sigmoid":
        # DeepSeek-V3: sigmoid scores; SELECTION adds the learned
        # correction bias, the combine WEIGHTS use the raw scores
        scores = jax.nn.sigmoid(logits)
        sel = scores + (router_bias if router_bias is not None else 0.0)
        _, top_idx = lax.top_k(sel, cfg.num_experts_per_tok)
        top_w = jnp.take_along_axis(scores, top_idx, axis=-1)
    elif cfg.moe_scoring == "softmax_topk":
        # GPT-OSS (modeling_gpt_oss GptOssTopKRouter): the router is a
        # true affine map; softmax runs over the SELECTED top-k logits,
        # not the full expert set
        if router_bias is not None:
            logits = logits + router_bias.astype(jnp.float32)
        top_v, top_idx = lax.top_k(logits, cfg.num_experts_per_tok)
        top_w = jax.nn.softmax(top_v, axis=-1)
    else:
        gates = jax.nn.softmax(logits, axis=-1)
        top_w, top_idx = lax.top_k(gates, cfg.num_experts_per_tok)
    if cfg.norm_topk_prob and cfg.moe_scoring != "softmax_topk":
        top_w = top_w / jnp.sum(top_w, axis=-1, keepdims=True)
    # Scatter top-k weights back to a dense [B, T, E] combine tensor.
    combine = jnp.sum(
        jax.nn.one_hot(top_idx, cfg.num_experts, dtype=jnp.float32)
        * top_w[..., None],
        axis=-2,
    ).astype(x.dtype)
    g = _mm("btd,edf->btef", x, we_gate)
    u = _mm("btd,edf->btef", x, we_up)
    if biases is not None:
        bg, bu, _bd = biases
        g = g + bg[None, None].astype(g.dtype)
        u = u + bu[None, None].astype(u.dtype)
    if cfg.moe_act == "gptoss":
        # GptOssExperts: clamped glu — gate capped above, up clamped
        # both ways, (up + 1) multiplies gate*sigmoid(1.702*gate)
        limit = 7.0
        g = jnp.clip(g, None, limit)
        u = jnp.clip(u, -limit, limit)
        h = (u + 1.0) * (g * jax.nn.sigmoid(1.702 * g))
    else:
        h = jax.nn.silu(g) * u
    y = _mm("btef,efd->bted", h, we_down)
    if biases is not None:
        _bg, _bu, bd = biases
        y = y + bd[None, None].astype(y.dtype)
    out = jnp.einsum("bted,bte->btd", y, combine)
    if cfg.routed_scaling_factor != 1.0:
        out = out * jnp.asarray(
            cfg.routed_scaling_factor, out.dtype
        )
    if shared is not None:
        # Shared experts: a dense MLP every token passes through, added
        # to the routed output — ungated (DeepSeek) or gated by
        # sigmoid(x @ g) (Qwen2-MoE)
        ws_gate, ws_up, ws_down, gate_w = shared
        sg = _mm("btd,df->btf", x, ws_gate)
        su = _mm("btd,df->btf", x, ws_up)
        shared_out = _mm("btf,fd->btd", jax.nn.silu(sg) * su, ws_down)
        if gate_w is not None:
            shared_out = shared_out * jax.nn.sigmoid(
                _mm("btd,dg->btg", x, gate_w)
            )
        out = out + shared_out
    return out


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,                # [B, T] int32
    positions: jax.Array,             # [B, T] int32 absolute positions
    cache: Optional[KVCache] = None,
    return_hidden: bool = False,
    attn_impl: str = "xla",
    mesh=None,
    embeds_override: Optional[Tuple[jax.Array, jax.Array]] = None,
) -> Tuple[jax.Array, Optional[KVCache]]:
    """Run the model.

    Without ``cache``: plain causal forward (training / scoring path).
    With ``cache``: writes K/V at ``positions`` into the cache and attends
    over the whole cache with an absolute-position causal mask. ``T > 1`` is
    a prefill step, ``T == 1`` a decode step — same code path, different jit
    specialization.

    ``attn_impl`` selects the prefill attention kernel: ``"xla"`` (einsum
    scores, fine for short prompts), ``"flash"`` (pallas blocked
    online-softmax — no [T, S] score tensor; required for long-context
    prefill), or ``"flash_interpret"`` (same kernel in interpret mode, for
    hermetic CPU tests). Flash applies to the prefill-from-zero cache path
    (T > 1, cache sized to the bucket); decode and the cacheless paths
    always use XLA attention.

    ``attn_impl="ring"`` (requires ``mesh`` with an ``sp`` axis) is the
    sequence-parallel serving path: prefill attention runs as ring
    attention over sp-sharded activations and the KV cache STAYS sharded
    over sp for the whole generation — decode/verify steps attend over the
    sharded cache with an exact pmax/psum online-softmax merge
    (ops/ring_attention.py). This is context parallelism as a first-class
    engine mode, not an arg passthrough (reference carries
    --prefill-context-parallel-size to vLLM and implements nothing:
    vllm_resource_fit_selector.py:118-148).

    Returns ``(logits [B, T, vocab] fp32, updated cache or None)``.
    """
    B, T = tokens.shape
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    x = _embed_lookup(params["embed"], tokens, dtype)
    if embeds_override is not None:
        # VLM token splicing: rows flagged by the mask (image
        # placeholders) take projected vision embeddings instead of the
        # vocab row (models/vlm.py build_mm_prompt)
        ov, ov_mask = embeds_override
        x = jnp.where(ov_mask[..., None], ov.astype(dtype), x)
    if cfg.embed_scale:
        # gemma: embeddings scaled by sqrt(d); HF casts the normalizer
        # to the compute dtype before multiplying
        x = x * jnp.asarray(math.sqrt(cfg.hidden_size)).astype(dtype)
    main_inv, main_att_factor = rope_params(cfg)
    sin, cos = rope_sin_cos(positions, main_inv)
    if main_att_factor != 1.0:
        # yarn on the standard attention path (Qwen/Llama long-context
        # configs): HF's attention_scaling rides cos/sin
        sin = sin * main_att_factor
        cos = cos * main_att_factor
    if cfg.is_mla:
        # decoupled rope: only the qk_rope part rotates, with its own
        # frequency table (interleaved-pair convention); DeepSeek ships
        # YaRN scaling whose attention factor rides the sin/cos tables
        rs = cfg.rope_scaling or {}
        if (rs.get("rope_type") or rs.get("type")) == "yarn":
            mla_inv, att_factor = yarn_inv_freq(
                cfg.rope_theta, cfg.qk_rope_head_dim, rs
            )
        else:
            mla_inv = _inv_freq(cfg.rope_theta, cfg.qk_rope_head_dim)
            att_factor = 1.0
        mla_sin, mla_cos = rope_sin_cos(positions, mla_inv)
        if att_factor != 1.0:
            mla_sin = mla_sin * att_factor
            mla_cos = mla_cos * att_factor
    if cfg.rope_local_theta:
        # gemma3: sliding layers rotate with a separate, unscaled theta
        sin_loc, cos_loc = rope_sin_cos(
            positions, _inv_freq(cfg.rope_local_theta, cfg.head_dim)
        )
    else:
        sin_loc, cos_loc = sin, cos
    scale = 1.0 / math.sqrt(cfg.query_pre_attn_scalar or cfg.head_dim)
    if cfg.is_mla:
        # DeepSeek YaRN applies a SECOND magnitude correction beyond the
        # sin/cos attention_factor: HF/vLLM multiply the softmax scale by
        # yarn_get_mscale(factor, mscale_all_dim)^2 (modeling_deepseek_v2
        # DeepseekV2Attention.__init__; vLLM deepseek_v2.py). For the
        # shipped V2/V3 configs mscale == mscale_all_dim, so the sin/cos
        # factor is 1.0 and THIS term carries the whole correction
        # (~1.59x for V2-Lite's factor=40, mscale_all_dim=0.707).
        rs_ = cfg.rope_scaling or {}
        if (
            (rs_.get("rope_type") or rs_.get("type")) == "yarn"
            and rs_.get("mscale_all_dim")
        ):
            m_ = yarn_get_mscale(
                float(rs_["factor"]), float(rs_["mscale_all_dim"])
            )
            scale = scale * m_ * m_
    hetero = cfg.layer_sliding is not None

    use_flash = (
        attn_impl in ("flash", "flash_interpret")
        and cache is not None
        and T > 1
        and cache.max_len >= T
        and not cfg.sliding_window
        and not cfg.attn_logit_softcap
        and not cfg.attn_sinks
    )
    use_ring = attn_impl == "ring" and cache is not None
    if use_ring and (
        mesh is None or cfg.sliding_window or cfg.attn_logit_softcap
        or cfg.attn_sinks
    ):
        raise ValueError(
            "attn_impl='ring' needs a mesh, no sliding window, no "
            "attention softcapping and no attention sinks"
        )

    # mask[b, t, s] — query t attends key s
    if cache is None:
        causal = positions[:, :, None] >= positions[:, None, :]
        delta = positions[:, :, None] - positions[:, None, :]
    else:
        S = cache.max_len
        cache_pos = jnp.arange(S, dtype=jnp.int32)
        causal = cache_pos[None, None, :] <= positions[:, :, None]
        delta = positions[:, :, None] - cache_pos[None, None, :]
    if hetero:
        # gemma-style alternating layers: both masks exist, each layer
        # picks one inside the scan by its slide flag
        mask_full = causal
        mask_slide = causal & (delta < cfg.sliding_window)
        mask = None
    elif cfg.sliding_window:
        mask = causal & (delta < cfg.sliding_window)
    else:
        mask = causal
    slide_flags = (
        jnp.asarray(cfg.layer_sliding, jnp.bool_)
        if hetero
        else jnp.zeros((cfg.num_layers,), jnp.bool_)
    )
    act = (
        jax.nn.silu
        if cfg.hidden_act == "silu"
        else lambda z: jax.nn.gelu(z, approximate=True)
    )

    def block(x_in: jax.Array, scanned, moe_layer: bool):
        lp, k_cache_l, v_cache_l, slide_flag = scanned
        if hetero:
            mask_l = jnp.where(slide_flag, mask_slide, mask_full)
            sin_b = jnp.where(slide_flag, sin_loc, sin)
            cos_b = jnp.where(slide_flag, cos_loc, cos)
        else:
            mask_l, sin_b, cos_b = mask, sin, cos
        h = rms_norm(
            x_in, lp["attn_norm"], cfg.rms_norm_eps, cfg.norm_delta_gain
        )
        if cfg.is_mla:
            # DeepSeek MLA, served decompressed: latent down-projections
            # + per-head up-projections materialize full K/V (head_dim =
            # qk_nope + qk_rope); v (v_head_dim wide) zero-pads to
            # head_dim so one cache layout serves every family.
            nope, rope_d = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
            if cfg.q_lora_rank:
                q_c = rms_norm(
                    _mm("btd,dr->btr", h, lp["wq_a"]),
                    lp["q_a_norm"], cfg.rms_norm_eps, False,
                )
                q = _mm("btr,rq->btq", q_c, lp["wq_b"])
            else:
                q = _mm("btd,dq->btq", h, lp["wq"])
            q = q.reshape(B, T, cfg.num_heads, cfg.head_dim)
            q_nope, q_pe = q[..., :nope], q[..., nope:]
            kv_a = _mm("btd,dr->btr", h, lp["wkv_a"])
            c_kv = kv_a[..., : cfg.kv_lora_rank]
            k_pe = kv_a[..., cfg.kv_lora_rank:]
            c_kv = rms_norm(
                c_kv, lp["kv_a_norm"], cfg.rms_norm_eps, False
            )
            kv = _mm("btr,rq->btq", c_kv, lp["wkv_b"]).reshape(
                B, T, cfg.num_heads, nope + cfg.v_head_dim
            )
            k_nope, v_small = kv[..., :nope], kv[..., nope:]
            q_pe = apply_rope_interleaved(q_pe, mla_sin, mla_cos)
            k_pe = apply_rope_interleaved(
                k_pe[:, :, None, :], mla_sin, mla_cos
            )
            k_pe = jnp.broadcast_to(
                k_pe, (B, T, cfg.num_heads, rope_d)
            )
            k = jnp.concatenate([k_nope, k_pe], axis=-1)
            v = jnp.concatenate(
                [
                    v_small,
                    jnp.zeros(
                        (B, T, cfg.num_heads,
                         cfg.head_dim - cfg.v_head_dim),
                        v_small.dtype,
                    ),
                ],
                axis=-1,
            )
            q = jnp.concatenate([q_nope, q_pe], axis=-1).reshape(
                B, T, cfg.num_kv_heads, cfg.group_size, cfg.head_dim
            )
        else:
            q = _mm("btd,dq->btq", h, lp["wq"])
            k = _mm("btd,dk->btk", h, lp["wk"])
            v = _mm("btd,dk->btk", h, lp["wv"])
            if cfg.qkv_bias:
                q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
            q = q.reshape(B, T, cfg.num_heads, cfg.head_dim)
            k = k.reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
            v = v.reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
            if cfg.qk_norm:
                # Qwen3/Gemma3: per-head RMSNorm on q/k BEFORE RoPE
                q = rms_norm(
                    q, lp["q_norm"], cfg.rms_norm_eps,
                    cfg.norm_delta_gain,
                )
                k = rms_norm(
                    k, lp["k_norm"], cfg.rms_norm_eps,
                    cfg.norm_delta_gain,
                )
            q = apply_rope(q, sin_b, cos_b).reshape(
                B, T, cfg.num_kv_heads, cfg.group_size, cfg.head_dim
            )
            k = apply_rope(k, sin_b, cos_b)

        sinks_l = (
            lp["sinks"].reshape(cfg.num_kv_heads, cfg.group_size)
            if cfg.attn_sinks else None
        )
        if cache is None:
            attn = _attend(
                q, k, v, mask_l, scale, cfg.attn_logit_softcap,
                sinks=sinks_l,
            )
            new_k, new_v = k_cache_l, v_cache_l
        else:
            # Write this step's K/V into the cache at each row's start
            # position (positions are contiguous per row).
            def write(buf, val, start):
                return lax.dynamic_update_slice(buf, val, (start, 0, 0))

            new_k = jax.vmap(write)(k_cache_l, k, positions[:, 0])
            new_v = jax.vmap(write)(v_cache_l, v, positions[:, 0])
            if use_ring:
                from gpustack_tpu.ops.ring_attention import (
                    sharded_prefill_attention,
                    sp_cache_attention,
                )

                if T > 1 and cache.max_len == T:
                    # prefill-from-zero: ring attention over the
                    # sp-sharded step K/V (== the whole written cache)
                    attn = sharded_prefill_attention(
                        mesh, q, k, v, positions, scale
                    )
                else:
                    # decode / verify: exact attention over the
                    # sp-sharded resident cache
                    attn = sp_cache_attention(
                        mesh, q, new_k, new_v, positions, scale
                    )
            elif use_flash:
                # prefill (from zero or from a chunk/prefix offset):
                # q rows sit at positions offset..offset+T-1 against the
                # freshly written cache; the kernel's q_offset shifts the
                # causal diagonal (all batch rows share one offset — the
                # engine's prefill paths are B=1; pad keys masked via
                # seq_k, pad/garbage cache rows above the last query
                # position are causally invisible)
                from gpustack_tpu.ops.flash_attention import (
                    flash_attention_prefill,
                )

                attn = flash_attention_prefill(
                    q.reshape(B, T, cfg.num_heads, cfg.head_dim),
                    new_k,
                    new_v,
                    scale,
                    interpret=attn_impl == "flash_interpret",
                    q_offset=positions[0, 0],
                )
            else:
                attn = _attend(
                    q, new_k, new_v, mask_l, scale,
                    cfg.attn_logit_softcap,
                    sinks=sinks_l,
                )

        if cfg.is_mla:
            # drop the zero-padded v tail before o_proj (which expects
            # num_heads * v_head_dim inputs)
            attn = attn.reshape(
                B, T, cfg.num_heads, cfg.head_dim
            )[..., : cfg.v_head_dim].reshape(
                B, T, cfg.num_heads * cfg.v_head_dim
            )
        attn_out = _mm("btq,qd->btd", attn, lp["wo"])
        if cfg.o_bias:
            attn_out = attn_out + lp["bo"]
        if cfg.post_norms:
            attn_out = rms_norm(
                attn_out, lp["post_attn_norm"], cfg.rms_norm_eps,
                cfg.norm_delta_gain,
            )
        x_mid = x_in + attn_out

        h2 = rms_norm(
            x_mid, lp["mlp_norm"], cfg.rms_norm_eps, cfg.norm_delta_gain
        )
        if moe_layer:
            mlp = _moe_mlp(
                h2, lp["router"], lp["we_gate"], lp["we_up"], lp["we_down"],
                cfg,
                router_bias=lp.get("router_bias"),
                shared=(
                    (
                        lp["ws_gate"], lp["ws_up"], lp["ws_down"],
                        lp.get("shared_gate"),
                    )
                    if "ws_gate" in lp else None
                ),
                biases=(
                    (lp["we_gate_b"], lp["we_up_b"], lp["we_down_b"])
                    if cfg.moe_bias else None
                ),
            )
        else:
            g = _mm("btd,df->btf", h2, lp["w_gate"])
            u = _mm("btd,df->btf", h2, lp["w_up"])
            mlp = _mm("btf,fd->btd", act(g) * u, lp["w_down"])
        if cfg.post_norms:
            mlp = rms_norm(
                mlp, lp["post_mlp_norm"], cfg.rms_norm_eps,
                cfg.norm_delta_gain,
            )
        return x_mid + mlp, (new_k, new_v)

    # DeepSeek ships heterogeneous stacks: the first first_k_dense
    # layers use a dense MLP, the rest MoE — structurally different
    # params can't share one lax.scan, so the stacks run back-to-back
    # over split slices of the same cache.
    kd = (
        len(next(iter(params["dense_layers"].values())))
        if "dense_layers" in params else 0
    )

    def run_stack(x, stack, k_c, v_c, flags, moe_layer):
        from functools import partial as _partial

        return lax.scan(
            _partial(block, moe_layer=moe_layer),
            x, (stack, k_c, v_c, flags),
        )

    if cache is None:
        L = cfg.num_layers
        def dummy(n):
            return jnp.zeros(
                (n, B, 0, cfg.num_kv_heads, cfg.head_dim), dtype
            )
        if kd:
            x, _ = run_stack(
                x, params["dense_layers"], dummy(kd), dummy(kd),
                slide_flags[:kd], False,
            )
        x, _ = run_stack(
            x, params["layers"], dummy(L - kd), dummy(L - kd),
            slide_flags[kd:], cfg.is_moe,
        )
        new_cache = None
    else:
        if kd:
            x, (k_d, v_d) = run_stack(
                x, params["dense_layers"], cache.k[:kd], cache.v[:kd],
                slide_flags[:kd], False,
            )
        x, (k_new, v_new) = run_stack(
            x, params["layers"], cache.k[kd:], cache.v[kd:],
            slide_flags[kd:], cfg.is_moe,
        )
        if kd:
            k_new = jnp.concatenate([k_d, k_new], axis=0)
            v_new = jnp.concatenate([v_d, v_new], axis=0)
        new_cache = KVCache(k=k_new, v=v_new)

    x = rms_norm(
        x, params["final_norm"], cfg.rms_norm_eps, cfg.norm_delta_gain
    )
    if return_hidden:
        # embeddings path: final normalized hidden states, no LM head
        return x.astype(jnp.float32), new_cache
    if cfg.tie_word_embeddings:
        logits = jnp.einsum("btd,vd->btv", x, params["embed"])
    else:
        logits = _mm("btd,dv->btv", x, params["lm_head"])
    logits = logits.astype(jnp.float32)
    if cfg.final_logit_softcap:
        cap = cfg.final_logit_softcap
        logits = cap * jnp.tanh(logits / cap)
    return logits, new_cache
