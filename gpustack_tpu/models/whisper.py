"""Functional Whisper-class speech-to-text model (encoder-decoder).

The audio modality of the framework (reference serves audio through the
VoxBox backend, worker/backends/vox_box.py:23; BASELINE config 5 pairs
Whisper-large-v3 with SDXL). TPU-first design mirrors the LM core
(models/transformer.py): per-layer weights stacked on a leading [L] axis
with ``lax.scan`` over blocks, static shapes (mel input padded to
``max_source_positions * 2`` frames, decode loop jitted one step at a
time over a preallocated KV cache), bf16 matmuls with fp32
softmax/normalization.

Architecture follows the published Whisper design (conv frontend →
sinusoidal positions → pre-LN transformer encoder; decoder with causal
self-attention + cross-attention, tied output embedding). Weights load
from HF safetensors checkpoints via the same weight-mapping approach as
the LM engine.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class WhisperConfig:
    name: str = "whisper"
    vocab_size: int = 51866
    num_mel_bins: int = 128
    d_model: int = 1280
    encoder_layers: int = 32
    decoder_layers: int = 32
    num_heads: int = 20
    max_source_positions: int = 1500   # encoder frames after conv stride 2
    max_target_positions: int = 448
    eos_token_id: int = 50257
    decoder_start_token_id: int = 50258
    dtype: str = "bfloat16"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    # calculator-facing surface (scheduler/calculator.py treats audio
    # models through the same claim math; whisper shards poorly and fits
    # one chip, so the mesh planner is pinned to tp=1 via num_kv_heads)
    @property
    def num_kv_heads(self) -> int:
        return 1

    @property
    def num_experts(self) -> int:
        return 0

    def kv_cache_bytes_per_token(self, bits: int = 16) -> int:
        # decoder self-attn K+V per position (cross-attn K/V is per
        # request, amortized into overhead)
        return self.decoder_layers * 2 * self.d_model * bits // 8

    def param_count(self) -> int:
        d, v = self.d_model, self.vocab_size
        enc = self.encoder_layers * (4 * d * d + 8 * d * d)   # attn + mlp
        dec = self.decoder_layers * (8 * d * d + 8 * d * d)   # self+cross+mlp
        embed = v * d + self.max_target_positions * d
        conv = 3 * self.num_mel_bins * d + 3 * d * d
        return enc + dec + embed + conv

    def weight_bytes(self, bits: int = 16) -> int:
        return self.param_count() * bits // 8


WHISPER_PRESETS: Dict[str, WhisperConfig] = {
    "whisper-large-v3": WhisperConfig(name="whisper-large-v3"),
    "whisper-small": WhisperConfig(
        name="whisper-small",
        vocab_size=51865,
        num_mel_bins=80,
        d_model=768,
        encoder_layers=12,
        decoder_layers=12,
        num_heads=12,
    ),
    "tiny-whisper": WhisperConfig(
        name="tiny-whisper",
        vocab_size=384,
        num_mel_bins=16,
        d_model=64,
        encoder_layers=2,
        decoder_layers=2,
        num_heads=4,
        max_source_positions=32,
        max_target_positions=32,
        eos_token_id=1,
        decoder_start_token_id=2,
    ),
}


def config_from_hf_whisper(cfg: Dict[str, Any], name: str = "") -> WhisperConfig:
    """Map an HF WhisperConfig dict (config.json) onto WhisperConfig."""
    return WhisperConfig(
        name=name or cfg.get("_name_or_path", "whisper"),
        vocab_size=cfg["vocab_size"],
        num_mel_bins=cfg.get("num_mel_bins", 80),
        d_model=cfg["d_model"],
        encoder_layers=cfg["encoder_layers"],
        decoder_layers=cfg["decoder_layers"],
        num_heads=cfg["encoder_attention_heads"],
        max_source_positions=cfg.get("max_source_positions", 1500),
        max_target_positions=cfg.get("max_target_positions", 448),
        eos_token_id=cfg.get("eos_token_id", 50257),
        decoder_start_token_id=cfg.get("decoder_start_token_id", 50258),
    )


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------


def init_whisper_params(
    cfg: WhisperConfig, key: jax.Array, dtype=jnp.bfloat16
) -> Params:
    d = cfg.d_model
    keys = iter(jax.random.split(key, 24))

    def w(k, *shape, scale=None):
        scale = scale if scale is not None else 1.0 / math.sqrt(shape[-2])
        return (
            jax.random.normal(k, shape, jnp.float32) * scale
        ).astype(dtype)

    def enc_layers(L):
        return {
            "ln1": jnp.ones((L, d), dtype),
            "ln1_b": jnp.zeros((L, d), dtype),
            "wq": w(next(keys), L, d, d),
            "bq": jnp.zeros((L, d), dtype),
            "wk": w(next(keys), L, d, d),
            "wv": w(next(keys), L, d, d),
            "bv": jnp.zeros((L, d), dtype),
            "wo": w(next(keys), L, d, d),
            "bo": jnp.zeros((L, d), dtype),
            "ln2": jnp.ones((L, d), dtype),
            "ln2_b": jnp.zeros((L, d), dtype),
            "w_up": w(next(keys), L, d, 4 * d),
            "b_up": jnp.zeros((L, 4 * d), dtype),
            "w_down": w(next(keys), L, 4 * d, d, scale=1 / math.sqrt(4 * d)),
            "b_down": jnp.zeros((L, d), dtype),
        }

    dec = enc_layers(cfg.decoder_layers)
    dec.update(
        {
            "lnx": jnp.ones((cfg.decoder_layers, d), dtype),
            "lnx_b": jnp.zeros((cfg.decoder_layers, d), dtype),
            "xwq": w(next(keys), cfg.decoder_layers, d, d),
            "xbq": jnp.zeros((cfg.decoder_layers, d), dtype),
            "xwk": w(next(keys), cfg.decoder_layers, d, d),
            "xwv": w(next(keys), cfg.decoder_layers, d, d),
            "xbv": jnp.zeros((cfg.decoder_layers, d), dtype),
            "xwo": w(next(keys), cfg.decoder_layers, d, d),
            "xbo": jnp.zeros((cfg.decoder_layers, d), dtype),
        }
    )

    return {
        "conv1": w(next(keys), 3, cfg.num_mel_bins, d),
        "conv1_b": jnp.zeros((d,), dtype),
        "conv2": w(next(keys), 3, d, d),
        "conv2_b": jnp.zeros((d,), dtype),
        "enc_layers": enc_layers(cfg.encoder_layers),
        "enc_ln": jnp.ones((d,), dtype),
        "enc_ln_b": jnp.zeros((d,), dtype),
        "tok_embed": w(next(keys), cfg.vocab_size, d, scale=0.02),
        "pos_embed": w(next(keys), cfg.max_target_positions, d, scale=0.02),
        "dec_layers": dec,
        "dec_ln": jnp.ones((d,), dtype),
        "dec_ln_b": jnp.zeros((d,), dtype),
    }


# ---------------------------------------------------------------------------
# Blocks
# ---------------------------------------------------------------------------


def _ln(x, w, b, eps=1e-5):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    return ((xf - mu) * lax.rsqrt(var + eps)).astype(x.dtype) * w + b


def _heads(x, n):  # [B, T, D] -> [B, T, H, hd]
    B, T, D = x.shape
    return x.reshape(B, T, n, D // n)


def _mha(q, k, v, scale, causal_mask=None):
    """q/k/v: [B, T, H, hd]; fp32 softmax; returns [B, Tq, D]."""
    scores = (
        jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32) * scale
    )
    if causal_mask is not None:
        scores = jnp.where(causal_mask, scores, -1e30)
    weights = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhts,bshd->bthd", weights, v)
    B, T = out.shape[0], out.shape[1]
    return out.reshape(B, T, -1)


def sinusoids(length: int, channels: int) -> jax.Array:
    """Whisper's fixed sinusoidal encoder positions."""
    log_timescale = math.log(10000) / (channels // 2 - 1)
    inv = jnp.exp(-log_timescale * jnp.arange(channels // 2))
    scaled = jnp.arange(length)[:, None] * inv[None, :]
    return jnp.concatenate([jnp.sin(scaled), jnp.cos(scaled)], axis=1)


def _conv1d(x, w, b, stride: int):
    """x [B, T, Cin], w [K, Cin, Cout] — SAME padding, like Whisper's
    torch Conv1d(kernel=3, padding=1)."""
    return (
        lax.conv_general_dilated(
            x,
            w,
            window_strides=(stride,),
            padding=((1, 1),),
            dimension_numbers=("NHC", "HIO", "NHC"),
        )
        + b
    )


def encode(params: Params, cfg: WhisperConfig, mel: jax.Array) -> jax.Array:
    """mel [B, frames, n_mels] (frames = 2 * max_source_positions) ->
    encoder states [B, max_source_positions, D]."""
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    x = mel.astype(dtype)
    x = jax.nn.gelu(_conv1d(x, params["conv1"], params["conv1_b"], 1))
    x = jax.nn.gelu(_conv1d(x, params["conv2"], params["conv2_b"], 2))
    x = x + sinusoids(x.shape[1], cfg.d_model).astype(dtype)
    scale = 1.0 / math.sqrt(cfg.head_dim)

    def block(x_in, lp):
        h = _ln(x_in, lp["ln1"], lp["ln1_b"])
        q = _heads(h @ lp["wq"] + lp["bq"], cfg.num_heads)
        k = _heads(h @ lp["wk"], cfg.num_heads)
        v = _heads(h @ lp["wv"] + lp["bv"], cfg.num_heads)
        x_mid = x_in + _mha(q, k, v, scale) @ lp["wo"] + lp["bo"]
        h2 = _ln(x_mid, lp["ln2"], lp["ln2_b"])
        mlp = jax.nn.gelu(h2 @ lp["w_up"] + lp["b_up"])
        return x_mid + mlp @ lp["w_down"] + lp["b_down"], None

    x, _ = lax.scan(block, x, params["enc_layers"])
    return _ln(x, params["enc_ln"], params["enc_ln_b"])


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DecCache:
    """Decoder KV cache: self-attn K/V [L, B, S, H, hd]."""

    k: jax.Array
    v: jax.Array

    @staticmethod
    def create(cfg: WhisperConfig, batch: int, dtype=jnp.bfloat16):
        shape = (
            cfg.decoder_layers, batch, cfg.max_target_positions,
            cfg.num_heads, cfg.head_dim,
        )
        return DecCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


def cross_kv(
    params: Params, cfg: WhisperConfig, enc_states: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Project encoder states to per-layer cross-attn K/V ONCE per
    utterance ([L, B, S_enc, H, hd] each) — recomputing them inside every
    decode step would redo L x 2 projections over 1500 positions per
    generated token."""
    dl = params["dec_layers"]

    def proj(enc, wk, wv, bv):
        k = _heads(enc @ wk, cfg.num_heads)
        v = _heads(enc @ wv + bv, cfg.num_heads)
        return k, v

    return jax.vmap(proj, in_axes=(None, 0, 0, 0))(
        enc_states, dl["xwk"], dl["xwv"], dl["xbv"]
    )


def decode_step(
    params: Params,
    cfg: WhisperConfig,
    tokens: jax.Array,      # [B, 1] int32
    position: jax.Array,    # scalar int32
    xk: jax.Array,          # [L, B, S_enc, H, hd] from cross_kv
    xv: jax.Array,
    cache: DecCache,
) -> Tuple[jax.Array, DecCache]:
    """One decode step; returns (logits [B, vocab], cache')."""
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    B = tokens.shape[0]
    x = jnp.take(params["tok_embed"], tokens[:, 0], axis=0).astype(dtype)
    x = x + params["pos_embed"][position].astype(dtype)
    x = x[:, None, :]                                     # [B, 1, D]
    scale = 1.0 / math.sqrt(cfg.head_dim)
    S = cfg.max_target_positions
    mask = (jnp.arange(S)[None, None, None, :] <= position)

    def block(x_in, scanned):
        lp, k_cache_l, v_cache_l, xk_l, xv_l = scanned
        h = _ln(x_in, lp["ln1"], lp["ln1_b"])
        q = _heads(h @ lp["wq"] + lp["bq"], cfg.num_heads)
        k = _heads(h @ lp["wk"], cfg.num_heads)
        v = _heads(h @ lp["wv"] + lp["bv"], cfg.num_heads)
        new_k = lax.dynamic_update_slice(
            k_cache_l, k, (0, position, 0, 0)
        )
        new_v = lax.dynamic_update_slice(
            v_cache_l, v, (0, position, 0, 0)
        )
        x_mid = x_in + _mha(q, new_k, new_v, scale, mask) @ lp["wo"] + lp["bo"]
        hx = _ln(x_mid, lp["lnx"], lp["lnx_b"])
        xq = _heads(hx @ lp["xwq"] + lp["xbq"], cfg.num_heads)
        x_mid = x_mid + _mha(xq, xk_l, xv_l, scale) @ lp["xwo"] + lp["xbo"]
        h2 = _ln(x_mid, lp["ln2"], lp["ln2_b"])
        mlp = jax.nn.gelu(h2 @ lp["w_up"] + lp["b_up"])
        return x_mid + mlp @ lp["w_down"] + lp["b_down"], (new_k, new_v)

    x, (k_new, v_new) = lax.scan(
        block, x, (params["dec_layers"], cache.k, cache.v, xk, xv)
    )
    x = _ln(x, params["dec_ln"], params["dec_ln_b"])
    logits = jnp.einsum("btd,vd->btv", x, params["tok_embed"])
    return logits[:, 0].astype(jnp.float32), DecCache(k_new, v_new)


def greedy_transcribe(
    params: Params,
    cfg: WhisperConfig,
    mel: np.ndarray,        # [frames, n_mels]
    prompt_ids: Tuple[int, ...] = (),
    max_tokens: int = 0,
) -> list:
    """Greedy decode one utterance; returns generated token ids."""
    max_tokens = max_tokens or cfg.max_target_positions
    enc = jax.jit(encode, static_argnums=1)(
        params, cfg, jnp.asarray(mel)[None]
    )
    xk, xv = jax.jit(cross_kv, static_argnums=1)(params, cfg, enc)
    step = jax.jit(decode_step, static_argnums=1)
    cache = DecCache.create(cfg, 1)
    ids = [cfg.decoder_start_token_id, *prompt_ids]
    out = []
    # feed the forced prompt, then generate
    pos = 0
    token = ids[0]
    for pos in range(
        min(cfg.max_target_positions - 1, len(ids) - 1 + max_tokens)
    ):
        logits, cache = step(
            params, cfg,
            jnp.asarray([[token]], jnp.int32),
            jnp.int32(pos),
            xk, xv,
            cache,
        )
        if pos + 1 < len(ids):
            token = ids[pos + 1]        # forced prompt token
            continue
        token = int(jnp.argmax(logits[0]))
        if token == cfg.eos_token_id:
            break
        out.append(token)
    return out
