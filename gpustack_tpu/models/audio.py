"""Audio frontend: WAV decoding + Whisper-convention log-mel features.

Pure numpy + stdlib ``wave`` (no audio dependencies exist in the image;
WAV/PCM covers the transcription API contract — compressed formats can
slot in behind the same function when a decoder is available).

The mel pipeline matches the published Whisper recipe: 16 kHz input,
25 ms Hann window / 10 ms hop STFT, triangular mel filterbank,
log10 clamped to (max - 8), scaled to roughly [-1, 1].
"""

from __future__ import annotations

import io
import wave

import numpy as np

SAMPLE_RATE = 16000
N_FFT = 400
HOP = 160
CHUNK_SECONDS = 30


def decode_wav(data: bytes) -> np.ndarray:
    """WAV bytes -> mono float32 [-1, 1] at 16 kHz (naive resample)."""
    with wave.open(io.BytesIO(data)) as wf:
        rate = wf.getframerate()
        n = wf.getnframes()
        width = wf.getsampwidth()
        channels = wf.getnchannels()
        raw = wf.readframes(n)
    if width == 2:
        x = np.frombuffer(raw, np.int16).astype(np.float32) / 32768.0
    elif width == 4:
        x = np.frombuffer(raw, np.int32).astype(np.float32) / 2**31
    elif width == 1:
        x = (
            np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0
        ) / 128.0
    else:
        raise ValueError(f"unsupported WAV sample width {width}")
    if channels > 1:
        x = x.reshape(-1, channels).mean(axis=1)
    if rate != SAMPLE_RATE:
        # linear interpolation resample — adequate for speech features
        target_len = int(round(len(x) * SAMPLE_RATE / rate))
        x = np.interp(
            np.linspace(0, len(x) - 1, target_len),
            np.arange(len(x)),
            x,
        ).astype(np.float32)
    return x


def mel_filterbank(n_mels: int, n_fft: int = N_FFT) -> np.ndarray:
    """Slaney-convention mel filterbank [n_mels, n_fft//2 + 1].

    Matches librosa.filters.mel defaults (Slaney mel scale — linear below
    1 kHz — and Slaney area normalization), which is what Whisper
    checkpoints were trained against; an HTK/unnormalized bank shifts
    per-band log energies by 1-2 orders of magnitude and feeds the
    encoder out-of-distribution features.
    """

    def hz_to_mel(f):
        f = np.asarray(f, np.float64)
        mel = f * 3.0 / 200.0
        log_region = f >= 1000.0
        mel = np.where(
            log_region,
            15.0 + np.log(np.maximum(f, 1e-10) / 1000.0) / np.log(6.4) * 27.0,
            mel,
        )
        return mel

    def mel_to_hz(m):
        m = np.asarray(m, np.float64)
        f = m * 200.0 / 3.0
        log_region = m >= 15.0
        return np.where(
            log_region, 1000.0 * np.exp(np.log(6.4) * (m - 15.0) / 27.0), f
        )

    fmax = SAMPLE_RATE / 2
    fftfreqs = np.linspace(0, fmax, n_fft // 2 + 1)
    mel_f = mel_to_hz(
        np.linspace(hz_to_mel(0.0), hz_to_mel(fmax), n_mels + 2)
    )
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    fb = np.maximum(0.0, np.minimum(lower, upper))
    # Slaney norm: each triangle integrates to ~constant energy
    fb *= (2.0 / (mel_f[2: n_mels + 2] - mel_f[:n_mels]))[:, None]
    return fb.astype(np.float32)


def log_mel(
    audio: np.ndarray, n_mels: int, chunk_seconds: int = CHUNK_SECONDS
) -> np.ndarray:
    """float32 PCM -> [frames, n_mels]; padded/truncated to the fixed
    chunk length (static shapes for the jitted encoder)."""
    target = chunk_seconds * SAMPLE_RATE
    if len(audio) < target:
        audio = np.pad(audio, (0, target - len(audio)))
    else:
        audio = audio[:target]
    # centered STFT (reflect pad n_fft/2 each side, drop the final
    # frame): 30 s -> exactly 3000 frames, the Whisper recipe
    audio = np.pad(audio, (N_FFT // 2, N_FFT // 2), mode="reflect")
    window = np.hanning(N_FFT + 1)[:-1].astype(np.float32)
    n_frames = (len(audio) - N_FFT) // HOP + 1
    idx = (
        np.arange(N_FFT)[None, :] + HOP * np.arange(n_frames)[:, None]
    )
    frames = audio[idx] * window
    spec = np.abs(np.fft.rfft(frames, axis=1)) ** 2        # [T, F]
    spec = spec[:-1]                                       # drop last
    mel = spec @ mel_filterbank(n_mels).T                  # [T, n_mels]
    log_spec = np.log10(np.maximum(mel, 1e-10))
    log_spec = np.maximum(log_spec, log_spec.max() - 8.0)
    return ((log_spec + 4.0) / 4.0).astype(np.float32)


def mel_frames_for(cfg) -> int:
    """Frames the encoder expects: conv stride 2 halves the time axis."""
    return cfg.max_source_positions * 2


def features_for_model(audio: np.ndarray, cfg) -> np.ndarray:
    """PCM -> mel features sized exactly for ``cfg`` ([2*S_pos, n_mels])."""
    frames = mel_frames_for(cfg)
    # chunk length that yields `frames` frames at the standard hop
    seconds = max(1, int(np.ceil((frames * HOP + N_FFT) / SAMPLE_RATE)))
    mel = log_mel(audio, cfg.num_mel_bins, chunk_seconds=seconds)
    if mel.shape[0] < frames:
        mel = np.pad(mel, ((0, frames - mel.shape[0]), (0, 0)))
    return mel[:frames]
