"""Model hyperparameter config for the transformer core.

Replaces the reference's scattered HF-config probing (reference
gpustack/policies/candidate_selectors/base_candidate_selector.py:56-165 parses
hidden_size / num_attention_heads / num_key_value_heads / moe experts for
memory estimation) with one typed config that both the serving engine and the
scheduler's HBM estimator consume.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Architecture description for a Llama/Qwen/Mistral/Mixtral-class LM.

    Attention type is derived, not stored: MHA when num_kv_heads ==
    num_heads, GQA when 1 < num_kv_heads < num_heads, MQA when
    num_kv_heads == 1 (mirrors the attention-type taxonomy the reference
    scheduler uses for KV-cache sizing,
    base_candidate_selector.py:148-165).
    """

    name: str = "custom"
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    rope_theta: float = 500000.0
    # HF-style rope_scaling dict: {"rope_type": "llama3"|"linear", "factor": ..}
    rope_scaling: Optional[Dict[str, Any]] = None
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    qkv_bias: bool = False          # Qwen2-style attention bias
    qk_norm: bool = False           # Qwen3-style per-head q/k RMSNorm
    max_position_embeddings: int = 8192
    sliding_window: int = 0         # 0 = full attention
    # ---- Gemma-family knobs (Gemma2/Gemma3 text) ----
    hidden_act: str = "silu"        # "gelu_tanh" for gemma
    norm_delta_gain: bool = False   # RMSNorm gain stored as (1 + w)
    embed_scale: bool = False       # scale embeddings by sqrt(hidden)
    post_norms: bool = False        # sandwich post-attn/post-mlp norms
    query_pre_attn_scalar: float = 0.0  # 0 = scale by 1/sqrt(head_dim)
    attn_logit_softcap: float = 0.0     # 0 = no softcapping
    final_logit_softcap: float = 0.0
    # per-layer sliding flags (True = sliding_attention); None = use the
    # global sliding_window for every layer (Mistral-style)
    layer_sliding: Optional[Tuple[bool, ...]] = None
    # rope theta for sliding layers (gemma3 local attention); 0 = shared
    rope_local_theta: float = 0.0
    # MoE (Mixtral / Qwen-MoE class); num_experts == 0 means dense MLP.
    num_experts: int = 0
    num_experts_per_tok: int = 0
    moe_intermediate_size: int = 0
    norm_topk_prob: bool = True
    # ---- DeepSeek-family knobs ----
    # MLA (multi-head latent attention, DeepSeek-V2/V3): kv_lora_rank>0
    # switches the attention block to compressed-latent projections. The
    # engine serves the DECOMPRESSED form: per-head K/V are materialized
    # (head_dim = qk_nope + qk_rope, num_kv_heads = num_heads) so the
    # existing cache/flash/ring machinery applies unchanged; v (width
    # v_head_dim) is zero-padded to head_dim in the cache and sliced
    # before o_proj. Trades cache bytes for zero structural divergence.
    q_lora_rank: int = 0            # 0 = direct q projection
    kv_lora_rank: int = 0           # >0 = MLA
    qk_nope_head_dim: int = 0
    qk_rope_head_dim: int = 0
    v_head_dim: int = 0
    # DeepSeek MoE: shared experts run on every token alongside routed
    # ones; routed outputs scale by routed_scaling_factor. The first
    # first_k_dense layers use a dense MLP (v2/v3 checkpoints ship 1).
    n_shared_experts: int = 0
    shared_expert_intermediate_size: int = 0
    # Qwen2-MoE: the shared expert's output is gated by
    # sigmoid(x @ gate); DeepSeek adds it ungated
    shared_expert_gated: bool = False
    routed_scaling_factor: float = 1.0
    first_k_dense: int = 0
    # "softmax" (v2) | "sigmoid" (v3: score + e_score_correction_bias)
    # | "softmax_topk" (GPT-OSS: softmax over the selected top-k logits)
    moe_scoring: str = "softmax"
    # ---- GPT-OSS knobs ----
    # learned per-head attention-sink logits (join the softmax
    # denominator only — modeling_gpt_oss eager_attention_forward)
    attn_sinks: bool = False
    o_bias: bool = False            # bias on the attention out proj
    # expert activation: "silu" (swiglu) | "gptoss" (clamped
    # gate*sigmoid(1.702*gate), combined as (up+1)*glu) — experts carry
    # biases on gate/up/down when moe_bias is set
    moe_act: str = "silu"
    moe_bias: bool = False
    dtype: str = "bfloat16"

    # ---- derived ----
    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    @property
    def group_size(self) -> int:
        return self.num_heads // self.num_kv_heads

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @property
    def is_mla(self) -> bool:
        return self.kv_lora_rank > 0

    @property
    def attention_type(self) -> str:
        if self.num_kv_heads == 1:
            return "MQA"
        if self.num_kv_heads == self.num_heads:
            return "MHA"
        return "GQA"

    def validate(self) -> "ModelConfig":
        assert self.num_heads % self.num_kv_heads == 0, (
            "num_heads must be divisible by num_kv_heads"
        )
        if self.is_moe:
            assert self.num_experts_per_tok > 0
            assert self.moe_intermediate_size > 0
        if self.layer_sliding is not None:
            assert len(self.layer_sliding) == self.num_layers
            assert self.sliding_window > 0
        return self

    # ---- memory accounting (used by scheduler + engine sizing) ----
    def param_count(self) -> int:
        """Exact parameter count for this architecture."""
        d, v = self.hidden_size, self.vocab_size
        embed = v * d
        lm_head = 0 if self.tie_word_embeddings else d * v
        if self.is_mla:
            qk_dim = self.qk_nope_head_dim + self.qk_rope_head_dim
            if self.q_lora_rank:
                attn = (
                    d * self.q_lora_rank
                    + self.q_lora_rank * self.num_heads * qk_dim
                    + self.q_lora_rank
                )
            else:
                attn = d * self.num_heads * qk_dim
            attn += (
                d * (self.kv_lora_rank + self.qk_rope_head_dim)
                + self.kv_lora_rank * self.num_heads
                * (self.qk_nope_head_dim + self.v_head_dim)
                + self.kv_lora_rank
                + self.num_heads * self.v_head_dim * d
            )
        else:
            attn = d * self.q_dim + 2 * d * self.kv_dim + self.q_dim * d
            if self.qkv_bias:
                attn += self.q_dim + 2 * self.kv_dim
            if self.qk_norm:
                attn += 2 * self.head_dim
        if self.is_moe:
            mlp = d * self.num_experts + self.num_experts * (
                3 * d * self.moe_intermediate_size
            )
            if self.shared_expert_intermediate_size:
                mlp += 3 * d * self.shared_expert_intermediate_size
            if self.moe_scoring == "sigmoid":
                mlp += self.num_experts     # e_score_correction_bias
        else:
            mlp = 3 * d * self.intermediate_size
        norms = (4 if self.post_norms else 2) * d
        per_layer = attn + mlp + norms
        dense_delta = 0
        if self.is_moe and self.first_k_dense:
            dense_delta = self.first_k_dense * (
                3 * d * self.intermediate_size - mlp
            )
        return (
            embed + lm_head + self.num_layers * per_layer
            + dense_delta + d
        )

    def weight_bytes(self, bits: int = 16) -> int:
        return self.param_count() * bits // 8

    def kv_cache_bytes_per_token(self, bits: int = 16) -> int:
        """Bytes of K+V cache per token position (all layers)."""
        return 2 * self.num_layers * self.kv_dim * bits // 8


def config_from_hf(cfg: Dict[str, Any], name: str = "custom") -> ModelConfig:
    """Build a ModelConfig from an HF ``config.json`` dict.

    Covers LlamaForCausalLM / Qwen2ForCausalLM / MistralForCausalLM /
    MixtralForCausalLM / Qwen2MoeForCausalLM-style keys — the same families the
    reference's selectors introspect (base_candidate_selector.py:56-165).
    """
    hidden = cfg["hidden_size"]
    heads = cfg["num_attention_heads"]
    head_dim = cfg.get("head_dim") or hidden // heads
    archs = cfg.get("architectures") or [""]
    arch = archs[0] if archs else ""
    num_experts = (
        cfg.get("num_local_experts")      # Mixtral
        or cfg.get("num_experts")         # Qwen2-MoE
        or cfg.get("n_routed_experts")    # DeepSeek-V2/V3
        or 0
    )
    deepseek = "Deepseek" in arch
    mla = deepseek and int(cfg.get("kv_lora_rank") or 0) > 0
    if mla:
        qk_nope = int(cfg.get("qk_nope_head_dim") or 0)
        qk_rope = int(cfg.get("qk_rope_head_dim") or 0)
        # decompressed MLA: the cache is per-head over the full qk dim
        head_dim = qk_nope + qk_rope
    if deepseek and int(cfg.get("n_group") or 1) > 1:
        # group-limited expert routing selects a DIFFERENT expert set
        # than plain top-k — serving it ungrouped would be silently
        # wrong logits, for any topk_method
        raise ValueError(
            "DeepSeek group-limited routing (n_group>1) is not "
            "supported yet; serve a checkpoint with n_group=1"
        )
    # Gemma2/Gemma3 text: (1+w) norms, scaled embeddings, sandwich
    # norms, gelu-tanh MLP, softcapping (gemma2), alternating
    # sliding/full layers, dual rope thetas (gemma3).  Gemma1
    # ("GemmaForCausalLM") shares the (1+w)-norm and sqrt(d)
    # embed-scale conventions but has no post-norms / softcap /
    # sliding layers — it must still take the gemma norm path or it
    # serves silently-wrong logits.
    gemma2plus = "Gemma2" in arch or "Gemma3" in arch
    gemma1 = arch == "GemmaForCausalLM"
    gemma = gemma2plus or gemma1
    # GPT-OSS: attention sinks, alternating sliding/full layers, biased
    # attention + router + experts, clamped-glu MoE, YaRN rope
    # (modeling_gpt_oss)
    gptoss = "GptOss" in arch
    layer_types = cfg.get("layer_types")
    layer_sliding = (
        tuple(t == "sliding_attention" for t in layer_types)
        if (gemma2plus or gptoss) and layer_types
        else None
    )
    if gemma2plus and layer_sliding is None:
        # original-release hub configs serialize no layer_types; derive
        # the pattern the way transformers does — gemma3:
        # sliding_window_pattern (every Nth layer is global), gemma2:
        # alternating starting sliding at layer 0
        L = cfg["num_hidden_layers"]
        pat = (
            int(cfg.get("sliding_window_pattern") or 6)
            if "Gemma3" in arch
            else 2
        )
        layer_sliding = tuple(bool((i + 1) % pat) for i in range(L))
    if gptoss and layer_sliding is None:
        # a stripped config without layer_types must NOT fall through
        # to the global-window branch (it would window the
        # full-attention layers too — silently wrong past 128 tokens);
        # GptOssConfig's own default is alternating starting sliding
        layer_sliding = tuple(
            i % 2 == 0 for i in range(cfg["num_hidden_layers"])
        )
    return ModelConfig(
        name=name,
        vocab_size=cfg["vocab_size"],
        hidden_size=hidden,
        intermediate_size=cfg.get("intermediate_size", 4 * hidden),
        num_layers=cfg["num_hidden_layers"],
        num_heads=heads,
        # decompressed MLA materializes per-head K/V: MHA cache shape
        num_kv_heads=(
            heads if mla
            else cfg.get("num_key_value_heads", heads)
        ),
        head_dim=head_dim,
        rope_theta=cfg.get("rope_theta", 10000.0),
        rope_scaling=cfg.get("rope_scaling"),
        rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
        tie_word_embeddings=cfg.get("tie_word_embeddings", False),
        qkv_bias=(
            ("Qwen2" in arch and not cfg.get("no_bias", False))
            or (gptoss and cfg.get("attention_bias", True))
        ),
        o_bias=gptoss and bool(cfg.get("attention_bias", True)),
        attn_sinks=gptoss,
        moe_act="gptoss" if gptoss else "silu",
        moe_bias=gptoss,
        # Qwen3 (dense + MoE) and Gemma3 replace attention bias with
        # per-head q/k RMSNorm
        qk_norm="Qwen3" in arch or "Gemma3" in arch,
        max_position_embeddings=cfg.get("max_position_embeddings", 8192),
        sliding_window=cfg.get("sliding_window") or 0,
        hidden_act=(
            "gelu_tanh"
            if cfg.get("hidden_activation") == "gelu_pytorch_tanh"
            or cfg.get("hidden_act") == "gelu_pytorch_tanh"
            # original gemma1 hub configs say "gelu" but the released
            # weights were trained with the tanh approximation
            or (gemma and cfg.get("hidden_act") in (None, "gelu"))
            else "silu"
        ),
        norm_delta_gain=gemma,
        embed_scale=gemma,
        post_norms=gemma2plus,
        query_pre_attn_scalar=(
            float(cfg.get("query_pre_attn_scalar") or 0) if gemma2plus else 0.0
        ),
        attn_logit_softcap=float(cfg.get("attn_logit_softcapping") or 0),
        final_logit_softcap=float(cfg.get("final_logit_softcapping") or 0),
        layer_sliding=layer_sliding,
        rope_local_theta=float(cfg.get("rope_local_base_freq") or 0),
        num_experts=num_experts,
        num_experts_per_tok=cfg.get("num_experts_per_tok", 0),
        moe_intermediate_size=(
            cfg.get("moe_intermediate_size")
            or (cfg.get("intermediate_size", 0) if num_experts else 0)
        ),
        norm_topk_prob=cfg.get("norm_topk_prob", True),
        q_lora_rank=int(cfg.get("q_lora_rank") or 0) if deepseek else 0,
        kv_lora_rank=int(cfg.get("kv_lora_rank") or 0) if deepseek else 0,
        qk_nope_head_dim=(
            int(cfg.get("qk_nope_head_dim") or 0) if deepseek else 0
        ),
        qk_rope_head_dim=(
            int(cfg.get("qk_rope_head_dim") or 0) if deepseek else 0
        ),
        v_head_dim=int(cfg.get("v_head_dim") or 0) if deepseek else 0,
        n_shared_experts=(
            int(cfg.get("n_shared_experts") or 0) if deepseek
            else (1 if cfg.get("shared_expert_intermediate_size") else 0)
        ),
        shared_expert_intermediate_size=(
            int(cfg.get("n_shared_experts") or 0)
            * int(cfg.get("moe_intermediate_size") or 0)
            if deepseek
            # Qwen2-MoE: explicit width key
            else int(cfg.get("shared_expert_intermediate_size") or 0)
        ),
        shared_expert_gated="Qwen2Moe" in arch,
        routed_scaling_factor=(
            float(cfg.get("routed_scaling_factor") or 1.0)
            if deepseek else 1.0
        ),
        first_k_dense=(
            int(cfg.get("first_k_dense_replace") or 0)
            if deepseek and num_experts else 0
        ),
        moe_scoring=(
            "sigmoid"
            if deepseek and cfg.get("scoring_func") == "sigmoid"
            else ("softmax_topk" if gptoss else "softmax")
        ),
    ).validate()


def load_hf_config(path: str, name: str = "") -> ModelConfig:
    """Read ``config.json`` from a local HF model directory."""
    with open(os.path.join(path, "config.json")) as f:
        cfg = json.load(f)
    return config_from_hf(cfg, name=name or os.path.basename(path.rstrip("/")))


# ---------------------------------------------------------------------------
# Presets. Flagship = llama3-8b (BASELINE.json north-star model). Tiny configs
# are for hermetic CPU tests (mirrors the reference's fixture doctrine,
# SURVEY.md §4).
# ---------------------------------------------------------------------------
PRESETS: Dict[str, ModelConfig] = {
    "llama3-8b": ModelConfig(
        name="llama3-8b",
        vocab_size=128256,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=500000.0,
        max_position_embeddings=8192,
    ),
    "llama3-70b": ModelConfig(
        name="llama3-70b",
        vocab_size=128256,
        hidden_size=8192,
        intermediate_size=28672,
        num_layers=80,
        num_heads=64,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=500000.0,
        max_position_embeddings=8192,
    ),
    "qwen2.5-7b": ModelConfig(
        name="qwen2.5-7b",
        vocab_size=152064,
        hidden_size=3584,
        intermediate_size=18944,
        num_layers=28,
        num_heads=28,
        num_kv_heads=4,
        head_dim=128,
        rope_theta=1000000.0,
        qkv_bias=True,
        tie_word_embeddings=False,
        max_position_embeddings=32768,
    ),
    # BASELINE anchor family: the reference's closest published 8B number
    # is Qwen3-8B (docs/performance-lab/qwen3-8b/910b.md:95-98).
    "qwen3-8b": ModelConfig(
        name="qwen3-8b",
        vocab_size=151936,
        hidden_size=4096,
        intermediate_size=12288,
        num_layers=36,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=1000000.0,
        qk_norm=True,
        max_position_embeddings=40960,
    ),
    "qwen3-30b-a3b": ModelConfig(
        name="qwen3-30b-a3b",
        vocab_size=151936,
        hidden_size=2048,
        intermediate_size=6144,
        num_layers=48,
        num_heads=32,
        num_kv_heads=4,
        head_dim=128,
        rope_theta=1000000.0,
        qk_norm=True,
        num_experts=128,
        num_experts_per_tok=8,
        moe_intermediate_size=768,
        norm_topk_prob=True,
        max_position_embeddings=40960,
    ),
    "gemma2-9b": ModelConfig(
        name="gemma2-9b",
        vocab_size=256000,
        hidden_size=3584,
        intermediate_size=14336,
        num_layers=42,
        num_heads=16,
        num_kv_heads=8,
        head_dim=256,
        rope_theta=10000.0,
        rms_norm_eps=1e-6,
        tie_word_embeddings=True,
        hidden_act="gelu_tanh",
        norm_delta_gain=True,
        embed_scale=True,
        post_norms=True,
        query_pre_attn_scalar=256.0,
        attn_logit_softcap=50.0,
        final_logit_softcap=30.0,
        sliding_window=4096,
        layer_sliding=tuple(i % 2 == 0 for i in range(42)),
        max_position_embeddings=8192,
    ),
    # GPT-OSS (openai/gpt-oss-20b — BASELINE.md headline anchor,
    # docs/performance-lab/gpt-oss-20b/a100.md): attention sinks,
    # alternating sliding/full layers, biased everything, clamped-glu
    # MoE, YaRN truncate=false. Hub dims from GptOssConfig.
    "gpt-oss-20b": ModelConfig(
        name="gpt-oss-20b",
        vocab_size=201088,
        hidden_size=2880,
        intermediate_size=2880,
        num_layers=24,
        num_heads=64,
        num_kv_heads=8,
        head_dim=64,
        rope_theta=150000.0,
        rope_scaling={
            "rope_type": "yarn", "factor": 32.0,
            "beta_fast": 32.0, "beta_slow": 1.0,
            "truncate": False,
            "original_max_position_embeddings": 4096,
        },
        rms_norm_eps=1e-5,
        max_position_embeddings=131072,
        sliding_window=128,
        layer_sliding=tuple(i % 2 == 0 for i in range(24)),
        qkv_bias=True,
        o_bias=True,
        attn_sinks=True,
        num_experts=32,
        num_experts_per_tok=4,
        moe_intermediate_size=2880,
        moe_scoring="softmax_topk",
        moe_act="gptoss",
        moe_bias=True,
    ),
    "gpt-oss-120b": ModelConfig(
        name="gpt-oss-120b",
        vocab_size=201088,
        hidden_size=2880,
        intermediate_size=2880,
        num_layers=36,
        num_heads=64,
        num_kv_heads=8,
        head_dim=64,
        rope_theta=150000.0,
        rope_scaling={
            "rope_type": "yarn", "factor": 32.0,
            "beta_fast": 32.0, "beta_slow": 1.0,
            "truncate": False,
            "original_max_position_embeddings": 4096,
        },
        rms_norm_eps=1e-5,
        max_position_embeddings=131072,
        sliding_window=128,
        layer_sliding=tuple(i % 2 == 0 for i in range(36)),
        qkv_bias=True,
        o_bias=True,
        attn_sinks=True,
        num_experts=128,
        num_experts_per_tok=4,
        moe_intermediate_size=2880,
        moe_scoring="softmax_topk",
        moe_act="gptoss",
        moe_bias=True,
    ),
    "mixtral-8x7b": ModelConfig(
        name="mixtral-8x7b",
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=1000000.0,
        num_experts=8,
        num_experts_per_tok=2,
        moe_intermediate_size=14336,
        max_position_embeddings=32768,
    ),
    # DeepSeek-V2-Lite (deepseek-ai/DeepSeek-V2-Lite): MLA + DeepSeek
    # MoE, served decompressed (see the MLA notes on ModelConfig)
    "deepseek-v2-lite": ModelConfig(
        name="deepseek-v2-lite",
        vocab_size=102400,
        hidden_size=2048,
        intermediate_size=10944,
        num_layers=27,
        num_heads=16,
        num_kv_heads=16,
        head_dim=192,                 # qk_nope + qk_rope
        rope_theta=10000.0,
        rms_norm_eps=1e-6,            # hub config.json value
        # the shipped YaRN scaling (hub config.json rope_scaling)
        rope_scaling={
            "type": "yarn", "factor": 40,
            "beta_fast": 32, "beta_slow": 1,
            "mscale": 0.707, "mscale_all_dim": 0.707,
            "original_max_position_embeddings": 4096,
        },
        max_position_embeddings=163840,
        num_experts=64,
        num_experts_per_tok=6,
        moe_intermediate_size=1408,
        norm_topk_prob=False,
        kv_lora_rank=512,
        qk_nope_head_dim=128,
        qk_rope_head_dim=64,
        v_head_dim=128,
        n_shared_experts=2,
        shared_expert_intermediate_size=2816,
        routed_scaling_factor=1.0,
        first_k_dense=1,
    ),
    # Hermetic-test configs (run everywhere, compile in seconds).
    "tiny": ModelConfig(
        name="tiny",
        vocab_size=264,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=16,
        rope_theta=10000.0,
        max_position_embeddings=256,
    ),
    "tiny-qwen3": ModelConfig(
        name="tiny-qwen3",
        vocab_size=264,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=16,
        rope_theta=10000.0,
        qk_norm=True,
        max_position_embeddings=256,
    ),
    "tiny-moe": ModelConfig(
        name="tiny-moe",
        vocab_size=264,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=16,
        rope_theta=10000.0,
        num_experts=4,
        num_experts_per_tok=2,
        moe_intermediate_size=96,
        max_position_embeddings=256,
    ),
}


def get_config(name: str) -> ModelConfig:
    if name in PRESETS:
        return PRESETS[name]
    raise KeyError(
        f"unknown model preset {name!r}; known: {sorted(PRESETS)}"
    )
