"""Int8 weight-only quantization for the transformer.

Decode is HBM-bandwidth-bound: weight bytes read per token dominate. Storing
weights as int8 with per-output-channel scales halves (vs bf16) the bytes per
decode step; the matmul contracts int8-upcast-to-bf16 directly
(``x @ q.astype(bf16) * s``) so the dequantized tensor is never materialized
in HBM — XLA fuses the convert into the MXU feed.

Scale layout: for each weight, scales live on the *output* (non-contracted)
dims, so the rescale is a cheap elementwise multiply on the matmul result.

The reference exposes per-model quantization as engine flags (vLLM
``--quantization``); here it is a first-class transform over the param tree
(``quantize_params``) the engine applies at load time.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QuantW:
    """An int8-quantized weight: ``q`` int8, ``s`` per-output-channel scale."""

    q: jax.Array
    s: jax.Array

    @property
    def shape(self):
        return self.q.shape

    @property
    def size(self):
        return self.q.size


# Which axes of each (per-layer-sliced) weight are contracted in its matmul.
# Scales span the remaining (output) axes. Leaves not listed stay unquantized
# (norm gains, biases, the tiny router).
_CONTRACT_AXES: Dict[str, tuple] = {
    "embed": (1,),      # gather: scale per vocab row
    "lm_head": (0,),    # [d, v] contracts d
    "wq": (0,), "wk": (0,), "wv": (0,),   # [d, out] contract d
    "wo": (0,),                            # [q, d] contracts q
    "w_gate": (0,), "w_up": (0,),          # [d, f] contract d
    "w_down": (0,),                        # [f, d] contracts f
    "we_gate": (1,), "we_up": (1,),        # [E, d, f] contract d
    "we_down": (1,),                       # [E, f, d] contract f
    # DeepSeek MLA projections + shared experts (the tiny rank-sized
    # norms and router bias stay unquantized like other small leaves)
    "wq_a": (0,), "wq_b": (0,),
    "wkv_a": (0,), "wkv_b": (0,),
    "ws_gate": (0,), "ws_up": (0,), "ws_down": (0,),
}
# Layer-stacked leaves carry a leading [L] axis not present at use time.
_STACKED = {
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
    "we_gate", "we_up", "we_down",
    "wq_a", "wq_b", "wkv_a", "wkv_b",
    "ws_gate", "ws_up", "ws_down",
}


def _quantize_leaf(name: str, w: jax.Array) -> QuantW:
    axes = _CONTRACT_AXES[name]
    if name in _STACKED:
        axes = tuple(a + 1 for a in axes)
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127).astype(
        jnp.int8
    )
    return QuantW(q=q, s=jnp.squeeze(scale, axis=axes).astype(jnp.bfloat16))


def quantize_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Quantize all large weights of a transformer param tree to int8.

    Tied-embedding models keep ``embed`` unquantized (the transpose reuse
    would need a second scale layout).
    """
    out: Dict[str, Any] = {}
    tie = "lm_head" not in params
    for k, v in params.items():
        if k in ("layers", "dense_layers"):
            out[k] = {
                lk: _quantize_leaf(lk, lv) if lk in _CONTRACT_AXES else lv
                for lk, lv in v.items()
            }
        elif k in _CONTRACT_AXES and not (k == "embed" and tie):
            out[k] = _quantize_leaf(k, v)
        else:
            out[k] = v
    return out


def quant_pspecs(specs: Dict[str, Any], params: Dict[str, Any]):
    """Adapt a PartitionSpec tree (from ``parallel.param_pspecs``) to a
    quantized param tree: ``q`` keeps the weight's spec, ``s`` keeps the
    spec's output-axis components."""
    from jax.sharding import PartitionSpec as P

    def adapt(name: str, spec, leaf):
        if not isinstance(leaf, QuantW):
            return spec
        axes = _CONTRACT_AXES[name]
        if name in _STACKED:
            axes = tuple(a + 1 for a in axes)
        s_spec = P(*(s for i, s in enumerate(spec) if i not in axes))
        return QuantW(q=spec, s=s_spec)

    out: Dict[str, Any] = {}
    for k, v in params.items():
        if k == "layers":
            out[k] = {
                lk: adapt(lk, specs["layers"][lk], lv) for lk, lv in v.items()
            }
        else:
            out[k] = adapt(k, specs[k], v)
    return out


def init_quantized_params(cfg, seed: int = 0):
    """Random int8 params generated *directly* (no bf16 detour).

    ``quantize_params(init_params(...))`` materializes the full bf16 tree
    first — 16 GB of jax PRNG work for an 8B model, minutes of host time.
    Synthetic benchmarks only need weight tensors of the right shape and
    scale, so this builds the QuantW tree straight from numpy int8 draws
    (~20x faster); statistics match the absmax-quantized normal init.
    """
    import math

    import numpy as np

    rng = np.random.default_rng(seed)
    d, f, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers

    def qw(shape, fan_in, name):
        q = rng.integers(-127, 128, size=shape, dtype=np.int8)
        axes = _CONTRACT_AXES[name]
        if name in _STACKED:
            axes = tuple(a + 1 for a in axes)
        s_shape = tuple(
            n for i, n in enumerate(shape) if i not in axes
        )
        # absmax-normal scale: ~3 sigma of N(0, 1/sqrt(fan_in)) per 127
        s = np.full(
            s_shape, 3.0 / math.sqrt(fan_in) / 127.0, dtype=np.float32
        )
        return QuantW(
            q=jnp.asarray(q), s=jnp.asarray(s).astype(jnp.bfloat16)
        )

    ones = lambda *shape: jnp.ones(shape, jnp.bfloat16)  # noqa: E731
    zeros = lambda *shape: jnp.zeros(shape, jnp.bfloat16)  # noqa: E731

    gain = zeros if cfg.norm_delta_gain else ones  # gemma: delta gains
    layers = {
        "attn_norm": gain(L, d),
        "mlp_norm": gain(L, d),
        "wq": qw((L, d, cfg.q_dim), d, "wq"),
        "wk": qw((L, d, cfg.kv_dim), d, "wk"),
        "wv": qw((L, d, cfg.kv_dim), d, "wv"),
        "wo": qw((L, cfg.q_dim, d), cfg.q_dim, "wo"),
    }
    if cfg.qkv_bias:
        layers["bq"] = zeros(L, cfg.q_dim)
        layers["bk"] = zeros(L, cfg.kv_dim)
        layers["bv"] = zeros(L, cfg.kv_dim)
    if cfg.qk_norm:
        norm_init = zeros if cfg.norm_delta_gain else ones
        layers["q_norm"] = norm_init(L, cfg.head_dim)
        layers["k_norm"] = norm_init(L, cfg.head_dim)
    if cfg.post_norms:
        norm_init = zeros if cfg.norm_delta_gain else ones
        layers["post_attn_norm"] = norm_init(L, d)
        layers["post_mlp_norm"] = norm_init(L, d)
    if cfg.is_moe:
        fm, E = cfg.moe_intermediate_size, cfg.num_experts
        layers["router"] = (
            jnp.asarray(
                rng.standard_normal((L, d, E), dtype=np.float32)
                / math.sqrt(d)
            ).astype(jnp.bfloat16)
        )
        layers["we_gate"] = qw((L, E, d, fm), d, "we_gate")
        layers["we_up"] = qw((L, E, d, fm), d, "we_up")
        layers["we_down"] = qw((L, E, fm, d), fm, "we_down")
    else:
        layers["w_gate"] = qw((L, d, f), d, "w_gate")
        layers["w_up"] = qw((L, d, f), d, "w_up")
        layers["w_down"] = qw((L, f, d), f, "w_down")

    params = {
        "layers": layers,
        "final_norm": gain(d),
    }
    if cfg.tie_word_embeddings:
        # Tied models contract embed.T at the LM head (transformer.forward
        # uses a raw einsum there) — keep embed bf16, matching
        # quantize_params' tied-embedding rule above.
        params["embed"] = jnp.asarray(
            rng.standard_normal((cfg.vocab_size, d), dtype=np.float32)
            * 0.02
        ).astype(jnp.bfloat16)
    else:
        params["embed"] = qw((cfg.vocab_size, d), 2500, "embed")  # ~0.02
        params["lm_head"] = qw((d, cfg.vocab_size), d, "lm_head")
    return params


def init_quantized_params_on_device(cfg, seed: int = 0):
    """Same tree as :func:`init_quantized_params`, generated on-accelerator.

    Under a remote / tunneled TPU (or any bandwidth-constrained
    host↔device link) materializing ~8 GB of int8 weights host-side and
    shipping them through the link dominates bench startup by minutes;
    one jitted PRNG program generates them in HBM directly. The tree and
    statistics match the host variant (absmax-quantized normal init).
    """
    import math

    d, f, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers

    def qw(key, shape, fan_in, name):
        # int32 draw then narrow: jax.random.randint's int8 path is not
        # supported on all backends; XLA fuses the convert.
        q = jax.random.randint(key, shape, -127, 128, dtype=jnp.int32).astype(
            jnp.int8
        )
        axes = _CONTRACT_AXES[name]
        if name in _STACKED:
            axes = tuple(a + 1 for a in axes)
        s_shape = tuple(n for i, n in enumerate(shape) if i not in axes)
        s = jnp.full(
            s_shape, 3.0 / math.sqrt(fan_in) / 127.0, jnp.bfloat16
        )
        return QuantW(q=q, s=s)

    def build(key):
        ones = lambda *shape: jnp.ones(shape, jnp.bfloat16)  # noqa: E731
        zeros = lambda *shape: jnp.zeros(shape, jnp.bfloat16)  # noqa: E731
        keys = iter(jax.random.split(key, 16))
        gain = zeros if cfg.norm_delta_gain else ones
        layers = {
            "attn_norm": gain(L, d),
            "mlp_norm": gain(L, d),
            "wq": qw(next(keys), (L, d, cfg.q_dim), d, "wq"),
            "wk": qw(next(keys), (L, d, cfg.kv_dim), d, "wk"),
            "wv": qw(next(keys), (L, d, cfg.kv_dim), d, "wv"),
            "wo": qw(next(keys), (L, cfg.q_dim, d), cfg.q_dim, "wo"),
        }
        if cfg.qkv_bias:
            layers["bq"] = zeros(L, cfg.q_dim)
            layers["bk"] = zeros(L, cfg.kv_dim)
            layers["bv"] = zeros(L, cfg.kv_dim)
        if cfg.qk_norm:
            norm_init = zeros if cfg.norm_delta_gain else ones
            layers["q_norm"] = norm_init(L, cfg.head_dim)
            layers["k_norm"] = norm_init(L, cfg.head_dim)
        if cfg.post_norms:
            norm_init = zeros if cfg.norm_delta_gain else ones
            layers["post_attn_norm"] = norm_init(L, d)
            layers["post_mlp_norm"] = norm_init(L, d)
        if cfg.is_moe:
            fm, E = cfg.moe_intermediate_size, cfg.num_experts
            layers["router"] = (
                jax.random.normal(next(keys), (L, d, E), jnp.float32)
                / math.sqrt(d)
            ).astype(jnp.bfloat16)
            layers["we_gate"] = qw(next(keys), (L, E, d, fm), d, "we_gate")
            layers["we_up"] = qw(next(keys), (L, E, d, fm), d, "we_up")
            layers["we_down"] = qw(next(keys), (L, E, fm, d), fm, "we_down")
        else:
            layers["w_gate"] = qw(next(keys), (L, d, f), d, "w_gate")
            layers["w_up"] = qw(next(keys), (L, d, f), d, "w_up")
            layers["w_down"] = qw(next(keys), (L, f, d), f, "w_down")
        params = {"layers": layers, "final_norm": gain(d)}
        if cfg.tie_word_embeddings:
            params["embed"] = (
                jax.random.normal(
                    next(keys), (cfg.vocab_size, d), jnp.float32
                )
                * 0.02
            ).astype(jnp.bfloat16)
        else:
            params["embed"] = qw(
                next(keys), (cfg.vocab_size, d), 2500, "embed"
            )
            params["lm_head"] = qw(
                next(keys), (d, cfg.vocab_size), d, "lm_head"
            )
        return params

    return jax.jit(build)(jax.random.key(seed))


def dequantize(name: str, w, stacked: Optional[bool] = None) -> jax.Array:
    """Reference dequantization (tests / debugging). ``name`` identifies the
    weight's contraction layout; ``stacked`` overrides the [L]-axis default
    (pass False for a per-layer slice of a stacked weight)."""
    if not isinstance(w, QuantW):
        return w
    axes = _CONTRACT_AXES[name]
    if stacked if stacked is not None else name in _STACKED:
        axes = tuple(a + 1 for a in axes)
    return w.q.astype(jnp.bfloat16) * jnp.expand_dims(w.s, axes)
