"""Int8 weight-only quantization for the transformer.

Decode is HBM-bandwidth-bound: weight bytes read per token dominate. Storing
weights as int8 with per-output-channel scales halves (vs bf16) the bytes per
decode step; the matmul contracts int8-upcast-to-bf16 directly
(``x @ q.astype(bf16) * s``) so the dequantized tensor is never materialized
in HBM — XLA fuses the convert into the MXU feed.

Scale layout: for each weight, scales live on the *output* (non-contracted)
dims, so the rescale is a cheap elementwise multiply on the matmul result.

The reference exposes per-model quantization as engine flags (vLLM
``--quantization``); here it is a first-class transform over the param tree
(``quantize_params``) the engine applies at load time.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QuantW:
    """An int8-quantized weight: ``q`` int8, ``s`` per-output-channel scale."""

    q: jax.Array
    s: jax.Array

    @property
    def shape(self):
        return self.q.shape

    @property
    def size(self):
        return self.q.size


# Which axes of each (per-layer-sliced) weight are contracted in its matmul.
# Scales span the remaining (output) axes. Leaves not listed stay unquantized
# (norm gains, biases, the tiny router).
_CONTRACT_AXES: Dict[str, tuple] = {
    "embed": (1,),      # gather: scale per vocab row
    "lm_head": (0,),    # [d, v] contracts d
    "wq": (0,), "wk": (0,), "wv": (0,),   # [d, out] contract d
    "wo": (0,),                            # [q, d] contracts q
    "w_gate": (0,), "w_up": (0,),          # [d, f] contract d
    "w_down": (0,),                        # [f, d] contracts f
    "we_gate": (1,), "we_up": (1,),        # [E, d, f] contract d
    "we_down": (1,),                       # [E, f, d] contract f
}
# Layer-stacked leaves carry a leading [L] axis not present at use time.
_STACKED = {
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
    "we_gate", "we_up", "we_down",
}


def _quantize_leaf(name: str, w: jax.Array) -> QuantW:
    axes = _CONTRACT_AXES[name]
    if name in _STACKED:
        axes = tuple(a + 1 for a in axes)
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127).astype(
        jnp.int8
    )
    return QuantW(q=q, s=jnp.squeeze(scale, axis=axes).astype(jnp.bfloat16))


def quantize_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Quantize all large weights of a transformer param tree to int8.

    Tied-embedding models keep ``embed`` unquantized (the transpose reuse
    would need a second scale layout).
    """
    out: Dict[str, Any] = {}
    tie = "lm_head" not in params
    for k, v in params.items():
        if k == "layers":
            out[k] = {
                lk: _quantize_leaf(lk, lv) if lk in _CONTRACT_AXES else lv
                for lk, lv in v.items()
            }
        elif k in _CONTRACT_AXES and not (k == "embed" and tie):
            out[k] = _quantize_leaf(k, v)
        else:
            out[k] = v
    return out


def quant_pspecs(specs: Dict[str, Any], params: Dict[str, Any]):
    """Adapt a PartitionSpec tree (from ``parallel.param_pspecs``) to a
    quantized param tree: ``q`` keeps the weight's spec, ``s`` keeps the
    spec's output-axis components."""
    from jax.sharding import PartitionSpec as P

    def adapt(name: str, spec, leaf):
        if not isinstance(leaf, QuantW):
            return spec
        axes = _CONTRACT_AXES[name]
        if name in _STACKED:
            axes = tuple(a + 1 for a in axes)
        s_spec = P(*(s for i, s in enumerate(spec) if i not in axes))
        return QuantW(q=spec, s=s_spec)

    out: Dict[str, Any] = {}
    for k, v in params.items():
        if k == "layers":
            out[k] = {
                lk: adapt(lk, specs["layers"][lk], lv) for lk, lv in v.items()
            }
        else:
            out[k] = adapt(k, specs[k], v)
    return out


def dequantize(name: str, w, stacked: Optional[bool] = None) -> jax.Array:
    """Reference dequantization (tests / debugging). ``name`` identifies the
    weight's contraction layout; ``stacked`` overrides the [L]-axis default
    (pass False for a per-layer slice of a stacked weight)."""
    if not isinstance(w, QuantW):
        return w
    axes = _CONTRACT_AXES[name]
    if stacked if stacked is not None else name in _STACKED:
        axes = tuple(a + 1 for a in axes)
    return w.q.astype(jnp.bfloat16) * jnp.expand_dims(w.s, axes)
