"""Model families for the built-in TPU serving engine.

The reference (gpustack/gpustack) ships no model code — it orchestrates
vLLM/SGLang containers. Our data plane is in-repo and TPU-native, so the model
zoo lives here: a single functional transformer core covering the Llama/Qwen/
Mistral dense families and Mixtral-class MoE, parameterized by
:class:`~gpustack_tpu.models.config.ModelConfig`.
"""

from gpustack_tpu.models.config import ModelConfig, PRESETS, config_from_hf
from gpustack_tpu.models.transformer import (
    KVCache,
    forward,
    init_params,
)

__all__ = [
    "ModelConfig",
    "PRESETS",
    "config_from_hf",
    "KVCache",
    "forward",
    "init_params",
]
