"""Functional text-to-speech model (FastSpeech-class, non-autoregressive).

Completes the VoxBox role (reference worker/backends/vox_box.py:23 serves
both STT *and* TTS behind the OpenAI audio surface) with a TPU-idiomatic
design: every stage is a fixed-shape jitted program —

  text ids [Tb] ──► encoder (pre-LN transformer) ──► durations [Tb]
        │                                              │
        └──► length-regulate (gather by searchsorted over cumulative
             durations — static [F] frame grid, no dynamic shapes) ──►
             frame decoder (transformer) ──► log-mel [F, n_mels]

and the vocoder is host-side Griffin-Lim (numpy): mel → linear via the
filterbank pseudo-inverse → iterative phase recovery → PCM. No learned
vocoder exists in the image's dependency set, and Griffin-Lim keeps the
whole path dependency-free like models/audio.py's frontend.

Non-autoregressive synthesis is the TPU-first choice: one batched
forward over the full frame grid (MXU-dense) instead of a
frame-at-a-time autoregressive loop.

Voices are a learned embedding table added to the encoder input; OpenAI's
``voice`` parameter maps onto table indices. ``speed`` scales predicted
durations before regulation.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TTSConfig:
    name: str = "tts"
    vocab_size: int = 258          # byte tokenizer (engine/tokenizer.py)
    dim: int = 256
    enc_layers: int = 4
    dec_layers: int = 4
    num_heads: int = 4
    n_mels: int = 80
    n_voices: int = 8
    max_text_len: int = 256        # token bucket (static)
    max_frames: int = 1024         # frame bucket (static)
    max_duration: int = 16         # frames a single token may span
    sample_rate: int = 16000
    n_fft: int = 400
    hop: int = 160

    @property
    def head_dim(self) -> int:
        return self.dim // self.num_heads

    @property
    def d_model(self) -> int:
        return self.dim

    # scheduler-calculator contract (same duck type as WhisperConfig /
    # ModelConfig): weight + activation budgets for placement math
    @property
    def num_kv_heads(self) -> int:
        return self.num_heads

    @property
    def num_experts(self) -> int:
        return 0

    def kv_cache_bytes_per_token(self, bits: int = 16) -> int:
        return 0                   # non-autoregressive: no KV cache

    def param_count(self) -> int:
        per_layer = 4 * self.dim * self.dim + 8 * self.dim * self.dim
        return (
            self.vocab_size * self.dim
            + self.n_voices * self.dim
            + (self.enc_layers + self.dec_layers) * per_layer
            + self.max_frames * self.dim          # frame_pos
            + self.dim * self.dim + self.dim      # duration head
            + self.dim * self.n_mels
            + 2 * self.dim
        )

    def weight_bytes(self, bits: int = 16) -> int:
        return self.param_count() * bits // 8


TTS_PRESETS = {
    "tts-base": TTSConfig(name="tts-base"),
    "tiny-tts": TTSConfig(
        name="tiny-tts", dim=32, enc_layers=2, dec_layers=2, num_heads=2,
        n_mels=20, max_text_len=64, max_frames=128, n_fft=256, hop=64,
    ),
}


def init_tts_params(cfg: TTSConfig, key: jax.Array) -> Params:
    """Random init in the init_whisper_params doctrine: a flat dict of
    stacked per-layer weights so the transformer scans over layers."""
    keys = iter(jax.random.split(key, 64))

    def w(*shape, scale=None):
        scale = scale or (1.0 / np.sqrt(shape[-1]))
        return (
            jax.random.normal(next(keys), shape, jnp.float32) * scale
        ).astype(jnp.bfloat16)

    def stack(layers, *shape):
        return w(layers, *shape)

    D, H = cfg.dim, cfg.n_mels
    return {
        "tok_emb": w(cfg.vocab_size, D, scale=0.02),
        "voice_emb": w(cfg.n_voices, D, scale=0.02),
        "enc": {
            "wq": stack(cfg.enc_layers, D, D),
            "wk": stack(cfg.enc_layers, D, D),
            "wv": stack(cfg.enc_layers, D, D),
            "wo": stack(cfg.enc_layers, D, D),
            "w1": stack(cfg.enc_layers, D, 4 * D),
            "w2": stack(cfg.enc_layers, 4 * D, D),
            "ln1": jnp.ones((cfg.enc_layers, D), jnp.float32),
            "ln2": jnp.ones((cfg.enc_layers, D), jnp.float32),
        },
        "dur_w1": w(D, D),
        "dur_w2": w(D, 1, scale=0.1),
        "frame_pos": w(cfg.max_frames, D, scale=0.02),
        "dec": {
            "wq": stack(cfg.dec_layers, D, D),
            "wk": stack(cfg.dec_layers, D, D),
            "wv": stack(cfg.dec_layers, D, D),
            "wo": stack(cfg.dec_layers, D, D),
            "w1": stack(cfg.dec_layers, D, 4 * D),
            "w2": stack(cfg.dec_layers, 4 * D, D),
            "ln1": jnp.ones((cfg.dec_layers, D), jnp.float32),
            "ln2": jnp.ones((cfg.dec_layers, D), jnp.float32),
        },
        "ln_out": jnp.ones((D,), jnp.float32),
        "mel_head": w(D, H),
    }


def _rms(x, g, eps=1e-6):
    n = x.astype(jnp.float32)
    n = n * jax.lax.rsqrt(jnp.mean(n * n, -1, keepdims=True) + eps)
    return (n * g).astype(x.dtype)


def _block_stack(x, blocks, cfg, mask):
    """Scan a non-causal transformer stack over its stacked layers.

    mask: [T, T] additive attention mask (0 / -inf for padding)."""
    nh, hd = cfg.num_heads, cfg.head_dim
    scale = 1.0 / np.sqrt(hd)

    def layer(x, wts):
        h = _rms(x, wts["ln1"])
        q = (h @ wts["wq"]).reshape(-1, nh, hd)
        k = (h @ wts["wk"]).reshape(-1, nh, hd)
        v = (h @ wts["wv"]).reshape(-1, nh, hd)
        att = jnp.einsum("qhd,khd->hqk", q, k) * scale
        att = att + mask[None, :, :]
        att = jax.nn.softmax(att.astype(jnp.float32), -1).astype(x.dtype)
        o = jnp.einsum("hqk,khd->qhd", att, v).reshape(-1, cfg.dim)
        x = x + o @ wts["wo"]
        h = _rms(x, wts["ln2"])
        x = x + jax.nn.gelu(h @ wts["w1"]) @ wts["w2"]
        return x, None

    x, _ = jax.lax.scan(layer, x, blocks)
    return x


def synthesize_mel(
    params: Params, cfg: TTSConfig, token_ids: jax.Array,
    true_len: jax.Array, voice: jax.Array, speed: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Jittable synthesis: padded ids [max_text_len] → (log-mel
    [max_frames, n_mels], n_frames, raw_frames). All shapes static."""
    T, F = cfg.max_text_len, cfg.max_frames
    tok_mask = jnp.arange(T) < true_len                       # [T]

    x = params["tok_emb"][token_ids] + params["voice_emb"][voice]
    attn_mask = jnp.where(tok_mask[None, :], 0.0, -jnp.inf)   # [1, T]
    attn_mask = jnp.broadcast_to(attn_mask, (T, T))
    x = _block_stack(x, params["enc"], cfg, attn_mask)

    # durations: positive frame counts per real token, scaled by 1/speed
    h = jax.nn.gelu(x @ params["dur_w1"])
    log_d = (h @ params["dur_w2"])[:, 0].astype(jnp.float32)
    dur = jnp.clip(jnp.exp(log_d) / speed, 1.0, cfg.max_duration)
    dur = jnp.where(tok_mask, dur, 0.0)
    cum = jnp.cumsum(dur)                                     # [T]
    raw_frames = jnp.round(cum[-1]).astype(jnp.int32)
    n_frames = jnp.minimum(raw_frames, F)

    # length regulation on a static frame grid: frame j belongs to the
    # first token whose cumulative duration exceeds j
    frame_pos_f = jnp.arange(F, dtype=jnp.float32)
    owner = jnp.searchsorted(cum, frame_pos_f, side="right")  # [F]
    owner = jnp.minimum(owner, T - 1)
    frames = x[owner] + params["frame_pos"]
    frame_mask = jnp.arange(F) < n_frames
    dec_mask = jnp.where(frame_mask[None, :], 0.0, -jnp.inf)
    dec_mask = jnp.broadcast_to(dec_mask, (F, F))
    y = _block_stack(frames, params["dec"], cfg, dec_mask)
    mel = _rms(y, params["ln_out"]) @ params["mel_head"]      # [F, n_mels]
    # raw_frames rides along so the host can detect (and reject) an
    # utterance that would be cut by the static frame budget instead of
    # silently returning truncated audio
    return mel.astype(jnp.float32), n_frames, raw_frames


_synth_cache: Dict[TTSConfig, Any] = {}


def _jitted_synth(cfg: TTSConfig):
    # frozen dataclass => hashable: the config itself is the cache key
    # (an id()-based key could collide after GC address reuse)
    fn = _synth_cache.get(cfg)
    if fn is None:
        fn = jax.jit(
            lambda p, ids, n, v, s: synthesize_mel(p, cfg, ids, n, v, s)
        )
        _synth_cache[cfg] = fn
    return fn


def griffin_lim(
    mel: np.ndarray, cfg: TTSConfig, n_iter: int = 30,
) -> np.ndarray:
    """Host vocoder: log-mel [F, n_mels] → float32 PCM.

    Mel → linear magnitude via the filterbank pseudo-inverse, then
    classic Griffin-Lim phase recovery over numpy STFT/ISTFT.
    """
    from gpustack_tpu.models.audio import mel_filterbank

    fb = mel_filterbank(cfg.n_mels, cfg.n_fft)        # [n_mels, bins]
    inv = np.linalg.pinv(fb)                          # [bins, n_mels]
    power = np.power(10.0, mel * 4.0 - 4.0)           # undo log scaling
    mag = np.sqrt(np.maximum(inv @ power.T, 1e-10))   # [bins, F]

    n_fft, hop = cfg.n_fft, cfg.hop
    window = np.hanning(n_fft + 1)[:-1].astype(np.float32)
    frames = mag.shape[1]
    length = hop * (frames - 1) + n_fft

    def istft(spec):
        x = np.zeros(length, np.float32)
        norm = np.zeros(length, np.float32)
        ytmp = np.fft.irfft(spec, n=n_fft, axis=0).real.astype(np.float32)
        for t in range(frames):
            s = t * hop
            x[s: s + n_fft] += ytmp[:, t] * window
            norm[s: s + n_fft] += window * window
        return x / np.maximum(norm, 1e-8)

    def stft(x):
        idx = (
            np.arange(n_fft)[None, :] + hop * np.arange(frames)[:, None]
        )
        xp = np.pad(x, (0, max(0, idx.max() + 1 - len(x))))
        return np.fft.rfft(xp[idx] * window, axis=1).T    # [bins, F]

    rng = np.random.default_rng(0)
    angles = np.exp(
        2j * np.pi * rng.random((mag.shape[0], frames))
    )
    for _ in range(n_iter):
        audio = istft(mag * angles)
        spec = stft(audio)
        angles = spec / np.maximum(np.abs(spec), 1e-8)
    audio = istft(mag * angles)
    peak = np.max(np.abs(audio))
    if peak > 0:
        audio = audio / peak * 0.9
    return audio.astype(np.float32)


def synthesize(
    params: Params, cfg: TTSConfig, token_ids, *,
    voice: int = 0, speed: float = 1.0,
) -> np.ndarray:
    """Text token ids → float32 PCM at ``cfg.sample_rate``.

    Raises ValueError on empty input, input past the text bucket, or an
    utterance whose predicted duration exceeds the frame budget — the
    caller turns these into clear 400s rather than shipping silently
    truncated audio."""
    ids = list(token_ids)
    true_len = len(ids)
    if true_len == 0:
        raise ValueError("empty input text")
    if true_len > cfg.max_text_len:
        raise ValueError(
            f"input of {true_len} tokens exceeds this model's text "
            f"budget of {cfg.max_text_len}; shorten the input"
        )
    padded = ids + [0] * (cfg.max_text_len - true_len)
    fn = _jitted_synth(cfg)
    mel, n_frames, raw_frames = fn(
        params,
        jnp.asarray(padded, jnp.int32),
        jnp.int32(true_len),
        jnp.int32(voice % cfg.n_voices),
        jnp.float32(max(0.25, min(4.0, speed))),
    )
    if int(raw_frames) > cfg.max_frames:
        raise ValueError(
            f"utterance needs {int(raw_frames)} frames but this model's "
            f"budget is {cfg.max_frames}; shorten the input or raise "
            f"speed"
        )
    n = int(n_frames)
    return griffin_lim(np.asarray(mel)[:n], cfg)


def pcm_to_wav_bytes(audio: np.ndarray, sample_rate: int) -> bytes:
    """float32 PCM [-1, 1] → 16-bit mono WAV bytes (stdlib only)."""
    import io
    import wave

    pcm16 = (np.clip(audio, -1.0, 1.0) * 32767).astype(np.int16)
    buf = io.BytesIO()
    with wave.open(buf, "wb") as wf:
        wf.setnchannels(1)
        wf.setsampwidth(2)
        wf.setframerate(sample_rate)
        wf.writeframes(pcm16.tobytes())
    return buf.getvalue()


# OpenAI voice names → voice-embedding indices (stable mapping so the
# same name always selects the same learned voice)
OPENAI_VOICES = {
    "alloy": 0, "echo": 1, "fable": 2, "onyx": 3,
    "nova": 4, "shimmer": 5,
}


def voice_index(name: Optional[str], cfg: TTSConfig) -> int:
    if not name:
        return 0
    if name in OPENAI_VOICES:
        return OPENAI_VOICES[name] % cfg.n_voices
    try:
        return int(name) % cfg.n_voices
    except ValueError:
        # unknown names hash stably onto the table
        return sum(name.encode()) % cfg.n_voices
