"""Vision-language serving: ViT tower + projector + token splicing.

Reference parity: the reference schedules and serves VLMs (vision-head
divisibility checks,
policies/candidate_selectors/base_candidate_selector.py:229-234; vLLM
consumes ``image_url`` content parts). Here the LLaVA-class recipe is
implemented TPU-first:

  image [S, S, 3] ── patchify (one reshape; stride-free) ──► ViT
  (non-causal transformer, jitted, static patch count) ──► projector
  (2-layer MLP into the language dim) ──► spliced into the prompt's
  embedding sequence at placeholder positions; the language model's
  prefill runs ONE fused program with the override applied after
  embedding lookup (models/transformer.py forward(embeds_override=...)).

Everything is static-shape: image size, patch count and the per-image
token run are fixed by the config, so the prefill hits the same bucket
ladder as text-only requests.

Images arrive as ``data:`` URLs (base64) only — this is a zero-egress
deployment; remote http(s) image URLs are rejected at the API layer.
"""

from __future__ import annotations

import base64
import dataclasses
import io
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gpustack_tpu.models.config import ModelConfig, get_config

Params = Dict[str, Any]

# ByteTokenizer id 257 is BOS/reserved (engine/tokenizer.py) — reused as
# the image-placeholder id so hermetic VLM configs need no vocab change.
IMAGE_PLACEHOLDER_ID = 257


@dataclasses.dataclass(frozen=True)
class VisionConfig:
    image_size: int = 64
    patch_size: int = 8
    dim: int = 64
    layers: int = 2
    heads: int = 2

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.dim // self.heads


@dataclasses.dataclass(frozen=True)
class VLMConfig:
    name: str
    language: ModelConfig
    vision: VisionConfig

    @property
    def n_image_tokens(self) -> int:
        return self.vision.n_patches


def _tiny_vlm() -> VLMConfig:
    return VLMConfig(
        name="tiny-vlm",
        language=get_config("tiny"),
        vision=VisionConfig(),
    )


VLM_PRESETS = {"tiny-vlm": _tiny_vlm}


def get_vlm_config(preset: str) -> VLMConfig:
    return VLM_PRESETS[preset]()


def init_vision_params(cfg: VLMConfig, key: jax.Array) -> Params:
    """Vision tower + projector params (language params live separately
    in the LLM engine's own tree)."""
    v = cfg.vision
    keys = iter(jax.random.split(key, 16))

    def w(*shape, scale=None):
        scale = scale or (1.0 / np.sqrt(shape[-1]))
        return (
            jax.random.normal(next(keys), shape, jnp.float32) * scale
        ).astype(jnp.bfloat16)

    D = v.dim
    patch_dim = 3 * v.patch_size * v.patch_size
    lm_dim = cfg.language.hidden_size
    return {
        "patch_proj": w(patch_dim, D),
        "pos_emb": w(v.n_patches, D, scale=0.02),
        "blocks": {
            "wq": w(v.layers, D, D),
            "wk": w(v.layers, D, D),
            "wv": w(v.layers, D, D),
            "wo": w(v.layers, D, D),
            "w1": w(v.layers, D, 4 * D),
            "w2": w(v.layers, 4 * D, D),
            "ln1": jnp.ones((v.layers, D), jnp.float32),
            "ln2": jnp.ones((v.layers, D), jnp.float32),
        },
        "proj_w1": w(D, lm_dim),
        "proj_w2": w(lm_dim, lm_dim),
    }


def _rms(x, g, eps=1e-6):
    n = x.astype(jnp.float32)
    n = n * jax.lax.rsqrt(jnp.mean(n * n, -1, keepdims=True) + eps)
    return (n * g).astype(x.dtype)


def encode_image(
    params: Params, cfg: VLMConfig, image: jax.Array
) -> jax.Array:
    """image [S, S, 3] float in [0, 1] → [n_patches, lm_dim] bf16."""
    v = cfg.vision
    p = v.patch_size
    g = v.image_size // p
    # patchify without convs: [g, p, g, p, 3] -> [g*g, p*p*3]
    x = image.reshape(g, p, g, p, 3).transpose(0, 2, 1, 3, 4)
    x = x.reshape(v.n_patches, p * p * 3).astype(jnp.bfloat16)
    x = (x * 2.0 - 1.0) @ params["patch_proj"] + params["pos_emb"]

    nh, hd = v.heads, v.head_dim
    scale = 1.0 / np.sqrt(hd)

    def layer(x, wts):
        h = _rms(x, wts["ln1"])
        q = (h @ wts["wq"]).reshape(-1, nh, hd)
        k = (h @ wts["wk"]).reshape(-1, nh, hd)
        val = (h @ wts["wv"]).reshape(-1, nh, hd)
        att = jnp.einsum("qhd,khd->hqk", q, k) * scale
        att = jax.nn.softmax(att.astype(jnp.float32), -1).astype(x.dtype)
        o = jnp.einsum("hqk,khd->qhd", att, val).reshape(-1, v.dim)
        x = x + o @ wts["wo"]
        h = _rms(x, wts["ln2"])
        x = x + jax.nn.gelu(h @ wts["w1"]) @ wts["w2"]
        return x, None

    x, _ = jax.lax.scan(layer, x, params["blocks"])
    # LLaVA-style 2-layer MLP projector into the language dim
    y = jax.nn.gelu(x @ params["proj_w1"]) @ params["proj_w2"]
    return y


class VisionBundle:
    """What the API server needs to serve image content parts: the tower
    params + a jitted encoder + preprocessing."""

    def __init__(self, cfg: VLMConfig, params: Params):
        self.cfg = cfg
        self.params = params
        self._encode = jax.jit(
            lambda p, img: encode_image(p, cfg, img)
        )

    @property
    def n_image_tokens(self) -> int:
        return self.cfg.n_image_tokens

    def preprocess(self, image_bytes: bytes) -> np.ndarray:
        """Decode + resize to the tower's square input, float [0, 1]."""
        from PIL import Image

        try:
            img = Image.open(io.BytesIO(image_bytes)).convert("RGB")
        except Exception as e:
            # PIL raises UnidentifiedImageError/OSError on garbage bytes;
            # normalize to ValueError so the API layer returns 400, not 500
            raise ValueError(f"cannot decode image: {e}") from e
        s = self.cfg.vision.image_size
        img = img.resize((s, s))
        return np.asarray(img, np.float32) / 255.0

    def encode(self, image_bytes: bytes) -> np.ndarray:
        emb = self._encode(
            self.params, jnp.asarray(self.preprocess(image_bytes))
        )
        return np.asarray(emb, np.float32)


def decode_data_url(url: str) -> bytes:
    """``data:image/...;base64,...`` → raw image bytes. Anything else is
    rejected: this is a zero-egress deployment, the engine never dials
    out for remote images."""
    if not url.startswith("data:"):
        raise ValueError(
            "only data: image URLs are supported (zero-egress deployment "
            "— inline the image as base64)"
        )
    header, _, payload = url.partition(",")
    if not payload or "base64" not in header:
        raise ValueError("malformed data URL (expected ';base64,' payload)")
    try:
        return base64.b64decode(payload, validate=True)
    except Exception as e:
        raise ValueError(f"invalid base64 image payload: {e}") from e


def build_mm_prompt(
    tokenizer,
    messages: List[dict],
    bundle: VisionBundle,
) -> Tuple[List[int], np.ndarray, np.ndarray]:
    """OpenAI messages with content parts → (prompt_ids, embeds [T, D],
    mask [T]).

    Text parts tokenize normally; each ``image_url`` part becomes a run
    of ``n_image_tokens`` placeholder ids whose embedding rows are
    overridden with the projected patch embeddings. The surrounding chat
    scaffolding mirrors the tokenizer's text-only template so text-only
    and multimodal prompts share a format.
    """
    n_img = bundle.n_image_tokens
    ids: List[int] = []
    embeds: List[Optional[np.ndarray]] = []   # aligned per-token rows

    def add_text(text: str) -> None:
        toks = tokenizer.encode(text)
        ids.extend(toks)
        embeds.extend([None] * len(toks))

    for m in messages:
        role = m.get("role", "user")
        content = m.get("content", "")
        add_text(f"<{role}>")
        if isinstance(content, list):
            for part in content:
                if not isinstance(part, dict):
                    raise ValueError(
                        "content parts must be objects with a 'type'"
                    )
                ptype = part.get("type")
                if ptype == "text":
                    add_text(part.get("text", ""))
                elif ptype == "image_url":
                    url = (part.get("image_url") or {}).get("url", "")
                    img_embeds = bundle.encode(decode_data_url(url))
                    ids.extend([IMAGE_PLACEHOLDER_ID] * n_img)
                    embeds.extend(list(img_embeds))
                else:
                    raise ValueError(
                        f"unsupported content part type {ptype!r}"
                    )
        else:
            add_text(str(content or ""))
        add_text(f"</{role}>")
    add_text("<assistant>")

    lm_dim = bundle.cfg.language.hidden_size
    embed_arr = np.zeros((len(ids), lm_dim), np.float32)
    mask = np.zeros((len(ids),), bool)
    for i, row in enumerate(embeds):
        if row is not None:
            embed_arr[i] = row
            mask[i] = True
    return ids, embed_arr, mask
