"""Functional latent-diffusion image generation (SD / SDXL class).

The image modality of the framework: the reference serves image models
(Stable Diffusion family) through the VoxBox backend and pairs SDXL with
Whisper in its benchmark config 5 (reference worker/backends/vox_box.py:23,
BASELINE config 5). TPU-first design:

- **Pure functional** params-in/params-out modules (CLIP-class text
  encoder, UNet with cross-attention, VAE decoder) — no framework layers.
- **Static shapes everywhere**: text is padded to ``max_text_len``; the
  denoising loop is a ``lax.fori_loop`` over a precomputed timestep
  buffer inside ONE jit, so a 30-step sample is a single XLA program
  (no per-step dispatch over a high-latency host link).
- **bf16 matmuls/convs, fp32 norms + softmax** — same precision story as
  the LM core (models/transformer.py).
- Classifier-free guidance runs cond+uncond as one batch of 2N (one MXU
  pass, not two kernels).

Architecture follows the published Stable Diffusion design; SDXL-style
micro-conditioning (dual text encoders, pooled + time-id additive
embedding, per-level transformer depth) is supported through the config.
Weights load from local diffusers-format checkpoints
(engine/image_weights.py).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class DiffusionConfig:
    name: str = "stable-diffusion"
    # latent space
    image_size: int = 512
    latent_channels: int = 4
    vae_scale_factor: int = 8
    scaling_factor: float = 0.18215
    # text encoder (CLIP-class)
    vocab_size: int = 49408
    text_dim: int = 768
    text_layers: int = 12
    text_heads: int = 12
    max_text_len: int = 77
    text_act: str = "quick_gelu"
    # optional second text encoder (SDXL): penultimate hidden states are
    # concatenated onto the first encoder's context
    text2_dim: int = 0
    text2_layers: int = 0
    text2_heads: int = 0
    text2_act: str = "gelu"
    text2_projection_dim: int = 0
    # unet
    model_channels: int = 320
    channel_mult: Tuple[int, ...] = (1, 2, 4, 4)
    num_res_blocks: int = 2
    attn_levels: Tuple[int, ...] = (0, 1, 2)
    transformer_depth: Tuple[int, ...] = (1, 1, 1, 1)  # per level
    # heads per level (diffusers' attention_head_dim is, despite the
    # name, the head COUNT in SD-family configs: SD1.5 → 8 everywhere,
    # SDXL → [5, 10, 20]); a wrong per-level head split silently
    # produces garbage with trained weights
    num_heads: Tuple[int, ...] = (8, 8, 8, 8)
    context_dim: int = 768
    addition_embed: bool = False       # SDXL pooled-text + time-ids
    addition_time_embed_dim: int = 256
    # vae decoder
    vae_channels: int = 128
    vae_channel_mult: Tuple[int, ...] = (1, 2, 4, 4)
    vae_res_blocks: int = 2
    # noise schedule (scaled-linear, SD convention)
    train_timesteps: int = 1000
    beta_start: float = 0.00085
    beta_end: float = 0.012
    prediction_type: str = "epsilon"   # or "v_prediction"
    dtype: str = "bfloat16"

    def heads_for(self, level: int) -> int:
        return self.num_heads[min(level, len(self.num_heads) - 1)]

    @property
    def time_embed_dim(self) -> int:
        return 4 * self.model_channels

    @property
    def latent_size(self) -> int:
        return self.image_size // self.vae_scale_factor

    # ---- calculator-facing surface (scheduler/calculator.py) ----
    @property
    def d_model(self) -> int:
        return self.model_channels * self.channel_mult[-1]

    @property
    def num_kv_heads(self) -> int:
        return 1      # pins the mesh planner to tp=1: one chip per sample

    @property
    def num_experts(self) -> int:
        return 0

    def kv_cache_bytes_per_token(self, bits: int = 16) -> int:
        return 0      # no autoregressive cache

    def param_count(self) -> int:
        c, td = self.model_channels, self.time_embed_dim
        total = td * td * 2 + self.latent_channels * c * 9 * 2
        # unet res blocks + attention, down+up approximated exactly by
        # walking the same structure init builds
        chans = self._down_channels()
        for in_ch, out_ch, level in chans:
            total += self._res_params(in_ch, out_ch)
            if level in self.attn_levels:
                total += self._attn_params(out_ch, level)
        mid = c * self.channel_mult[-1]
        total += 2 * self._res_params(mid, mid) + self._attn_params(
            mid, len(self.channel_mult) - 1
        )
        for in_ch, out_ch, level in self._up_channels():
            total += self._res_params(in_ch, out_ch)
            if level in self.attn_levels:
                total += self._attn_params(out_ch, level)
        # text encoder(s)
        total += self.vocab_size * self.text_dim
        total += self.text_layers * 12 * self.text_dim * self.text_dim
        if self.text2_dim:
            total += self.vocab_size * self.text2_dim
            total += self.text2_layers * 12 * self.text2_dim * self.text2_dim
        # vae decoder
        v = self.vae_channels
        total += self.latent_channels * v * self.vae_channel_mult[-1] * 9
        for m in reversed(self.vae_channel_mult):
            total += (self.vae_res_blocks + 1) * self._res_params(
                v * m, v * m, vae=True
            )
        total += v * 3 * 9
        return int(total)

    def _res_params(self, in_ch: int, out_ch: int, vae: bool = False) -> int:
        p = in_ch * out_ch * 9 + out_ch * out_ch * 9
        if not vae:
            p += self.time_embed_dim * out_ch
        if in_ch != out_ch:
            p += in_ch * out_ch
        return p

    def _attn_params(self, ch: int, level: int) -> int:
        depth = self.transformer_depth[min(level, len(self.transformer_depth) - 1)]
        ctx = self.context_dim
        per_block = 4 * ch * ch + 2 * ch * ctx + 2 * ch * ch + 8 * ch * ch + 4 * ch * ch
        return 2 * ch * ch + depth * per_block

    def _down_channels(self):
        out = []
        ch = self.model_channels
        in_ch = ch
        for level, m in enumerate(self.channel_mult):
            out_ch = self.model_channels * m
            for _ in range(self.num_res_blocks):
                out.append((in_ch, out_ch, level))
                in_ch = out_ch
        return out

    def _up_channels(self):
        out = []
        # mirror of the down path: skip-concat doubles input channels
        down_outs = [self.model_channels]
        ch = self.model_channels
        for level, m in enumerate(self.channel_mult):
            for _ in range(self.num_res_blocks):
                ch = self.model_channels * m
                down_outs.append(ch)
            if level != len(self.channel_mult) - 1:
                down_outs.append(ch)
        in_ch = self.model_channels * self.channel_mult[-1]
        for rlevel, m in enumerate(reversed(self.channel_mult)):
            level = len(self.channel_mult) - 1 - rlevel
            out_ch = self.model_channels * m
            for _ in range(self.num_res_blocks + 1):
                skip = down_outs.pop()
                out.append((in_ch + skip, out_ch, level))
                in_ch = out_ch
        return out

    def weight_bytes(self, bits: int = 16) -> int:
        return self.param_count() * bits // 8


DIFFUSION_PRESETS: Dict[str, DiffusionConfig] = {
    "sd15-shaped": DiffusionConfig(name="sd15-shaped"),
    "sdxl-shaped": DiffusionConfig(
        name="sdxl-shaped",
        image_size=1024,
        scaling_factor=0.13025,
        channel_mult=(1, 2, 4),
        attn_levels=(1, 2),
        transformer_depth=(0, 2, 10),
        context_dim=2048,
        text2_dim=1280,
        text2_layers=32,
        text2_heads=20,
        text2_projection_dim=1280,
        addition_embed=True,
        num_heads=(5, 10, 20),
    ),
    "tiny-diffusion": DiffusionConfig(
        name="tiny-diffusion",
        image_size=32,
        vae_scale_factor=2,   # one VAE upsample (2 levels below)
        vocab_size=256,
        text_dim=16,
        text_layers=2,
        text_heads=2,
        max_text_len=16,
        model_channels=8,
        channel_mult=(1, 2),
        num_res_blocks=1,
        attn_levels=(0, 1),
        transformer_depth=(1, 1),
        num_heads=(2, 2),
        context_dim=16,
        vae_channels=8,
        vae_channel_mult=(1, 2),
        vae_res_blocks=1,
        train_timesteps=100,
    ),
}


def config_from_diffusers(model_dir: str, name: str = "") -> DiffusionConfig:
    """Build a DiffusionConfig from a local diffusers-format checkpoint
    (model_index.json + per-component config.json files)."""
    import json
    import os

    def read(*parts):
        try:
            with open(os.path.join(model_dir, *parts)) as f:
                return json.load(f)
        except OSError:
            return {}

    index = read("model_index.json")
    unet = read("unet", "config.json")
    vae = read("vae", "config.json")
    text = read("text_encoder", "config.json")
    text2 = read("text_encoder_2", "config.json")
    if not unet:
        raise ValueError(f"{model_dir} has no unet/config.json")

    block_types = unet.get("down_block_types", [])
    attn_levels = tuple(
        i for i, t in enumerate(block_types) if "CrossAttn" in t
    )
    block_out = unet.get("block_out_channels", [320, 640, 1280, 1280])
    base = block_out[0]
    depth = unet.get("transformer_layers_per_block", 1)
    if isinstance(depth, int):
        depth = [depth] * len(block_out)
    sample = unet.get("sample_size", 64)
    vae_scale = 2 ** (len(vae.get("block_out_channels", [0] * 4)) - 1)
    return DiffusionConfig(
        name=name or index.get("_class_name", "stable-diffusion"),
        image_size=sample * vae_scale,
        latent_channels=unet.get("in_channels", 4),
        vae_scale_factor=vae_scale,
        scaling_factor=vae.get("scaling_factor", 0.18215),
        vocab_size=text.get("vocab_size", 49408),
        text_dim=text.get("hidden_size", 768),
        text_layers=text.get("num_hidden_layers", 12),
        text_heads=text.get("num_attention_heads", 12),
        max_text_len=text.get("max_position_embeddings", 77),
        text_act=text.get("hidden_act", "quick_gelu"),
        text2_dim=text2.get("hidden_size", 0),
        text2_layers=text2.get("num_hidden_layers", 0),
        text2_heads=text2.get("num_attention_heads", 1) if text2 else 0,
        text2_act=text2.get("hidden_act", "gelu"),
        text2_projection_dim=text2.get("projection_dim", 0),
        model_channels=base,
        channel_mult=tuple(c // base for c in block_out),
        num_res_blocks=unet.get("layers_per_block", 2),
        attn_levels=attn_levels,
        transformer_depth=tuple(depth),
        num_heads=tuple(ahd)
        if isinstance(
            (ahd := unet.get("attention_head_dim", 8)), (list, tuple)
        )
        else (ahd,) * len(block_out),
        context_dim=unet.get("cross_attention_dim", 768),
        addition_embed=unet.get("addition_embed_type") == "text_time",
        addition_time_embed_dim=unet.get("addition_time_embed_dim", 256)
        or 256,
        vae_channels=(vae.get("block_out_channels") or [128])[0],
        vae_channel_mult=tuple(
            c // (vae.get("block_out_channels") or [128])[0]
            for c in vae.get("block_out_channels", [128, 256, 512, 512])
        ),
        vae_res_blocks=vae.get("layers_per_block", 2),
        train_timesteps=1000,
        beta_start=0.00085,
        beta_end=0.012,
        prediction_type=unet.get("prediction_type", "epsilon")
        if "prediction_type" in unet
        else "epsilon",
    )


# ---------------------------------------------------------------------------
# primitives


def _dtype(cfg: DiffusionConfig):
    return jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32


def group_norm(x: jax.Array, g: jax.Array, b: jax.Array, groups: int = 32) -> jax.Array:
    """GroupNorm over the channel (last) axis of NHWC / [B, T, C] input,
    computed in fp32."""
    orig_dtype = x.dtype
    C = x.shape[-1]
    groups = min(groups, C)
    while C % groups:
        groups -= 1
    xf = x.astype(jnp.float32)
    shape = x.shape[:-1] + (groups, C // groups)
    xg = xf.reshape(shape)
    axes = tuple(range(1, xg.ndim - 2)) + (xg.ndim - 1,)
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    xg = (xg - mean) * lax.rsqrt(var + 1e-6)
    out = xg.reshape(x.shape) * g + b
    return out.astype(orig_dtype)


def layer_norm(x: jax.Array, g: jax.Array, b: jax.Array) -> jax.Array:
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    return (((xf - mean) * lax.rsqrt(var + 1e-5)) * g + b).astype(x.dtype)


def conv2d(x: jax.Array, w: jax.Array, b: jax.Array, stride: int = 1,
           padding: int = 1) -> jax.Array:
    """NHWC conv; w is HWIO."""
    out = lax.conv_general_dilated(
        x, w.astype(x.dtype),
        window_strides=(stride, stride),
        padding=[(padding, padding), (padding, padding)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return out + b.astype(out.dtype)


def silu(x: jax.Array) -> jax.Array:
    return x * jax.nn.sigmoid(x)


def _act(name: str):
    if name == "quick_gelu":
        return lambda x: x * jax.nn.sigmoid(1.702 * x)
    return jax.nn.gelu


def timestep_embedding(t: jax.Array, dim: int, max_period: float = 10000.0) -> jax.Array:
    """Sinusoidal embedding [B] -> [B, dim] (fp32)."""
    half = dim // 2
    freqs = jnp.exp(
        -math.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half
    )
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    emb = jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)
    if dim % 2:
        emb = jnp.pad(emb, ((0, 0), (0, 1)))
    return emb


def _attention(q: jax.Array, k: jax.Array, v: jax.Array, heads: int) -> jax.Array:
    """[B, Tq, C] x [B, Tk, C] multi-head attention, fp32 softmax."""
    B, Tq, C = q.shape
    Tk = k.shape[1]
    hd = C // heads
    q = q.reshape(B, Tq, heads, hd).transpose(0, 2, 1, 3)
    k = k.reshape(B, Tk, heads, hd).transpose(0, 2, 1, 3)
    v = v.reshape(B, Tk, heads, hd).transpose(0, 2, 1, 3)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
    scores = scores / math.sqrt(hd)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    return out.transpose(0, 2, 1, 3).reshape(B, Tq, C)


# ---------------------------------------------------------------------------
# text encoder (CLIP-class)


def encode_text(params: Params, cfg: DiffusionConfig, tokens: jax.Array,
                which: str = "text") -> Tuple[jax.Array, jax.Array, jax.Array]:
    """tokens [B, T] -> (last_hidden [B, T, D], penultimate [B, T, D],
    pooled [B, D]). Pooled output = final-LN hidden at each row's last
    EOS/argmax token (CLIP convention: EOT has the highest token id)."""
    p = params[which]
    dim = cfg.text_dim if which == "text" else cfg.text2_dim
    heads = cfg.text_heads if which == "text" else cfg.text2_heads
    act = _act(cfg.text_act if which == "text" else cfg.text2_act)
    dt = _dtype(cfg)

    B, T = tokens.shape
    x = p["tok_emb"][tokens].astype(dt) + p["pos_emb"][:T].astype(dt)
    causal = jnp.tril(jnp.ones((T, T), jnp.bool_))

    def block(x, lp):
        h = layer_norm(x, lp["ln1_g"], lp["ln1_b"])
        q = h @ lp["wq"].astype(dt) + lp["bq"].astype(dt)
        k = h @ lp["wk"].astype(dt) + lp["bk"].astype(dt)
        v = h @ lp["wv"].astype(dt) + lp["bv"].astype(dt)
        hd = dim // heads
        qh = q.reshape(B, T, heads, hd).transpose(0, 2, 1, 3)
        kh = k.reshape(B, T, heads, hd).transpose(0, 2, 1, 3)
        vh = v.reshape(B, T, heads, hd).transpose(0, 2, 1, 3)
        scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh).astype(jnp.float32)
        scores = scores / math.sqrt(hd)
        scores = jnp.where(causal[None, None], scores, -1e9)
        probs = jax.nn.softmax(scores, axis=-1).astype(dt)
        attn = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
        attn = attn.transpose(0, 2, 1, 3).reshape(B, T, dim)
        x = x + attn @ lp["wo"].astype(dt) + lp["bo"].astype(dt)
        h = layer_norm(x, lp["ln2_g"], lp["ln2_b"])
        h = act(h @ lp["w1"].astype(dt) + lp["b1"].astype(dt))
        x = x + h @ lp["w2"].astype(dt) + lp["b2"].astype(dt)
        return x, x

    x, all_states = lax.scan(block, x, p["layers"])
    # all_states[i] is the output of layer i; penultimate = input of the
    # final layer = all_states[-2] (SDXL consumes it pre-final-LN)
    penultimate = all_states[-2] if all_states.shape[0] >= 2 else x
    last = layer_norm(x, p["lnf_g"], p["lnf_b"])
    eot = jnp.argmax(tokens, axis=-1)
    pooled = jnp.take_along_axis(
        last, eot[:, None, None].repeat(dim, axis=-1), axis=1
    )[:, 0]
    if "proj" in p:
        pooled = pooled @ p["proj"].astype(dt)
    return last, penultimate, pooled


# ---------------------------------------------------------------------------
# UNet


def _resblock(h: jax.Array, temb: jax.Array, p: Params) -> jax.Array:
    skip = h
    h = group_norm(h, p["norm1_g"], p["norm1_b"])
    h = conv2d(silu(h), p["conv1_w"], p["conv1_b"])
    if "temb_w" in p:
        t = silu(temb) @ p["temb_w"].astype(temb.dtype) + p["temb_b"].astype(temb.dtype)
        h = h + t[:, None, None, :].astype(h.dtype)
    h = group_norm(h, p["norm2_g"], p["norm2_b"])
    h = conv2d(silu(h), p["conv2_w"], p["conv2_b"])
    if "skip_w" in p:
        skip = jnp.einsum("bhwc,cd->bhwd", skip, p["skip_w"].astype(skip.dtype))
        skip = skip + p["skip_b"].astype(skip.dtype)
    return h + skip


def _transformer_block(x: jax.Array, context: jax.Array, p: Params,
                       heads: int) -> jax.Array:
    dt = x.dtype
    h = layer_norm(x, p["ln1_g"], p["ln1_b"])
    q = h @ p["attn1_q"].astype(dt)
    k = h @ p["attn1_k"].astype(dt)
    v = h @ p["attn1_v"].astype(dt)
    x = x + _attention(q, k, v, heads) @ p["attn1_o"].astype(dt) + p["attn1_ob"].astype(dt)
    h = layer_norm(x, p["ln2_g"], p["ln2_b"])
    q = h @ p["attn2_q"].astype(dt)
    k = context @ p["attn2_k"].astype(dt)
    v = context @ p["attn2_v"].astype(dt)
    x = x + _attention(q, k, v, heads) @ p["attn2_o"].astype(dt) + p["attn2_ob"].astype(dt)
    h = layer_norm(x, p["ln3_g"], p["ln3_b"])
    # GEGLU feed-forward
    hw = h @ p["ff_w1"].astype(dt) + p["ff_b1"].astype(dt)
    a, b = jnp.split(hw, 2, axis=-1)
    h = a * jax.nn.gelu(b)
    x = x + h @ p["ff_w2"].astype(dt) + p["ff_b2"].astype(dt)
    return x


def _spatial_transformer(h: jax.Array, context: jax.Array, p: Params,
                         heads: int) -> jax.Array:
    B, H, W, C = h.shape
    skip = h
    x = group_norm(h, p["norm_g"], p["norm_b"])
    x = x.reshape(B, H * W, C)
    x = x @ p["proj_in_w"].astype(x.dtype) + p["proj_in_b"].astype(x.dtype)
    for bp in p["blocks"]:
        x = _transformer_block(x, context, bp, heads)
    x = x @ p["proj_out_w"].astype(x.dtype) + p["proj_out_b"].astype(x.dtype)
    return skip + x.reshape(B, H, W, C)


def unet_apply(params: Params, cfg: DiffusionConfig, latents: jax.Array,
               t: jax.Array, context: jax.Array,
               added_cond: Optional[Dict[str, jax.Array]] = None) -> jax.Array:
    """latents [B, H, W, Cl], t [B], context [B, S, ctx] -> noise pred."""
    p = params["unet"]
    dt = _dtype(cfg)
    temb = timestep_embedding(t, cfg.model_channels)
    temb = temb @ p["time_w1"] + p["time_b1"]
    temb = silu(temb) @ p["time_w2"] + p["time_b2"]
    if cfg.addition_embed and added_cond is not None:
        # SDXL text_time conditioning: pooled text2 embedding + six
        # micro-conditioning scalars, each sinusoidally embedded
        ids = added_cond["time_ids"]                      # [B, 6]
        B = ids.shape[0]
        id_emb = timestep_embedding(
            ids.reshape(-1), cfg.addition_time_embed_dim
        ).reshape(B, -1)
        add = jnp.concatenate(
            [added_cond["pooled_text"].astype(jnp.float32), id_emb], axis=-1
        )
        add = add @ p["add_w1"] + p["add_b1"]
        temb = temb + (silu(add) @ p["add_w2"] + p["add_b2"])
    temb = temb.astype(dt)
    context = context.astype(dt)

    h = conv2d(latents.astype(dt), p["conv_in_w"], p["conv_in_b"])
    skips = [h]
    for level, lv in enumerate(p["down"]):
        for i, rp in enumerate(lv["res"]):
            h = _resblock(h, temb, rp)
            if lv["attn"] is not None:
                h = _spatial_transformer(
                    h, context, lv["attn"][i], cfg.heads_for(level)
                )
            skips.append(h)
        if lv["down"] is not None:
            h = conv2d(h, lv["down"]["w"], lv["down"]["b"], stride=2)
            skips.append(h)

    h = _resblock(h, temb, p["mid"]["res1"])
    h = _spatial_transformer(
        h, context, p["mid"]["attn"],
        cfg.heads_for(len(cfg.channel_mult) - 1),
    )
    h = _resblock(h, temb, p["mid"]["res2"])

    for ui, lv in enumerate(p["up"]):
        level = len(cfg.channel_mult) - 1 - ui
        for i, rp in enumerate(lv["res"]):
            h = jnp.concatenate([h, skips.pop()], axis=-1)
            h = _resblock(h, temb, rp)
            if lv["attn"] is not None:
                h = _spatial_transformer(
                    h, context, lv["attn"][i], cfg.heads_for(level)
                )
        if lv["up"] is not None:
            B, H, W, C = h.shape
            h = jax.image.resize(h, (B, H * 2, W * 2, C), "nearest")
            h = conv2d(h, lv["up"]["w"], lv["up"]["b"])

    h = group_norm(h, p["norm_out_g"], p["norm_out_b"])
    h = conv2d(silu(h), p["conv_out_w"], p["conv_out_b"])
    return h


# ---------------------------------------------------------------------------
# VAE decoder


def _vae_attn(h: jax.Array, p: Params) -> jax.Array:
    B, H, W, C = h.shape
    skip = h
    x = group_norm(h, p["norm_g"], p["norm_b"]).reshape(B, H * W, C)
    q = x @ p["q_w"].astype(x.dtype) + p["q_b"].astype(x.dtype)
    k = x @ p["k_w"].astype(x.dtype) + p["k_b"].astype(x.dtype)
    v = x @ p["v_w"].astype(x.dtype) + p["v_b"].astype(x.dtype)
    out = _attention(q, k, v, heads=1)
    out = out @ p["o_w"].astype(x.dtype) + p["o_b"].astype(x.dtype)
    return skip + out.reshape(B, H, W, C)


def vae_decode(params: Params, cfg: DiffusionConfig, z: jax.Array) -> jax.Array:
    """latents [B, h, w, Cl] -> images [B, H, W, 3] in [-1, 1]."""
    p = params["vae"]
    dt = _dtype(cfg)
    z = z.astype(dt) / cfg.scaling_factor
    z = jnp.einsum("bhwc,cd->bhwd", z, p["post_quant_w"].astype(dt))
    z = z + p["post_quant_b"].astype(dt)
    h = conv2d(z, p["conv_in_w"], p["conv_in_b"])
    h = _resblock(h, jnp.zeros((z.shape[0], 1), dt), p["mid"]["res1"])
    h = _vae_attn(h, p["mid"]["attn"])
    h = _resblock(h, jnp.zeros((z.shape[0], 1), dt), p["mid"]["res2"])
    for lv in p["up"]:
        for rp in lv["res"]:
            h = _resblock(h, jnp.zeros((z.shape[0], 1), dt), rp)
        if lv["up"] is not None:
            B, H, W, C = h.shape
            h = jax.image.resize(h, (B, H * 2, W * 2, C), "nearest")
            h = conv2d(h, lv["up"]["w"], lv["up"]["b"])
    h = group_norm(h, p["norm_out_g"], p["norm_out_b"])
    h = conv2d(silu(h), p["conv_out_w"], p["conv_out_b"])
    return jnp.clip(h.astype(jnp.float32), -1.0, 1.0)


# ---------------------------------------------------------------------------
# sampling (DDIM, classifier-free guidance)


def _alphas_cumprod(cfg: DiffusionConfig) -> np.ndarray:
    betas = (
        np.linspace(
            cfg.beta_start ** 0.5, cfg.beta_end ** 0.5, cfg.train_timesteps,
            dtype=np.float64,
        )
        ** 2
    )
    return np.cumprod(1.0 - betas).astype(np.float32)


@partial(
    jax.jit, static_argnames=("cfg", "steps", "height", "width")
)
def sample_images(
    params: Params,
    cfg: DiffusionConfig,
    key: jax.Array,
    cond_tokens: jax.Array,
    uncond_tokens: jax.Array,
    steps: int = 30,
    guidance: float = 7.5,
    height: int = 0,
    width: int = 0,
    cond_tokens2: Optional[jax.Array] = None,
    uncond_tokens2: Optional[jax.Array] = None,
) -> jax.Array:
    """DDIM sampling with classifier-free guidance. Returns images
    [B, H, W, 3] in [0, 1]. The whole pipeline (text encode → denoise
    loop → VAE decode) is ONE jitted XLA program, cached per
    (cfg, steps, size, batch) — ``guidance`` and the seed are traced, so
    changing them never recompiles."""
    height = height or cfg.image_size
    width = width or cfg.image_size
    lh, lw = height // cfg.vae_scale_factor, width // cfg.vae_scale_factor
    B = cond_tokens.shape[0]

    last_c, pen1_c, pooled_c = encode_text(params, cfg, cond_tokens)
    last_u, pen1_u, pooled_u = encode_text(params, cfg, uncond_tokens)
    # SD1.x conditions on encoder-1's final-LN output; SDXL was trained
    # on the PENULTIMATE hidden states of BOTH encoders (diffusers feeds
    # hidden_states[-2] for each) — using `last` for encoder 1 there
    # degrades every SDXL generation.
    context_c = pen1_c if cfg.text2_dim else last_c
    context_u = pen1_u if cfg.text2_dim else last_u
    added = None
    if cfg.text2_dim:
        ct2 = cond_tokens2 if cond_tokens2 is not None else cond_tokens
        ut2 = uncond_tokens2 if uncond_tokens2 is not None else uncond_tokens
        _, pen_c, pooled_c2 = encode_text(params, cfg, ct2, which="text2")
        _, pen_u, pooled_u2 = encode_text(params, cfg, ut2, which="text2")
        context_c = jnp.concatenate([context_c, pen_c], axis=-1)
        context_u = jnp.concatenate([context_u, pen_u], axis=-1)
        if cfg.addition_embed:
            time_ids = jnp.asarray(
                [[height, width, 0, 0, height, width]], jnp.float32
            ).repeat(B, axis=0)
            added = {
                "pooled_text": jnp.concatenate(
                    [pooled_u2, pooled_c2], axis=0
                ),
                "time_ids": jnp.concatenate([time_ids, time_ids], axis=0),
            }
    # one batched pass: rows [0..B) uncond, [B..2B) cond
    context = jnp.concatenate([context_u, context_c], axis=0)

    acp = jnp.asarray(_alphas_cumprod(cfg))
    ts = np.linspace(
        cfg.train_timesteps - 1, 0, steps, dtype=np.float64
    ).round().astype(np.int32)
    ts = jnp.asarray(ts)
    prev_ts = jnp.concatenate([ts[1:], jnp.array([-1], jnp.int32)])

    noise = jax.random.normal(key, (B, lh, lw, cfg.latent_channels), jnp.float32)

    def step(i, lat):
        t = ts[i]
        a_t = acp[t]
        a_prev = jnp.where(prev_ts[i] >= 0, acp[jnp.maximum(prev_ts[i], 0)], 1.0)
        lat_in = jnp.concatenate([lat, lat], axis=0)
        tb = jnp.full((2 * B,), t, jnp.int32)
        out = unet_apply(
            params, cfg, lat_in, tb, context, added_cond=added
        ).astype(jnp.float32)
        eps_u, eps_c = out[:B], out[B:]
        eps = eps_u + guidance * (eps_c - eps_u)
        if cfg.prediction_type == "v_prediction":
            # v = sqrt(a) eps - sqrt(1-a) x0  =>  recover eps
            eps = jnp.sqrt(a_t) * eps + jnp.sqrt(1.0 - a_t) * lat
        x0 = (lat - jnp.sqrt(1.0 - a_t) * eps) / jnp.sqrt(a_t)
        x0 = jnp.clip(x0, -10.0, 10.0)
        return jnp.sqrt(a_prev) * x0 + jnp.sqrt(1.0 - a_prev) * eps

    latents = lax.fori_loop(0, steps, step, noise)
    images = vae_decode(params, cfg, latents)
    return (images + 1.0) / 2.0


# ---------------------------------------------------------------------------
# init (tests, presets, synthetic serving)


def _linear(key, din, dout, scale=0.02):
    return jax.random.normal(key, (din, dout), jnp.float32) * scale


def _conv(key, kh, kw, cin, cout, scale=0.02):
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * scale


def _init_text(cfg: DiffusionConfig, key, which: str) -> Params:
    dim = cfg.text_dim if which == "text" else cfg.text2_dim
    layers = cfg.text_layers if which == "text" else cfg.text2_layers
    ks = jax.random.split(key, 16)
    L = layers

    def stack(k, shape, scale=0.02):
        return jax.random.normal(k, (L,) + shape, jnp.float32) * scale

    p = {
        "tok_emb": _linear(ks[0], cfg.vocab_size, dim),
        "pos_emb": _linear(ks[1], cfg.max_text_len, dim),
        "layers": {
            "ln1_g": jnp.ones((L, dim)), "ln1_b": jnp.zeros((L, dim)),
            "wq": stack(ks[2], (dim, dim)), "bq": jnp.zeros((L, dim)),
            "wk": stack(ks[3], (dim, dim)), "bk": jnp.zeros((L, dim)),
            "wv": stack(ks[4], (dim, dim)), "bv": jnp.zeros((L, dim)),
            "wo": stack(ks[5], (dim, dim)), "bo": jnp.zeros((L, dim)),
            "ln2_g": jnp.ones((L, dim)), "ln2_b": jnp.zeros((L, dim)),
            "w1": stack(ks[6], (dim, 4 * dim)),
            "b1": jnp.zeros((L, 4 * dim)),
            "w2": stack(ks[7], (4 * dim, dim)),
            "b2": jnp.zeros((L, dim)),
        },
        "lnf_g": jnp.ones((dim,)), "lnf_b": jnp.zeros((dim,)),
    }
    if which == "text2" and cfg.text2_projection_dim:
        p["proj"] = _linear(ks[8], dim, cfg.text2_projection_dim)
    return p


def _init_res(key, in_ch, out_ch, time_dim=0) -> Params:
    ks = jax.random.split(key, 4)
    p = {
        "norm1_g": jnp.ones((in_ch,)), "norm1_b": jnp.zeros((in_ch,)),
        "conv1_w": _conv(ks[0], 3, 3, in_ch, out_ch),
        "conv1_b": jnp.zeros((out_ch,)),
        "norm2_g": jnp.ones((out_ch,)), "norm2_b": jnp.zeros((out_ch,)),
        "conv2_w": _conv(ks[1], 3, 3, out_ch, out_ch),
        "conv2_b": jnp.zeros((out_ch,)),
    }
    if time_dim:
        p["temb_w"] = _linear(ks[2], time_dim, out_ch)
        p["temb_b"] = jnp.zeros((out_ch,))
    if in_ch != out_ch:
        p["skip_w"] = _linear(ks[3], in_ch, out_ch)
        p["skip_b"] = jnp.zeros((out_ch,))
    return p


def _init_spatial(cfg: DiffusionConfig, key, ch: int, depth: int) -> Params:
    ks = jax.random.split(key, 2 + depth)
    blocks = []
    ctx = cfg.context_dim
    for d in range(depth):
        bk = jax.random.split(ks[2 + d], 10)
        blocks.append({
            "ln1_g": jnp.ones((ch,)), "ln1_b": jnp.zeros((ch,)),
            "attn1_q": _linear(bk[0], ch, ch),
            "attn1_k": _linear(bk[1], ch, ch),
            "attn1_v": _linear(bk[2], ch, ch),
            "attn1_o": _linear(bk[3], ch, ch),
            "attn1_ob": jnp.zeros((ch,)),
            "ln2_g": jnp.ones((ch,)), "ln2_b": jnp.zeros((ch,)),
            "attn2_q": _linear(bk[4], ch, ch),
            "attn2_k": _linear(bk[5], ctx, ch),
            "attn2_v": _linear(bk[6], ctx, ch),
            "attn2_o": _linear(bk[7], ch, ch),
            "attn2_ob": jnp.zeros((ch,)),
            "ln3_g": jnp.ones((ch,)), "ln3_b": jnp.zeros((ch,)),
            "ff_w1": _linear(bk[8], ch, 8 * ch),
            "ff_b1": jnp.zeros((8 * ch,)),
            "ff_w2": _linear(bk[9], 4 * ch, ch),
            "ff_b2": jnp.zeros((ch,)),
        })
    return {
        "norm_g": jnp.ones((ch,)), "norm_b": jnp.zeros((ch,)),
        "proj_in_w": _linear(ks[0], ch, ch),
        "proj_in_b": jnp.zeros((ch,)),
        "blocks": blocks,
        "proj_out_w": _linear(ks[1], ch, ch),
        "proj_out_b": jnp.zeros((ch,)),
    }


def init_diffusion_params(cfg: DiffusionConfig, key: jax.Array) -> Params:
    """Random-init the full pipeline (text encoder(s) + UNet + VAE
    decoder). Used by tests, synthetic presets, and the image engine's
    no-checkpoint mode."""
    k_text, k_text2, k_unet, k_vae = jax.random.split(key, 4)
    params: Params = {"text": _init_text(cfg, k_text, "text")}
    if cfg.text2_dim:
        params["text2"] = _init_text(cfg, k_text2, "text2")

    td = cfg.time_embed_dim
    mc = cfg.model_channels
    uks = iter(jax.random.split(k_unet, 256))
    unet: Params = {
        "time_w1": _linear(next(uks), mc, td), "time_b1": jnp.zeros((td,)),
        "time_w2": _linear(next(uks), td, td), "time_b2": jnp.zeros((td,)),
        "conv_in_w": _conv(next(uks), 3, 3, cfg.latent_channels, mc),
        "conv_in_b": jnp.zeros((mc,)),
    }
    if cfg.addition_embed:
        add_in = (
            cfg.text2_projection_dim + 6 * cfg.addition_time_embed_dim
        )
        unet["add_w1"] = _linear(next(uks), add_in, td)
        unet["add_b1"] = jnp.zeros((td,))
        unet["add_w2"] = _linear(next(uks), td, td)
        unet["add_b2"] = jnp.zeros((td,))

    def depth_for(level):
        return cfg.transformer_depth[
            min(level, len(cfg.transformer_depth) - 1)
        ]

    down = []
    in_ch = mc
    for level, m in enumerate(cfg.channel_mult):
        out_ch = mc * m
        res, attn = [], []
        for _ in range(cfg.num_res_blocks):
            res.append(_init_res(next(uks), in_ch, out_ch, td))
            if level in cfg.attn_levels:
                attn.append(
                    _init_spatial(cfg, next(uks), out_ch, depth_for(level))
                )
            in_ch = out_ch
        lv = {
            "res": res,
            "attn": attn if level in cfg.attn_levels else None,
            "down": None,
        }
        if level != len(cfg.channel_mult) - 1:
            lv["down"] = {
                "w": _conv(next(uks), 3, 3, out_ch, out_ch),
                "b": jnp.zeros((out_ch,)),
            }
        down.append(lv)
    unet["down"] = down

    mid_ch = mc * cfg.channel_mult[-1]
    unet["mid"] = {
        "res1": _init_res(next(uks), mid_ch, mid_ch, td),
        "attn": _init_spatial(
            cfg, next(uks), mid_ch, depth_for(len(cfg.channel_mult) - 1)
        ),
        "res2": _init_res(next(uks), mid_ch, mid_ch, td),
    }

    # skip-channel bookkeeping mirrors the down path
    down_outs = [mc]
    ch = mc
    for level, m in enumerate(cfg.channel_mult):
        for _ in range(cfg.num_res_blocks):
            ch = mc * m
            down_outs.append(ch)
        if level != len(cfg.channel_mult) - 1:
            down_outs.append(ch)

    up = []
    in_ch = mid_ch
    for rlevel, m in enumerate(reversed(cfg.channel_mult)):
        level = len(cfg.channel_mult) - 1 - rlevel
        out_ch = mc * m
        res, attn = [], []
        for _ in range(cfg.num_res_blocks + 1):
            skip_ch = down_outs.pop()
            res.append(_init_res(next(uks), in_ch + skip_ch, out_ch, td))
            if level in cfg.attn_levels:
                attn.append(
                    _init_spatial(cfg, next(uks), out_ch, depth_for(level))
                )
            in_ch = out_ch
        lv = {
            "res": res,
            "attn": attn if level in cfg.attn_levels else None,
            "up": None,
        }
        if rlevel != len(cfg.channel_mult) - 1:
            lv["up"] = {
                "w": _conv(next(uks), 3, 3, out_ch, out_ch),
                "b": jnp.zeros((out_ch,)),
            }
        up.append(lv)
    unet["up"] = up
    unet["norm_out_g"] = jnp.ones((mc,))
    unet["norm_out_b"] = jnp.zeros((mc,))
    unet["conv_out_w"] = _conv(next(uks), 3, 3, mc, cfg.latent_channels)
    unet["conv_out_b"] = jnp.zeros((cfg.latent_channels,))
    params["unet"] = unet

    vks = iter(jax.random.split(k_vae, 64))
    vc = cfg.vae_channels
    top = vc * cfg.vae_channel_mult[-1]
    vae: Params = {
        "post_quant_w": _linear(
            next(vks), cfg.latent_channels, cfg.latent_channels
        ),
        "post_quant_b": jnp.zeros((cfg.latent_channels,)),
        "conv_in_w": _conv(next(vks), 3, 3, cfg.latent_channels, top),
        "conv_in_b": jnp.zeros((top,)),
        "mid": {
            "res1": _init_res(next(vks), top, top),
            "attn": {
                "norm_g": jnp.ones((top,)), "norm_b": jnp.zeros((top,)),
                "q_w": _linear(next(vks), top, top), "q_b": jnp.zeros((top,)),
                "k_w": _linear(next(vks), top, top), "k_b": jnp.zeros((top,)),
                "v_w": _linear(next(vks), top, top), "v_b": jnp.zeros((top,)),
                "o_w": _linear(next(vks), top, top), "o_b": jnp.zeros((top,)),
            },
            "res2": _init_res(next(vks), top, top),
        },
    }
    vup = []
    in_ch = top
    for rlevel, m in enumerate(reversed(cfg.vae_channel_mult)):
        out_ch = vc * m
        res = []
        for _ in range(cfg.vae_res_blocks + 1):
            res.append(_init_res(next(vks), in_ch, out_ch))
            in_ch = out_ch
        lv = {"res": res, "up": None}
        if rlevel != len(cfg.vae_channel_mult) - 1:
            lv["up"] = {
                "w": _conv(next(vks), 3, 3, out_ch, out_ch),
                "b": jnp.zeros((out_ch,)),
            }
        vup.append(lv)
    vae["up"] = vup
    vae["norm_out_g"] = jnp.ones((vc,))
    vae["norm_out_b"] = jnp.zeros((vc,))
    vae["conv_out_w"] = _conv(next(vks), 3, 3, vc, 3)
    vae["conv_out_b"] = jnp.zeros((3,))
    params["vae"] = vae
    return params
