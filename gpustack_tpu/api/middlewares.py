"""aiohttp middlewares: tracing + authentication + request logging.

Reference analogue: the FastAPI dependency chain ``get_current_user``
(gpustack/api/auth.py:118) + middleware stack (server/app.py:26).

``timing_middleware`` is the trace edge: it mints (or adopts from
``traceparent``/``X-Request-ID``) the request's trace context, echoes
``X-Request-ID`` on every response, and emits ONE access log line per
request — trace id, principal kind, status, per-phase breakdown —
which is also where slow requests surface (threshold:
``Config.slow_request_ms``). It must be the OUTERMOST middleware so
auth time and auth failures are traced too."""

from __future__ import annotations

import logging
import re

from aiohttp import web

from gpustack_tpu.api import auth as auth_mod
from gpustack_tpu.observability import tracing

logger = logging.getLogger(__name__)

# Paths reachable without a principal.
PUBLIC_PATHS = {
    "/healthz",
    "/readyz",
    "/auth/login",
    "/auth/oidc/login",
    "/auth/oidc/callback",
    "/auth/saml/login",
    "/auth/saml/acs",
    "/auth/cas/login",
    "/auth/cas/callback",
    "/v2/workers/register",
    "/metrics",
}

# Worker tokens are confined to the routes the agent actually needs
# (reference confines worker credentials to worker endpoints — a
# compromised worker must not be able to read users/usage or mutate other
# workers' resources). Everything else on /v2 is denied for kind=worker;
# per-record ownership is enforced again inside the CRUD write guard.
_WORKER_ROUTE_ALLOWLIST = (
    ("POST", re.compile(r"^/v2/workers/\d+/(status|heartbeat)$")),
    ("GET", re.compile(r"^/v2/tunnel$")),
    # reads + watch streams the agent's reconcile loops depend on
    ("GET", re.compile(
        r"^/v2/(models|model-instances|model-files|benchmarks|"
        r"inference-backends|workers|dev-instances)(/\d+)?$"
    )),
    # instance/file/benchmark state reporting (ownership-guarded in crud)
    ("POST", re.compile(r"^/v2/model-files$")),
    ("PUT", re.compile(
        r"^/v2/(model-instances|model-files|benchmarks|dev-instances)"
        r"/\d+$"
    )),
    ("PATCH", re.compile(
        r"^/v2/(model-instances|model-files|benchmarks|dev-instances)"
        r"/\d+$"
    )),
    # graceful-drain retirement: the owning worker deletes its drained
    # instance row so replica sync creates a replacement (ownership is
    # enforced in crud's instance_worker_owns — a worker can only ever
    # delete instances placed on itself)
    ("DELETE", re.compile(r"^/v2/model-instances/\d+$")),
)


def worker_route_allowed(method: str, path: str) -> bool:
    return any(
        method == m and rx.match(path)
        for m, rx in _WORKER_ROUTE_ALLOWLIST
    )


def _extract_token(request: web.Request) -> str:
    authz = request.headers.get("Authorization", "")
    if authz.startswith("Bearer "):
        return authz[7:]
    from gpustack_tpu.routes.auth_routes import SESSION_COOKIE

    return request.cookies.get(SESSION_COOKIE, "")


@web.middleware
async def auth_middleware(request: web.Request, handler):
    path = request.path
    if path in PUBLIC_PATHS:
        return await handler(request)
    cfg = request.app["config"]
    token = _extract_token(request)
    trace = request.get("trace")
    if trace is not None:
        trace.begin("auth")
    principal = await auth_mod.authenticate(token, cfg.jwt_secret)
    if trace is not None:
        trace.end("auth")
    if principal is None:
        return web.json_response(
            {"error": "authentication required"}, status=401
        )
    if path.startswith("/v1/") and not principal.has_scope("inference"):
        if principal.kind == "user":
            return web.json_response(
                {"error": "token lacks inference scope"}, status=403
            )
    if path.startswith("/v2/") and principal.kind == "user":
        if not principal.has_scope("management"):
            return web.json_response(
                {"error": "token lacks management scope"}, status=403
            )
    if path.startswith("/v2/") and principal.kind == "worker":
        if not worker_route_allowed(request.method, path):
            return web.json_response(
                {"error": "worker tokens cannot access this route"},
                status=403,
            )
    request["principal"] = principal
    return await handler(request)


@web.middleware
async def timing_middleware(request: web.Request, handler):
    # machine chatter (health probes, metrics scrapes) must not flood
    # the access log or evict real requests from the trace ring
    if request.path in tracing.UNTRACED_PATHS:
        return await handler(request)
    ctx = tracing.from_headers(request.headers)
    trace = tracing.RequestTrace(
        ctx, "server", f"{request.method} {request.path}"
    )
    request["trace"] = trace
    status = 500
    try:
        try:
            resp = await handler(request)
        except web.HTTPException as e:
            # router 404s/405s propagate as exceptions — they are
            # ordinary responses, not server errors
            status = e.status
            e.headers.setdefault(
                tracing.REQUEST_ID_HEADER, ctx.request_id
            )
            raise
        status = resp.status
        if not resp.prepared:
            # streamed responses (SSE relays, log follow) set these
            # themselves before prepare(); everything else gets them here
            resp.headers.setdefault(
                tracing.REQUEST_ID_HEADER, ctx.request_id
            )
            resp.headers.setdefault(
                tracing.TRACEPARENT_HEADER, ctx.traceparent()
            )
        return resp
    finally:
        principal = request.get("principal")
        kind = principal.kind if principal else "-"
        phases = trace.phases          # sealed by finish() below
        elapsed_ms = trace.finish(
            status=status, log=False, principal=kind,
        )
        logger.info(
            "access %s %s status=%d ms=%.1f trace=%s req=%s "
            "principal=%s model=%s phases=[%s]",
            request.method, request.path, status, elapsed_ms,
            ctx.trace_id, ctx.request_id, kind, trace.model or "-",
            " ".join(
                f"{p['phase']}:{p['duration_ms']:.1f}" for p in phases
            ),
        )
        slow_ms = getattr(
            request.app.get("config"), "slow_request_ms", 1000.0
        )
        if elapsed_ms > slow_ms:
            logger.warning(
                "slow request: %s %s took %.0fms (threshold %.0fms) "
                "trace=%s",
                request.method, request.path, elapsed_ms, slow_ms,
                ctx.trace_id,
            )
