"""aiohttp middlewares: authentication + request logging.

Reference analogue: the FastAPI dependency chain ``get_current_user``
(gpustack/api/auth.py:118) + middleware stack (server/app.py:26)."""

from __future__ import annotations

import logging
import re
import time

from aiohttp import web

from gpustack_tpu.api import auth as auth_mod

logger = logging.getLogger(__name__)

# Paths reachable without a principal.
PUBLIC_PATHS = {
    "/healthz",
    "/readyz",
    "/auth/login",
    "/auth/oidc/login",
    "/auth/oidc/callback",
    "/auth/saml/login",
    "/auth/saml/acs",
    "/auth/cas/login",
    "/auth/cas/callback",
    "/v2/workers/register",
    "/metrics",
}

# Worker tokens are confined to the routes the agent actually needs
# (reference confines worker credentials to worker endpoints — a
# compromised worker must not be able to read users/usage or mutate other
# workers' resources). Everything else on /v2 is denied for kind=worker;
# per-record ownership is enforced again inside the CRUD write guard.
_WORKER_ROUTE_ALLOWLIST = (
    ("POST", re.compile(r"^/v2/workers/\d+/(status|heartbeat)$")),
    ("GET", re.compile(r"^/v2/tunnel$")),
    # reads + watch streams the agent's reconcile loops depend on
    ("GET", re.compile(
        r"^/v2/(models|model-instances|model-files|benchmarks|"
        r"inference-backends|workers|dev-instances)(/\d+)?$"
    )),
    # instance/file/benchmark state reporting (ownership-guarded in crud)
    ("POST", re.compile(r"^/v2/model-files$")),
    ("PUT", re.compile(
        r"^/v2/(model-instances|model-files|benchmarks|dev-instances)"
        r"/\d+$"
    )),
    ("PATCH", re.compile(
        r"^/v2/(model-instances|model-files|benchmarks|dev-instances)"
        r"/\d+$"
    )),
    # graceful-drain retirement: the owning worker deletes its drained
    # instance row so replica sync creates a replacement (ownership is
    # enforced in crud's instance_worker_owns — a worker can only ever
    # delete instances placed on itself)
    ("DELETE", re.compile(r"^/v2/model-instances/\d+$")),
)


def worker_route_allowed(method: str, path: str) -> bool:
    return any(
        method == m and rx.match(path)
        for m, rx in _WORKER_ROUTE_ALLOWLIST
    )


def _extract_token(request: web.Request) -> str:
    authz = request.headers.get("Authorization", "")
    if authz.startswith("Bearer "):
        return authz[7:]
    from gpustack_tpu.routes.auth_routes import SESSION_COOKIE

    return request.cookies.get(SESSION_COOKIE, "")


@web.middleware
async def auth_middleware(request: web.Request, handler):
    path = request.path
    if path in PUBLIC_PATHS:
        return await handler(request)
    cfg = request.app["config"]
    token = _extract_token(request)
    principal = await auth_mod.authenticate(token, cfg.jwt_secret)
    if principal is None:
        return web.json_response(
            {"error": "authentication required"}, status=401
        )
    if path.startswith("/v1/") and not principal.has_scope("inference"):
        if principal.kind == "user":
            return web.json_response(
                {"error": "token lacks inference scope"}, status=403
            )
    if path.startswith("/v2/") and principal.kind == "user":
        if not principal.has_scope("management"):
            return web.json_response(
                {"error": "token lacks management scope"}, status=403
            )
    if path.startswith("/v2/") and principal.kind == "worker":
        if not worker_route_allowed(request.method, path):
            return web.json_response(
                {"error": "worker tokens cannot access this route"},
                status=403,
            )
    request["principal"] = principal
    return await handler(request)


@web.middleware
async def timing_middleware(request: web.Request, handler):
    start = time.monotonic()
    try:
        return await handler(request)
    finally:
        elapsed = (time.monotonic() - start) * 1e3
        if elapsed > 1000:
            logger.warning(
                "slow request: %s %s took %.0fms",
                request.method, request.path, elapsed,
            )
