"""Tenant scoping: which models can this principal see and use?

Reference parity: gpustack/api/tenant.py TenantContext — org membership
filters both the management API (model listings) and the inference path
(model resolution in the OpenAI proxy). Admin and system principals see
everything; worker principals see everything (they must serve any model
placed on them); plain users see unscoped models (org_id=0) plus models
of orgs they belong to.
"""

from __future__ import annotations

from typing import Optional, Set

from gpustack_tpu.schemas import Model, OrgMember


async def accessible_org_ids(principal) -> Optional[Set[int]]:
    """Org ids the principal may access; None = unrestricted."""
    if principal is None:
        return set()
    if principal.is_admin or principal.kind in ("worker", "system"):
        return None
    if principal.user is None:
        return set()
    members = await OrgMember.filter(user_id=principal.user.id)
    return {m.org_id for m in members}


async def org_scoped_accessible(principal, obj) -> bool:
    """Generic org-scope check for any record with an ``org_id`` field
    (models, external providers, ...): unscoped records (org_id=0) are
    visible to everyone; scoped ones to members/admin/system only."""
    if obj.org_id == 0:
        return True
    orgs = await accessible_org_ids(principal)
    return orgs is None or obj.org_id in orgs


async def model_accessible(principal, model: Model) -> bool:
    return await org_scoped_accessible(principal, model)


async def visible_models(principal, models):
    """Filter a model list down to what the principal may see."""
    orgs = await accessible_org_ids(principal)
    if orgs is None:
        return list(models)
    return [m for m in models if m.org_id == 0 or m.org_id in orgs]
