"""API-layer helpers: auth, middlewares (reference gpustack/api)."""
