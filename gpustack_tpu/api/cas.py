"""CAS (Central Authentication Service) client: login redirect + ticket
validation.

Reference parity: routes/auth.py CAS flow. Protocol v2/v3
``serviceValidate``: the browser returns from the CAS server with a
service ticket; we validate it server-to-server and read the username
from the XML envelope. XML parsing is entity/network-hardened.
"""

from __future__ import annotations

import urllib.parse
from typing import Any, Dict

import aiohttp
from lxml import etree

CAS_NS = {"cas": "http://www.yale.edu/tp/cas"}
_PARSER = etree.XMLParser(
    resolve_entities=False, no_network=True, huge_tree=False
)


class CASError(ValueError):
    pass


class CASProvider:
    def __init__(self, cas_url: str) -> None:
        self.cas_url = cas_url.rstrip("/")
        self._session = None   # lazy long-lived pool (one per provider)

    def _http(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        if self._session and not self._session.closed:
            await self._session.close()

    def login_url(self, service: str) -> str:
        return (
            f"{self.cas_url}/login?"
            + urllib.parse.urlencode({"service": service})
        )

    async def validate(self, ticket: str, service: str) -> Dict[str, Any]:
        """serviceValidate; returns {"user": ..., "attributes": {...}}."""
        url = (
            f"{self.cas_url}/serviceValidate?"
            + urllib.parse.urlencode(
                {"ticket": ticket, "service": service}
            )
        )
        async with self._http().get(
            url, timeout=aiohttp.ClientTimeout(total=10)
        ) as r:
            if r.status != 200:
                raise CASError(
                    f"CAS serviceValidate HTTP {r.status}"
                )
            body = await r.read()
        try:
            root = etree.fromstring(body, parser=_PARSER)
        except etree.XMLSyntaxError as e:
            raise CASError(f"malformed CAS response: {e}")
        failure = root.find("cas:authenticationFailure", CAS_NS)
        if failure is not None:
            raise CASError(
                f"CAS rejected ticket: {failure.get('code', '')} "
                f"{(failure.text or '').strip()}"
            )
        success = root.find("cas:authenticationSuccess", CAS_NS)
        if success is None:
            raise CASError("CAS response carries no success element")
        user = success.findtext(
            "cas:user", default="", namespaces=CAS_NS
        ).strip()
        if not user:
            raise CASError("CAS success carries no user")
        attributes: Dict[str, Any] = {}
        attrs = success.find("cas:attributes", CAS_NS)
        if attrs is not None:
            for child in attrs:
                tag = etree.QName(child).localname
                attributes[tag] = (child.text or "").strip()
        return {"user": user, "attributes": attributes}
