"""OIDC single sign-on: authorization-code flow (reference routes/auth.py
OIDC section of the 1,415-LoC SSO module; SAML/CAS are round-3).

Flow: ``/auth/oidc/login`` redirects to the issuer's authorization
endpoint with an HMAC-signed state (CSRF); ``/auth/oidc/callback``
exchanges the code at the token endpoint (client-secret auth over TLS),
verifies the returned id_token — RS256 against the issuer's JWKS via
``cryptography``, or HS256 with the client secret — maps claims to a
local user (auto-provisioned on first login), and issues the normal
session JWT.

Discovery (``/.well-known/openid-configuration``) and JWKS are fetched
lazily and cached per process.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import logging
import time
from typing import Any, Dict, Optional

import aiohttp

logger = logging.getLogger(__name__)

STATE_TTL = 600.0

# reuse the auth module's padding-sensitive base64url helpers
from gpustack_tpu.api.auth import _b64 as _b64url  # noqa: E402
from gpustack_tpu.api.auth import _unb64 as _unb64url  # noqa: E402

NONCE_COOKIE = "gpustack_tpu_oidc_nonce"


def make_state(secret: str, nonce: str) -> str:
    """State bound to a per-browser nonce (set as a short-lived cookie at
    login): an attacker cannot splice their own authorization code into a
    victim's callback, because the victim's browser lacks the matching
    nonce cookie (login-CSRF / session fixation defense)."""
    ts = str(int(time.time()))
    sig = hmac.new(
        secret.encode(), f"oidc:{ts}:{nonce}".encode(), hashlib.sha256
    ).hexdigest()[:32]
    return f"{ts}.{sig}"


def check_state(state: str, secret: str, nonce: str) -> bool:
    try:
        ts, sig = state.split(".")
        expect = hmac.new(
            secret.encode(), f"oidc:{ts}:{nonce}".encode(),
            hashlib.sha256,
        ).hexdigest()[:32]
        return (
            hmac.compare_digest(sig, expect)
            and time.time() - float(ts) < STATE_TTL
        )
    except (ValueError, TypeError):
        return False


class OIDCProvider:
    def __init__(
        self,
        issuer: str,
        client_id: str,
        client_secret: str,
        session: Optional[aiohttp.ClientSession] = None,
    ):
        self.issuer = issuer.rstrip("/")
        self.client_id = client_id
        self.client_secret = client_secret
        # shared pooled session (per-request sessions are an aiohttp
        # antipattern — token exchange runs on every SSO login)
        self._session = session
        self._discovery: Optional[Dict[str, Any]] = None
        self._jwks: Optional[Dict[str, Any]] = None

    def _http(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def discovery(self) -> Dict[str, Any]:
        if self._discovery is None:
            url = self.issuer + "/.well-known/openid-configuration"
            async with self._http().get(
                url, timeout=aiohttp.ClientTimeout(total=10)
            ) as resp:
                resp.raise_for_status()
                self._discovery = await resp.json()
        return self._discovery

    async def jwks(self, refresh: bool = False) -> Dict[str, Any]:
        if self._jwks is None or refresh:
            disc = await self.discovery()
            async with self._http().get(
                disc["jwks_uri"],
                timeout=aiohttp.ClientTimeout(total=10),
            ) as resp:
                resp.raise_for_status()
                self._jwks = await resp.json()
        return self._jwks

    async def auth_url(self, redirect_uri: str, state: str) -> str:
        from urllib.parse import urlencode

        disc = await self.discovery()
        query = urlencode(
            {
                "response_type": "code",
                "client_id": self.client_id,
                "redirect_uri": redirect_uri,
                "scope": "openid profile email",
                "state": state,
            }
        )
        return f"{disc['authorization_endpoint']}?{query}"

    async def exchange_code(
        self, code: str, redirect_uri: str
    ) -> Dict[str, Any]:
        disc = await self.discovery()
        async with self._http().post(
            disc["token_endpoint"],
            data={
                "grant_type": "authorization_code",
                "code": code,
                "redirect_uri": redirect_uri,
                "client_id": self.client_id,
                "client_secret": self.client_secret,
            },
            timeout=aiohttp.ClientTimeout(total=15),
        ) as resp:
            body = await resp.json()
            if resp.status != 200:
                raise ValueError(f"token exchange failed: {body}")
            return body

    async def verify_id_token(self, token: str) -> Dict[str, Any]:
        """Verify signature + iss/aud/exp; returns the claims."""
        try:
            header_b64, body_b64, sig_b64 = token.split(".")
            header = json.loads(_unb64url(header_b64))
            claims = json.loads(_unb64url(body_b64))
        except (ValueError, json.JSONDecodeError) as e:
            raise ValueError(f"malformed id_token: {e}")
        signing = f"{header_b64}.{body_b64}".encode()
        sig = _unb64url(sig_b64)
        alg = header.get("alg")
        if alg == "HS256":
            expect = hmac.new(
                self.client_secret.encode(), signing, hashlib.sha256
            ).digest()
            if not hmac.compare_digest(expect, sig):
                raise ValueError("id_token HS256 signature mismatch")
        elif alg == "RS256":
            await self._verify_rs256(header, signing, sig)
        else:
            raise ValueError(f"unsupported id_token alg {alg!r}")
        if claims.get("iss", "").rstrip("/") != self.issuer:
            raise ValueError("id_token issuer mismatch")
        aud = claims.get("aud")
        auds = aud if isinstance(aud, list) else [aud]
        if self.client_id not in auds:
            raise ValueError("id_token audience mismatch")
        if claims.get("exp", 0) < time.time():
            raise ValueError("id_token expired")
        return claims

    async def _verify_rs256(
        self, header: Dict[str, Any], signing: bytes, sig: bytes
    ) -> None:
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import (
            padding,
            rsa,
        )

        kid = header.get("kid")

        def find(keys):
            return next(
                (
                    k for k in keys
                    if k.get("kty") == "RSA"
                    and (kid is None or k.get("kid") == kid)
                ),
                None,
            )

        jwk = find((await self.jwks()).get("keys", []))
        if jwk is None:
            # IdPs rotate signing keys (daily at some providers): one
            # refetch on kid miss, or SSO breaks until a server restart
            jwk = find(
                (await self.jwks(refresh=True)).get("keys", [])
            )
        if jwk is None:
            raise ValueError(f"no RSA JWK for kid {kid!r}")
        n = int.from_bytes(_unb64url(jwk["n"]), "big")
        e = int.from_bytes(_unb64url(jwk["e"]), "big")
        public_key = rsa.RSAPublicNumbers(e, n).public_key()
        try:
            public_key.verify(
                sig, signing, padding.PKCS1v15(), hashes.SHA256()
            )
        except Exception:
            raise ValueError("id_token RS256 signature mismatch")


def claims_to_username(claims: Dict[str, Any]) -> str:
    return str(
        claims.get("preferred_username")
        or claims.get("email")
        or claims.get("sub")
        or ""
    )
