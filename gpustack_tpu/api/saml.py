"""SAML 2.0 service provider: SP-initiated redirect login + POST ACS.

Reference parity: routes/auth.py SAML flow (python3-saml there). Here the
SP is self-contained on lxml + cryptography:

- ``authn_request_url`` — AuthnRequest via the HTTP-Redirect binding
  (deflate → b64 → query param).
- ``verify_response`` — full XML-DSig check of the POSTed SAMLResponse:
  exclusive-c14n SignedInfo, enveloped-signature + exclusive-c14n
  reference digest, RSA-SHA256 (SHA-1 rejected), signing cert PINNED
  from server config (KeyInfo in the message is never trusted), then
  Conditions window + audience restriction.

XML parsing is hardened: entity resolution and network access disabled
(XXE), and the signed-reference lookup only honors the assertion/response
elements' own IDs (no id-attribute spoofing via unsigned wrappers).
"""

from __future__ import annotations

import base64
import datetime
import secrets
import urllib.parse
import zlib
from typing import Any, Dict

from lxml import etree

NSMAP = {
    "samlp": "urn:oasis:names:tc:SAML:2.0:protocol",
    "saml": "urn:oasis:names:tc:SAML:2.0:assertion",
    "ds": "http://www.w3.org/2000/09/xmldsig#",
}
RSA_SHA256 = "http://www.w3.org/2001/04/xmldsig-more#rsa-sha256"
SHA256 = "http://www.w3.org/2001/04/xmlenc#sha256"
ENVELOPED = "http://www.w3.org/2000/09/xmldsig#enveloped-signature"
EXC_C14N = "http://www.w3.org/2001/10/xml-exc-c14n#"

_PARSER = etree.XMLParser(
    resolve_entities=False, no_network=True, remove_comments=False,
    huge_tree=False,
)


def _utcnow() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def _parse_saml_time(s: str) -> datetime.datetime:
    s = s.strip()
    if s.endswith("Z"):
        s = s[:-1] + "+00:00"
    return datetime.datetime.fromisoformat(s)


class SAMLError(ValueError):
    pass


class SAMLProvider:
    def __init__(
        self,
        idp_sso_url: str,
        idp_cert_pem: str,
        sp_entity_id: str,
        clock_skew_s: float = 90.0,
    ) -> None:
        self.idp_sso_url = idp_sso_url
        self.sp_entity_id = sp_entity_id
        self.clock_skew = datetime.timedelta(seconds=clock_skew_s)
        self._public_key = self._load_cert(idp_cert_pem)
        # one-time-use ledger: assertion IDs consumed within their
        # validity window — a captured signed response must not mint a
        # second session (replay defense alongside InResponseTo)
        self._seen_assertions: Dict[str, float] = {}

    @staticmethod
    def _load_cert(pem: str):
        from cryptography import x509
        from cryptography.hazmat.primitives.asymmetric import rsa

        text = pem.strip()
        if not text.startswith("-----"):
            with open(text) as f:
                text = f.read()
        cert = x509.load_pem_x509_certificate(text.encode())
        key = cert.public_key()
        if not isinstance(key, rsa.RSAPublicKey):
            raise SAMLError("IdP certificate must carry an RSA key")
        return key

    # -- AuthnRequest (HTTP-Redirect binding) -----------------------------

    def authn_request_url(
        self, acs_url: str, relay_state: str
    ) -> "tuple[str, str]":
        """Returns (redirect_url, request_id). The caller must remember
        the request id (browser-bound cookie) and pass it to
        ``verify_response`` — the assertion's InResponseTo has to match,
        or a response captured from another login replays."""
        req_id = "_" + secrets.token_hex(16)
        issue_instant = _utcnow().strftime("%Y-%m-%dT%H:%M:%SZ")
        xml = (
            f'<samlp:AuthnRequest xmlns:samlp="{NSMAP["samlp"]}" '
            f'xmlns:saml="{NSMAP["saml"]}" ID="{req_id}" Version="2.0" '
            f'IssueInstant="{issue_instant}" '
            f'ProtocolBinding="urn:oasis:names:tc:SAML:2.0:bindings:'
            f'HTTP-POST" '
            f'AssertionConsumerServiceURL="{acs_url}">'
            f"<saml:Issuer>{self.sp_entity_id}</saml:Issuer>"
            f"</samlp:AuthnRequest>"
        )
        deflated = zlib.compress(xml.encode())[2:-4]  # raw DEFLATE
        query = urllib.parse.urlencode(
            {
                "SAMLRequest": base64.b64encode(deflated).decode(),
                "RelayState": relay_state,
            }
        )
        sep = "&" if "?" in self.idp_sso_url else "?"
        return f"{self.idp_sso_url}{sep}{query}", req_id

    # -- Response verification (HTTP-POST binding) ------------------------

    def verify_response(
        self,
        saml_response_b64: str,
        request_id: str = "",
        acs_url: str = "",
    ) -> Dict[str, Any]:
        """Validate the POSTed SAMLResponse; returns
        {"name_id": ..., "attributes": {...}}.

        ``request_id``: the AuthnRequest ID this browser initiated —
        the response's InResponseTo must match (replay/mix-up defense).
        ``acs_url``: checked against SubjectConfirmationData Recipient
        when the IdP includes one.
        """
        try:
            raw = base64.b64decode(saml_response_b64, validate=True)
        except Exception:
            raise SAMLError("SAMLResponse is not valid base64")
        try:
            root = etree.fromstring(raw, parser=_PARSER)
        except etree.XMLSyntaxError as e:
            raise SAMLError(f"malformed XML: {e}")

        status = root.find(
            ".//samlp:StatusCode", NSMAP
        )
        if status is None or not status.get("Value", "").endswith(
            ":Success"
        ):
            raise SAMLError(
                "IdP status "
                f"{status.get('Value') if status is not None else 'absent'}"
            )

        assertion = root.find("saml:Assertion", NSMAP)
        if assertion is None:
            raise SAMLError(
                "no bare Assertion (encrypted assertions unsupported)"
            )

        # signature may envelop the Response or the Assertion; at least
        # one must verify, and it must cover the element we consume
        verified = False
        for scope in (root, assertion):
            sig = scope.find("ds:Signature", NSMAP)
            if sig is not None:
                self._verify_signature(scope, sig)
                verified = True
                break
        if not verified:
            raise SAMLError("response carries no signature")

        self._check_conditions(assertion)
        self._check_subject_confirmation(
            assertion, request_id, acs_url
        )
        if request_id:
            # Precedence matters: when the signature envelops only the
            # Assertion, the Response root's InResponseTo is UNSIGNED —
            # an attacker could rewrite it to their own request id. The
            # SubjectConfirmationData inside the signed assertion wins;
            # the root attribute is only a fallback for IdPs that omit
            # it there.
            scd = assertion.find(
                "saml:Subject/saml:SubjectConfirmation/"
                "saml:SubjectConfirmationData", NSMAP,
            )
            irt = ""
            if scd is not None:
                irt = scd.get("InResponseTo", "")
            if not irt:
                irt = assertion.get("InResponseTo", "") or root.get(
                    "InResponseTo", ""
                )
            if irt != request_id:
                raise SAMLError(
                    "InResponseTo does not match this browser's "
                    "AuthnRequest"
                )
        self._consume_assertion_id(assertion)

        name_id = assertion.findtext(
            "saml:Subject/saml:NameID", default="", namespaces=NSMAP
        ).strip()
        attributes: Dict[str, Any] = {}
        for attr in assertion.findall(
            "saml:AttributeStatement/saml:Attribute", NSMAP
        ):
            values = [
                (v.text or "").strip()
                for v in attr.findall("saml:AttributeValue", NSMAP)
            ]
            name = attr.get("Name", "")
            if name:
                attributes[name] = (
                    values[0] if len(values) == 1 else values
                )
        if not name_id and not attributes:
            raise SAMLError("assertion carries no identity")
        return {"name_id": name_id, "attributes": attributes}

    # -- XML-DSig ----------------------------------------------------------

    def _verify_signature(self, scope, sig) -> None:
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding

        signed_info = sig.find("ds:SignedInfo", NSMAP)
        if signed_info is None:
            raise SAMLError("signature missing SignedInfo")
        sig_method = signed_info.find(
            "ds:SignatureMethod", NSMAP
        )
        if sig_method is None or sig_method.get(
            "Algorithm"
        ) != RSA_SHA256:
            raise SAMLError(
                "unsupported signature algorithm (only RSA-SHA256)"
            )
        ref = signed_info.find("ds:Reference", NSMAP)
        if ref is None:
            raise SAMLError("signature missing Reference")
        uri = ref.get("URI", "")
        if not uri.startswith("#"):
            raise SAMLError("only same-document references supported")
        if uri[1:] != scope.get("ID", ""):
            # the signature must cover the element it envelops — a
            # reference to some other id would let an attacker wrap a
            # signed assertion beside an unsigned one
            raise SAMLError("signature reference does not cover scope")
        digest_method = ref.find("ds:DigestMethod", NSMAP)
        if digest_method is None or digest_method.get(
            "Algorithm"
        ) != SHA256:
            raise SAMLError("unsupported digest algorithm (only SHA-256)")
        transforms = [
            t.get("Algorithm")
            for t in ref.findall("ds:Transforms/ds:Transform", NSMAP)
        ]
        if not set(transforms) <= {ENVELOPED, EXC_C14N}:
            raise SAMLError(f"unsupported transforms {transforms}")

        # reference digest: element minus its enveloped Signature,
        # exclusive c14n
        import copy

        scope_copy = copy.deepcopy(scope)
        sig_copy = scope_copy.find("ds:Signature", NSMAP)
        if sig_copy is not None:
            scope_copy.remove(sig_copy)
        digest_input = etree.tostring(
            scope_copy, method="c14n", exclusive=True, with_comments=False
        )
        import hashlib

        digest = hashlib.sha256(digest_input).digest()
        want = base64.b64decode(
            ref.findtext("ds:DigestValue", default="", namespaces=NSMAP)
        )
        if digest != want:
            raise SAMLError("reference digest mismatch")

        # SignedInfo signature
        si_c14n = etree.tostring(
            signed_info, method="c14n", exclusive=True, with_comments=False
        )
        sig_value = base64.b64decode(
            sig.findtext(
                "ds:SignatureValue", default="", namespaces=NSMAP
            )
        )
        try:
            self._public_key.verify(
                sig_value, si_c14n, padding.PKCS1v15(), hashes.SHA256()
            )
        except InvalidSignature:
            raise SAMLError("signature verification failed")

    @staticmethod
    def _parse_time_or_raise(s: str) -> datetime.datetime:
        # parse-only try scope: SAMLError subclasses ValueError, so the
        # validity checks themselves must sit OUTSIDE any
        # except-ValueError, or "assertion expired" gets re-wrapped as a
        # misleading "bad timestamp" error
        try:
            t = _parse_saml_time(s)
        except ValueError as e:
            raise SAMLError(f"bad condition timestamp {s!r}: {e}")
        if t.tzinfo is None:
            # SAML timestamps are UTC; a missing designator must not
            # blow up the aware-vs-naive comparison
            t = t.replace(tzinfo=datetime.timezone.utc)
        return t

    def _check_conditions(self, assertion) -> None:
        cond = assertion.find("saml:Conditions", NSMAP)
        now = _utcnow()
        if cond is not None:
            nb = cond.get("NotBefore")
            na = cond.get("NotOnOrAfter")
            if nb and now + self.clock_skew < self._parse_time_or_raise(
                nb
            ):
                raise SAMLError("assertion not yet valid")
            if na and now - self.clock_skew >= self._parse_time_or_raise(
                na
            ):
                raise SAMLError("assertion expired")
            audiences = [
                (a.text or "").strip()
                for a in cond.findall(
                    "saml:AudienceRestriction/saml:Audience", NSMAP
                )
            ]
            if audiences and self.sp_entity_id not in audiences:
                raise SAMLError("assertion audience mismatch")

    def _check_subject_confirmation(
        self, assertion, request_id: str, acs_url: str
    ) -> None:
        scd = assertion.find(
            "saml:Subject/saml:SubjectConfirmation/"
            "saml:SubjectConfirmationData", NSMAP,
        )
        if scd is None:
            return
        now = _utcnow()
        na = scd.get("NotOnOrAfter")
        if na and now - self.clock_skew >= self._parse_time_or_raise(na):
            raise SAMLError("subject confirmation expired")
        recipient = scd.get("Recipient", "")
        if acs_url and recipient and recipient != acs_url:
            raise SAMLError("subject confirmation recipient mismatch")

    def _consume_assertion_id(self, assertion) -> None:
        import time as _time

        now = _time.monotonic()
        # prune expired entries (window: validity + skew, capped 1h)
        for aid, exp in list(self._seen_assertions.items()):
            if exp < now:
                del self._seen_assertions[aid]
        aid = assertion.get("ID", "")
        if not aid:
            raise SAMLError("assertion has no ID")
        if aid in self._seen_assertions:
            raise SAMLError("assertion already consumed (replay)")
        self._seen_assertions[aid] = now + 3600.0


def claims_to_username(result: Dict[str, Any]) -> str:
    """NameID first; common email/uid attributes as fallback."""
    if result.get("name_id"):
        return str(result["name_id"])
    attrs = result.get("attributes", {})
    for key in (
        "email", "mail", "uid",
        "urn:oid:0.9.2342.19200300.100.1.3",   # mail
        "urn:oid:0.9.2342.19200300.100.1.1",   # uid
    ):
        v = attrs.get(key)
        if v:
            return v if isinstance(v, str) else v[0]
    return ""
