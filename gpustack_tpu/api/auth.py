"""Authentication: password hashing, JWT (stdlib HMAC), API keys.

Reference parity (gpustack/api/auth.py): JWT cookie/bearer sessions, API
keys of the form ``<prefix>_<access>_<secret>`` where only a hash of the
secret is stored (gpustack/security.py), worker/system principals for the
agent, scopes (management vs inference).

No PyJWT in the image — JWTs are HS256 via stdlib hmac/hashlib, which is
all the server ever issues or accepts.
"""

from __future__ import annotations

import base64
import dataclasses
import hashlib
import hmac
import json
import secrets
import time
from typing import Any, Dict, Optional, Tuple

from gpustack_tpu.schemas.users import API_KEY_PREFIX, ApiKey, User

JWT_TTL_SECONDS = 12 * 3600


# ---------------------------------------------------------------------------
# Password hashing (scrypt, stdlib)
# ---------------------------------------------------------------------------


def hash_password(password: str) -> str:
    salt = secrets.token_bytes(16)
    digest = hashlib.scrypt(
        password.encode(), salt=salt, n=2**14, r=8, p=1
    )
    return f"scrypt${salt.hex()}${digest.hex()}"


def verify_password(password: str, stored: str) -> bool:
    try:
        algo, salt_hex, digest_hex = stored.split("$")
        assert algo == "scrypt"
        digest = hashlib.scrypt(
            password.encode(), salt=bytes.fromhex(salt_hex), n=2**14, r=8, p=1
        )
        return hmac.compare_digest(digest.hex(), digest_hex)
    except Exception:
        return False


# ---------------------------------------------------------------------------
# JWT (HS256)
# ---------------------------------------------------------------------------


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def jwt_encode(payload: Dict[str, Any], secret: str) -> str:
    header = _b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    body = _b64(json.dumps(payload).encode())
    signing = f"{header}.{body}".encode()
    sig = _b64(hmac.new(secret.encode(), signing, hashlib.sha256).digest())
    return f"{header}.{body}.{sig}"


def jwt_decode(token: str, secret: str) -> Optional[Dict[str, Any]]:
    try:
        header, body, sig = token.split(".")
        signing = f"{header}.{body}".encode()
        expect = _b64(
            hmac.new(secret.encode(), signing, hashlib.sha256).digest()
        )
        if not hmac.compare_digest(expect, sig):
            return None
        payload = json.loads(_unb64(body))
        if payload.get("exp", 0) < time.time():
            return None
        return payload
    except Exception:
        return None


def issue_session_token(user: User, secret: str) -> str:
    return jwt_encode(
        {
            "sub": user.id,
            "username": user.username,
            "admin": user.is_admin,
            "exp": int(time.time()) + JWT_TTL_SECONDS,
        },
        secret,
    )


# ---------------------------------------------------------------------------
# API keys
# ---------------------------------------------------------------------------


def generate_api_key() -> Tuple[str, str, str]:
    """Returns (full_key, access_key, hashed_secret)."""
    access = secrets.token_hex(8)
    secret = secrets.token_urlsafe(24)
    full = f"{API_KEY_PREFIX}_{access}_{secret}"
    return full, access, hash_secret(secret)


def hash_secret(secret: str) -> str:
    return hashlib.sha256(secret.encode()).hexdigest()


def parse_api_key(token: str) -> Optional[Tuple[str, str]]:
    parts = token.split("_", 2)
    if len(parts) != 3 or parts[0] != API_KEY_PREFIX:
        return None
    return parts[1], parts[2]


# ---------------------------------------------------------------------------
# Principals
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Principal:
    """The authenticated caller: a user, a worker, or the system."""

    kind: str = "user"                # user | worker | system
    user: Optional[User] = None
    worker_id: int = 0
    scopes: Tuple[str, ...] = ("management", "inference")
    # the resolved ApiKey record when the bearer was an API key: the
    # tenancy layer (server/tenancy.py) reads its QoS fields per
    # request, so quota/weight updates apply without any cache bust
    api_key: Optional[ApiKey] = None

    @property
    def is_admin(self) -> bool:
        return self.kind == "system" or bool(self.user and self.user.is_admin)

    def has_scope(self, scope: str) -> bool:
        return scope in self.scopes


async def authenticate(
    token: str, jwt_secret: str
) -> Optional[Principal]:
    """Resolve a bearer token: API key, worker token, or session JWT."""
    if not token:
        return None
    if token.startswith(API_KEY_PREFIX + "_"):
        parsed = parse_api_key(token)
        if not parsed:
            return None
        access, secret = parsed
        key = await ApiKey.first(access_key=access)
        if key is None:
            return None
        if not hmac.compare_digest(key.hashed_secret, hash_secret(secret)):
            return None
        if key.expires_at and key.expires_at < time_iso_now():
            return None
        user = await User.get(key.user_id)
        if user is None:
            return None
        return Principal(
            kind="user", user=user, scopes=tuple(key.scopes),
            api_key=key,
        )
    payload = jwt_decode(token, jwt_secret)
    if payload is None:
        return None
    if payload.get("worker"):
        return Principal(
            kind="worker",
            worker_id=int(payload["worker"]),
            scopes=("worker",),
        )
    user = await User.get(int(payload.get("sub", 0)))
    if user is None:
        return None
    return Principal(kind="user", user=user)


# ---------------------------------------------------------------------------
# KV-scoped worker-proxy tokens (disaggregated handoff credentials)
# ---------------------------------------------------------------------------
#
# Engine→engine KV pulls ride the source worker's reverse proxy. The
# pull credential travels in a per-request header through another
# worker and an engine process, so it must NOT be the worker's full
# proxy secret (which authorizes every instance-proxy and control
# route): mint a short-lived token scoped to ONE instance's /kv/export
# instead. HMAC over the worker's proxy secret — the worker verifies
# without any server round-trip, and rotating the proxy secret (every
# re-registration) invalidates outstanding KV tokens with it.

KV_TOKEN_PREFIX = "gkv1"


def mint_kv_token(
    proxy_secret: str, instance_id: int, ttl: float,
    now: Optional[float] = None,
) -> str:
    expires = int((time.time() if now is None else now) + max(1.0, ttl))
    payload = f"{KV_TOKEN_PREFIX}:{int(instance_id)}:{expires}"
    sig = hmac.new(
        proxy_secret.encode(), payload.encode(), hashlib.sha256
    ).hexdigest()
    return f"{payload}:{sig}"


def verify_kv_token(
    token: str, proxy_secret: str, instance_id: int,
    now: Optional[float] = None,
) -> bool:
    """True iff ``token`` is an unexpired KV token for THIS instance,
    signed with THIS worker's proxy secret."""
    parts = token.split(":")
    if len(parts) != 4 or parts[0] != KV_TOKEN_PREFIX:
        return False
    prefix, iid_s, expires_s, sig = parts
    payload = f"{prefix}:{iid_s}:{expires_s}"
    expect = hmac.new(
        proxy_secret.encode(), payload.encode(), hashlib.sha256
    ).hexdigest()
    if not hmac.compare_digest(expect, sig):
        return False
    try:
        iid, expires = int(iid_s), int(expires_s)
    except ValueError:
        return False
    if iid != int(instance_id):
        return False
    return (time.time() if now is None else now) < expires


def issue_worker_token(worker_id: int, secret: str) -> str:
    return jwt_encode(
        {
            "worker": worker_id,
            # worker tokens are long-lived; rotation happens via
            # re-registration
            "exp": int(time.time()) + 365 * 24 * 3600,
        },
        secret,
    )


def time_iso_now() -> str:
    import datetime

    return datetime.datetime.now(datetime.timezone.utc).isoformat()
