"""Control-plane write combiner: heartbeat/status writes that scale
sub-linearly in workers.

The reference architecture's control plane melts exactly here (SURVEY
§1 state layer): every worker heartbeat and status refresh used to be
its own read-modify-write cycle — a ``Worker.get`` + a whole-document
CAS ``update`` + a bus event — so DB write rate (and watch fan-out)
grew linearly in fleet width. At 1000+ workers that is thousands of
transactions per flush interval for data nobody watches.

:class:`ControlWriteCombiner` replaces that path on EVERY server
(leader and follower — heartbeats land wherever the load balancer
sends them):

- **Debounced coalescing**: heartbeat and status refreshes buffer in
  memory per worker (newest wins) and flush on a fixed cadence
  (``control_flush_interval``). One flush issues at most TWO batched
  statements (one ``executemany`` for liveness-only entries, one for
  status refreshes) inside ONE transaction — DB write rate per second
  is O(flushes), not O(workers).
- **``Record.set_field``-shaped column writes**: the flush targets the
  ``heartbeat_at``/``status`` document fields via the per-dialect
  ``json_set`` helpers, bumps ``updated_at`` (column + document, so
  whole-document CAS saves still conflict instead of silently
  reverting), publishes NO bus event, and appends NO change-log entry
  — liveness is read from the shared DB, never replicated. A guard
  clause (``heartbeat_at`` strictly newer) makes a late flush unable
  to regress a write-through state transition's fresher timestamp.
- **Deadline bound**: every buffered status write lands within
  ``control_write_deadline`` seconds of being offered, degraded mode
  included.
- **Overload degradation** (the ladder): when the buffered queue or
  the last flush's latency crosses its watermark
  (``control_queue_watermark`` / ``control_latency_watermark``),
  ``write_pressure`` reaches 1.0 and the combiner degrades to
  **liveness-only** — heartbeat timestamps still land (tiny, one
  batched statement) while status-document writes defer until
  pressure clears or their deadline expires. Freshness is always
  tracked in memory (:meth:`freshness_for`), and the WorkerSyncer
  consults THIS server's map, so a heartbeat the leader received is
  never read as stale just because the DB is slow.
  ``gpustack_control_write_pressure`` exports the ladder's position.
  Scope honesty: the freshness shield is per-server. In HA, a
  heartbeat routed to a FOLLOWER reaches the leader's syncer only via
  the follower's flushed liveness row — which keeps landing every
  flush interval even degraded, so the exposure narrows to a DB that
  accepts reads while rejecting writes cluster-wide for most of the
  staleness budget (recorded residual: a peer-freshness query would
  close it).
- **Shared drain contract** (orm/db.py :class:`DatabaseClosedError`):
  a write offered behind shutdown — or a final drain racing a closed
  Database — fails LOUDLY with the same typed error the Database's
  own queue uses; nothing is ever silently dropped.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Tuple

from gpustack_tpu.orm.db import DatabaseClosedError
from gpustack_tpu.server.collectors import PeriodicTask
from gpustack_tpu.utils.profiling import timed


# concurrency contract (checked by `python -m gpustack_tpu.analysis`):
# the combiner is event-loop-only — no locks, no threads. The queues
# are single-thread-owned by the declared method set (guarded-by rule,
# owner-list form), and LOOP_OWNED marks the seam for the
# thread-boundary rule: a worker thread must never reach into these.
_QUEUE_OWNERS = (
    "offer_heartbeat", "offer_status", "queue_depth", "_requeue",
    "flush",
)

GUARDED_BY = {
    "_hb": _QUEUE_OWNERS,
    "_status": _QUEUE_OWNERS,
    "_freshness": ("_note_fresh", "freshness_for", "flush", "snapshot"),
}

LOOP_OWNED = ("_hb", "_status", "_freshness")


class ControlWriteCombiner(PeriodicTask):
    task_name = "control-write-combiner"

    def __init__(
        self,
        flush_interval: float = 2.0,
        deadline: float = 10.0,
        queue_watermark: int = 4096,
        latency_watermark: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        super().__init__(max(0.05, flush_interval))
        self.deadline = max(flush_interval, deadline)
        self.queue_watermark = max(1, int(queue_watermark))
        self.latency_watermark = max(0.01, float(latency_watermark))
        self._clock = clock
        self.closed = False
        # worker_id -> newest heartbeat iso awaiting flush
        self._hb: Dict[int, str] = {}
        # worker_id -> (status json-able doc, heartbeat iso, offered_at)
        self._status: Dict[int, Tuple[dict, str, float]] = {}
        # worker_id -> newest heartbeat iso EVER offered: the in-memory
        # liveness truth the WorkerSyncer consults so degraded-mode
        # deferral can never park a healthy worker
        self._freshness: Dict[int, str] = {}
        self._last_flush_s = 0.0
        self.coalesced: Dict[str, int] = {"heartbeat": 0, "status": 0}
        self.flushed: Dict[str, int] = {"heartbeat": 0, "status": 0}
        self.deferred_total = 0
        self.degraded_flushes = 0

    @classmethod
    def from_config(cls, cfg) -> "ControlWriteCombiner":
        # the flush cadence must comfortably outpace the syncer's
        # staleness budget (4.5 × heartbeat_interval): a combiner that
        # flushes slower than workers heartbeat would itself make
        # fresh heartbeats read stale from the DB
        flush = min(
            float(getattr(cfg, "control_flush_interval", 2.0)),
            float(getattr(cfg, "heartbeat_interval", 10.0)),
        )
        return cls(
            flush_interval=flush,
            deadline=float(
                getattr(cfg, "control_write_deadline", 10.0)
            ),
            queue_watermark=int(
                getattr(cfg, "control_queue_watermark", 4096)
            ),
            latency_watermark=float(
                getattr(cfg, "control_latency_watermark", 1.0)
            ),
        )

    # ---- offer side (request handlers; sync + cheap) -----------------

    def _check_open(self) -> None:
        if self.closed:
            # the shared drain contract: work offered behind shutdown
            # fails loudly to its caller, exactly like a write queued
            # behind Database.close()
            raise DatabaseClosedError("control write combiner")

    def offer_heartbeat(self, worker_id: int, heartbeat_at: str) -> None:
        """Buffer one liveness write (newest wins per worker)."""
        self._check_open()
        worker_id = int(worker_id)
        pending = self._status.get(worker_id)
        if pending is not None:
            # a status write is already queued for this worker and will
            # carry liveness: advance ITS timestamp instead of queueing
            # a plain heartbeat the flush would discard as subsumed —
            # the DB must land the NEWEST liveness either way
            doc, hb, offered = pending
            if heartbeat_at > hb:
                self._status[worker_id] = (
                    doc, heartbeat_at, offered
                )
            self.coalesced["heartbeat"] += 1
            self._note_fresh(worker_id, heartbeat_at)
            return
        if worker_id in self._hb:
            self.coalesced["heartbeat"] += 1
        if heartbeat_at > self._hb.get(worker_id, ""):
            self._hb[worker_id] = heartbeat_at
        self._note_fresh(worker_id, heartbeat_at)

    def offer_status(
        self, worker_id: int, status_doc: dict, heartbeat_at: str
    ) -> None:
        """Buffer one status refresh (carries liveness too)."""
        self._check_open()
        worker_id = int(worker_id)
        if worker_id in self._status:
            self.coalesced["status"] += 1
            offered = self._status[worker_id][2]
        else:
            offered = self._clock()
        self._status[worker_id] = (status_doc, heartbeat_at, offered)
        # a pending plain heartbeat is subsumed: the status write lands
        # heartbeat_at as well
        self._hb.pop(worker_id, None)
        self._note_fresh(worker_id, heartbeat_at)

    def _note_fresh(self, worker_id: int, heartbeat_at: str) -> None:
        prior = self._freshness.get(worker_id, "")
        if heartbeat_at > prior:
            self._freshness[worker_id] = heartbeat_at

    def freshness_for(self, worker_id: int) -> str:
        """Newest heartbeat this SERVER has seen for the worker —
        in-memory, ahead of (or equal to) whatever the DB holds."""
        return self._freshness.get(int(worker_id), "")

    # ---- pressure ladder ---------------------------------------------

    def queue_depth(self) -> int:
        return len(self._hb) + len(self._status)

    def write_pressure(self) -> float:
        """0 = idle; >= 1.0 = degraded (liveness-only flushes)."""
        return max(
            self.queue_depth() / self.queue_watermark,
            self._last_flush_s / self.latency_watermark,
        )

    @property
    def degraded(self) -> bool:
        return self.write_pressure() >= 1.0

    # ---- flush side ---------------------------------------------------

    def _requeue(
        self,
        statuses: Dict[int, Tuple[dict, str, float]],
        heartbeats: Dict[int, str],
    ) -> None:
        """Put a swapped-out (but unlanded) batch back — never
        clobbering anything NEWER offered while the flush was in
        flight. One home for both failure paths (unbound mount,
        failed DB run) so the newest-wins rules can't diverge."""
        for wid, entry in statuses.items():
            self._status.setdefault(wid, entry)
        for wid, hb in heartbeats.items():
            if wid not in self._status and hb > self._hb.get(wid, ""):
                self._hb[wid] = hb

    async def tick(self) -> None:
        await self.flush()

    @timed(threshold_s=2.0, name="write_combiner.flush")
    async def flush(self, force: bool = False) -> Tuple[int, int]:
        """Flush buffered writes; returns (heartbeats, statuses)
        landed. Degraded mode defers status documents that are still
        inside their deadline; liveness always lands. ``force`` skips
        the degradation deferral (the shutdown drain)."""
        from gpustack_tpu.orm.record import Record, _now

        now_mono = self._clock()
        degraded = self.degraded and not force
        statuses, self._status = self._status, {}
        if degraded and statuses:
            self.degraded_flushes += 1
            keep: Dict[int, Tuple[dict, str, float]] = {}
            flush_now: Dict[int, Tuple[dict, str, float]] = {}
            for wid, entry in statuses.items():
                # the deadline bound survives degradation: an entry
                # due now lands even under pressure
                if now_mono - entry[2] >= self.deadline - self.interval:
                    flush_now[wid] = entry
                else:
                    keep[wid] = entry
            self.deferred_total += len(keep)
            for wid, entry in keep.items():
                self._status.setdefault(wid, entry)
                # its liveness half still lands this flush
                self._hb.setdefault(wid, entry[1])
            statuses = flush_now
        heartbeats, self._hb = self._hb, {}
        # a status row that also re-buffered a liveness write above
        # must not double-write
        for wid in statuses:
            heartbeats.pop(wid, None)
        if not heartbeats and not statuses:
            self._last_flush_s = 0.0
            return (0, 0)

        try:
            db = Record.db()
        except AssertionError:
            # unbound test mount: drop is impossible to act on — put
            # the work back and report pressure honestly
            self._requeue(statuses, heartbeats)
            return (0, 0)
        from gpustack_tpu.schemas import Worker

        table = Worker.__kind__
        now = _now()
        import json as _json

        now_json = _json.dumps(now)
        # <=, not <: a worker whose liveness already landed at this
        # exact timestamp (a deferred status's heartbeat half flushed
        # one interval earlier) must still take its status document;
        # only a STRICTLY newer write-through timestamp blocks us
        hb_guard = (
            f"COALESCE({db.json_text('heartbeat_at')}, '') <= ?"
        )
        # liveness-only writer: nested per-dialect setters target the
        # heartbeat_at field and the document's updated_at; binds in
        # textual order: inner value first, then the timestamp, then
        # the column, id, guard
        hb_setter = db.json_set(
            "updated_at", col=db.json_set("heartbeat_at")
        )
        hb_sql = (
            f"UPDATE {table} SET data = {hb_setter}, updated_at = ? "
            f"WHERE id = ? AND {hb_guard}"
        )
        hb_rows: List[Tuple] = [
            (_json.dumps(hb), now_json, now, wid, hb)
            for wid, hb in heartbeats.items()
        ]
        st_setter = db.json_set(
            "updated_at",
            col=db.json_set("heartbeat_at", col=db.json_set("status")),
        )
        st_sql = (
            f"UPDATE {table} SET data = {st_setter}, updated_at = ? "
            f"WHERE id = ? AND {hb_guard}"
        )
        st_rows: List[Tuple] = [
            (
                _json.dumps(status_doc), _json.dumps(hb), now_json,
                now, wid, hb,
            )
            for wid, (status_doc, hb, _offered) in statuses.items()
        ]

        def go(conn):
            try:
                if hb_rows:
                    conn.executemany(hb_sql, hb_rows)
                if st_rows:
                    conn.executemany(st_sql, st_rows)
                conn.commit()
            except BaseException:
                conn.rollback()
                raise
            return (len(hb_rows), len(st_rows))

        t0 = time.monotonic()
        try:
            counts = await db.run(go)
        except BaseException:
            # ANY failed flush (a closed DB's typed drain error, lock
            # contention, disk I/O) re-buffers its batch so nothing is
            # silently dropped and deadlines keep counting from the
            # original offer; the error itself propagates loudly
            # (run-loop log / drain() caller)
            self._requeue(statuses, heartbeats)
            raise
        self._last_flush_s = time.monotonic() - t0
        self.flushed["heartbeat"] += counts[0]
        self.flushed["status"] += counts[1]
        # the in-memory freshness map tracks every worker ever seen:
        # keep it bounded against churned fleets (dead workers' entries
        # serve nothing once the syncer has parked them)
        cap = 4 * self.queue_watermark
        if len(self._freshness) > cap:
            doomed = sorted(
                self._freshness, key=self._freshness.get
            )[: len(self._freshness) - cap]
            for wid in doomed:
                self._freshness.pop(wid, None)
        return counts

    async def drain(self) -> None:
        """Final flush at shutdown. Everything still buffered either
        lands now or surfaces as :class:`DatabaseClosedError` — the
        one loud way a queued write behind shutdown may end."""
        self.closed = True
        self.stop()
        await self.flush(force=True)
        if self.queue_depth():
            raise DatabaseClosedError(
                f"control write combiner ({self.queue_depth()} "
                "buffered writes undrained)"
            )

    # ---- observability -------------------------------------------------

    def metrics_lines(self) -> List[str]:
        from gpustack_tpu.observability.metrics import METRIC_FAMILIES

        lines = [
            "# TYPE gpustack_control_write_pressure "
            f"{METRIC_FAMILIES['gpustack_control_write_pressure']}",
            f"gpustack_control_write_pressure "
            f"{self.write_pressure():.6f}",
            "# TYPE gpustack_control_coalesced_writes_total "
            f"{METRIC_FAMILIES['gpustack_control_coalesced_writes_total']}",
        ]
        for kind in ("heartbeat", "status"):
            lines.append(
                "gpustack_control_coalesced_writes_total"
                f'{{kind="{kind}"}} {self.coalesced[kind]}'
            )
        lines += [
            "# TYPE gpustack_control_flushed_writes_total "
            f"{METRIC_FAMILIES['gpustack_control_flushed_writes_total']}",
        ]
        for kind in ("heartbeat", "status"):
            lines.append(
                "gpustack_control_flushed_writes_total"
                f'{{kind="{kind}"}} {self.flushed[kind]}'
            )
        lines += [
            "# TYPE gpustack_control_deferred_writes_total "
            f"{METRIC_FAMILIES['gpustack_control_deferred_writes_total']}",
            "gpustack_control_deferred_writes_total "
            f"{self.deferred_total}",
        ]
        return lines

    def snapshot(self) -> Dict:
        """Triage view (debug surfaces / tests)."""
        return {
            "queue_depth": self.queue_depth(),
            "pressure": round(self.write_pressure(), 6),
            "degraded": self.degraded,
            "coalesced": dict(self.coalesced),
            "flushed": dict(self.flushed),
            "deferred_total": self.deferred_total,
            "degraded_flushes": self.degraded_flushes,
            "last_flush_s": round(self._last_flush_s, 6),
            "tracked_workers": len(self._freshness),
        }
