"""Periodic update check (reference server/update_check.py).

Disabled by default in zero-egress deployments: set
``GPUSTACK_TPU_UPDATE_URL`` to a JSON endpoint returning
``{"latest": "x.y.z"}``. Failures only log — an update check must never
affect serving.
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Optional

import aiohttp

from gpustack_tpu import __version__

logger = logging.getLogger(__name__)


def _newer(latest: str, current: str) -> bool:
    def parse(v: str):
        parts = v.strip().lstrip("v").split(".")
        if not parts or not all(p.isdigit() for p in parts):
            raise ValueError(f"non-numeric version {v!r}")
        nums = [int(p) for p in parts]
        # zero-pad so '1.2' == '1.2.0' (silent truncation would report
        # phantom updates forever)
        return tuple(nums + [0] * (3 - len(nums)))

    try:
        return parse(latest) > parse(current)
    except ValueError:
        return False


class UpdateChecker:
    def __init__(self, interval: float = 24 * 3600.0):
        self.url = os.environ.get("GPUSTACK_TPU_UPDATE_URL", "")
        self.interval = interval
        self.latest: str = ""
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self.url and self._task is None:
            self._task = asyncio.create_task(
                self._loop(), name="update-check"
            )

    def stop(self) -> None:
        if self._task:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            try:
                await self.check_once()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                logger.debug("update check failed: %s", e)
            await asyncio.sleep(self.interval)

    async def check_once(self) -> Optional[str]:
        async with aiohttp.ClientSession() as session:
            async with session.get(
                self.url, timeout=aiohttp.ClientTimeout(total=10)
            ) as resp:
                data = await resp.json()
        latest = str(data.get("latest", ""))
        if latest and _newer(latest, __version__):
            self.latest = latest
            logger.info(
                "a newer gpustack_tpu release is available: %s "
                "(running %s)", latest, __version__,
            )
        return latest or None
