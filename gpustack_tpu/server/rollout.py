"""Zero-downtime rollouts: health-gated canaries + automatic rollback.

The reference rolls replicas through drain-based updates (reference
scheduler/scheduler.py:261-298 reschedule shape); this controller goes
further and makes a serving-spec change a *versioned, judged* operation:

1. the model-update API hook bumps ``Model.generation`` for any
   ``ROLLOUT_FIELDS`` change and archives the previous spec as a
   ``ModelRevision``;
2. this leader-only reconcile loop notices instances tagged with an
   older generation and opens a ``Rollout`` plan: bring up ``surge``
   new-generation replicas (capacity never dips below spec), wait for
   each to reach RUNNING within ``rollout_running_deadline``, then hold
   an observation window;
3. health gates run every tick: new-generation replica health
   (ERROR/UNREACHABLE/deadline), any PR 8 SLO burn FIRING on the model,
   and delta gates against the request histogram — the canary window's
   error rate and TTFT p95 vs the pre-rollout baseline window (pure
   old-generation traffic);
4. gates pass → the matched batch of old replicas drains through the
   existing DRAINING path (PR 2) and the worker retires them; repeat
   until the old generation is gone;
5. ANY gate failure (or ``POST /v2/models/{id}/rollback``) triggers
   automatic rollback: the archived old spec is restored onto the
   Model row (generation bumped again so nothing re-rolls), surviving
   old-generation instances are re-tagged to the restored generation,
   the new generation is drained/deleted, and the incident lands in
   the PR 8 ring with a ``rollout`` evidence tag.

During a canary-stage rollback (no batch promoted yet — the seeded
chaos e2e's acceptance case) the old generation is never touched, so
it never drops below spec. ``ModelController._sync_replicas`` defers
replica-count enforcement to this controller while a rollout is
active; the autoscaler likewise refuses to act mid-rollout.
"""

from __future__ import annotations

import asyncio
import contextlib
import datetime
import logging
import time
from typing import Any, Dict, List, Optional, Tuple

from gpustack_tpu.config import Config
from gpustack_tpu.observability.metrics import (
    METRIC_FAMILIES,
    escape_label_value,
    get_registry,
)
from gpustack_tpu.schemas import (
    Model,
    ModelInstance,
    ModelInstanceState,
    ModelRevision,
    Rollout,
    RolloutState,
)
from gpustack_tpu.schemas.models import ROLLOUT_FIELDS
from gpustack_tpu.schemas.rollouts import ACTIVE_ROLLOUT_STATES
from gpustack_tpu.server.collectors import DirtyTrackedTask
from gpustack_tpu.server.controllers import create_pending_instances
from gpustack_tpu.utils.profiling import timed

logger = logging.getLogger(__name__)

# gpustack_rollout_state gauge encoding (docs/OBSERVABILITY.md)
ROLLOUT_STATE_CODES = {
    RolloutState.COMPLETED: 0,
    RolloutState.SURGING: 1,
    RolloutState.OBSERVING: 2,
    RolloutState.PROMOTING: 3,
    RolloutState.ROLLING_BACK: 4,
    RolloutState.ROLLED_BACK: 5,
    RolloutState.FAILED: 6,
}

HISTORY_CAP = 50
# finished plans kept per model (active plans are never pruned)
ROLLOUT_KEEP = 20


# ---------------------------------------------------------------------------
# request-histogram snapshots + delta gates (pure helpers, unit-tested)
# ---------------------------------------------------------------------------


def snapshot_model_requests(model_name: str) -> Dict[str, Any]:
    """JSON-serializable cumulative request counts for one model from
    the server's live ``gpustack_request_duration_seconds`` histogram:
    outcome=ok vs all (phase=total) and the TTFT bucket counts."""
    snap = get_registry("server").histogram(
        "gpustack_request_duration_seconds",
        label_names=("phase", "model", "outcome"),
    ).snapshot()
    ok = total = ttft_count = 0
    ttft: Dict[str, float] = {}
    for (phase, m, _outcome), (cum, _sum, count) in snap.items():
        if m != model_name:
            continue
        if phase == "total":
            total += count
            if _outcome == "ok":
                ok += count
        elif phase == "ttft":
            ttft_count += count
            # cumulative arrays share bucket bounds, so summing them
            # pairwise across outcomes keeps them cumulative
            for ub, c in cum:
                key = "inf" if ub == float("inf") else repr(ub)
                ttft[key] = ttft.get(key, 0) + c
    return {
        "ok": ok, "total": total,
        "ttft": ttft, "ttft_count": ttft_count,
    }


def window_error_rate(
    end: Dict[str, Any], start: Dict[str, Any], min_requests: int
) -> Optional[float]:
    """Error rate over the [start, end) snapshot delta, or None when
    the window saw fewer than ``min_requests`` requests."""
    total = end.get("total", 0) - start.get("total", 0)
    if total < max(1, min_requests):
        return None
    ok = end.get("ok", 0) - start.get("ok", 0)
    return max(0.0, min(1.0, 1.0 - ok / total))


def window_ttft_p95(
    end: Dict[str, Any], start: Dict[str, Any], min_requests: int
) -> Optional[float]:
    """TTFT p95 (seconds) over the snapshot delta via the same
    within-bucket interpolation PromQL's histogram_quantile uses."""
    count = end.get("ttft_count", 0) - start.get("ttft_count", 0)
    if count < max(1, min_requests):
        return None
    s_ttft = start.get("ttft", {})
    cum: List[Tuple[float, float]] = []
    for key, c in end.get("ttft", {}).items():
        ub = float("inf") if key == "inf" else float(key)
        cum.append((ub, c - s_ttft.get(key, 0)))
    cum.sort(key=lambda p: p[0])
    if not cum:
        return None
    rank = 0.95 * count
    prev_ub, prev_cum = 0.0, 0.0
    for ub, c in cum:
        if c >= rank:
            if ub == float("inf"):
                return prev_ub
            if c == prev_cum:
                return ub
            frac = (rank - prev_cum) / (c - prev_cum)
            return prev_ub + (ub - prev_ub) * frac
        prev_ub, prev_cum = ub, c
    return prev_ub


def delta_gate_failure(
    baseline: Dict[str, Any],
    baseline_end: Dict[str, Any],
    canary: Dict[str, Any],
    current: Dict[str, Any],
    cfg: Config,
) -> Optional[str]:
    """Judge the canary window against the pre-rollout baseline window.

    Baseline window = [plan creation, FIRST observation start): pure
    old-generation traffic — frozen there so later batches are not
    judged against a baseline the new generation already contaminated
    (a canary just under the allowed delta per batch would otherwise
    ratchet the baseline up batch over batch). Canary window =
    [current observation start, now). Either window with fewer than
    ``rollout_min_requests`` requests leaves its gate undecided (no
    verdict from noise).
    """
    min_req = cfg.rollout_min_requests
    during_err = window_error_rate(current, canary, min_req)
    base_err = window_error_rate(baseline_end, baseline, min_req)
    if during_err is not None and base_err is not None:
        # BOTH windows must be sampled: an under-sampled baseline is
        # "no verdict", never a perfect 0.0 — a low-traffic model's
        # first transient error must not blacklist its generation
        # (the burn-rate gate still covers absolute error budgets)
        if during_err > base_err + cfg.rollout_max_error_delta:
            return (
                f"error-rate gate: {during_err:.3f} in the canary "
                f"window vs {base_err:.3f} baseline "
                f"(allowed delta {cfg.rollout_max_error_delta})"
            )
    during_p95 = window_ttft_p95(current, canary, min_req)
    base_p95 = window_ttft_p95(baseline_end, baseline, min_req)
    if during_p95 is not None and base_p95 is not None:
        limit = max(base_p95, 1e-3) * cfg.rollout_max_ttft_degradation
        if during_p95 > limit:
            return (
                f"ttft gate: p95 {during_p95 * 1000:.0f}ms in the "
                f"canary window vs {base_p95 * 1000:.0f}ms baseline "
                f"(allowed x{cfg.rollout_max_ttft_degradation})"
            )
    return None


def _created_age(inst: ModelInstance, now: float) -> Optional[float]:
    try:
        created = datetime.datetime.fromisoformat(inst.created_at)
    except ValueError:
        return None
    return now - created.timestamp()


# ---------------------------------------------------------------------------
# controller
# ---------------------------------------------------------------------------


class RolloutController(DirtyTrackedTask):
    dirty_kinds = ("model", "model_instance", "rollout")
    task_name = "rollout-controller"

    def __init__(self, app, cfg: Config):
        super().__init__(max(0.05, cfg.rollout_interval))
        self.app = app
        self.cfg = cfg
        # serializes every plan write: the route (leader path) and the
        # reconcile tick both run in the leader process, and the
        # ROLLING_BACK write lands only AFTER the restore's awaits —
        # unserialized, a concurrent begin_rollback pair could bump
        # the generation twice, and a forward _record could fetch its
        # guard snapshot before a rollback lands yet write after it
        # (follower routes defer via rollback_requested, so
        # leader-local locking is sufficient). Reentrant per task:
        # begin_rollback holds it across its body while its own
        # _record/_finish calls pass straight through.
        self._plan_lock_inner = asyncio.Lock()
        self._plan_lock_task: Optional[asyncio.Task] = None
        self._events = get_registry("server").counter(
            "gpustack_rollout_events_total",
            label_names=("model", "event"),
        )
        # model name -> newest rollout state (metrics render cache —
        # the scrape path never touches the DB)
        self._latest_state: Dict[str, RolloutState] = {}
        self.ticks = 0
        # dirty-set (DirtyTrackedTask): a steady-state pass with
        # nothing dirty AND no active plan skips the per-tick
        # Model/Instance/Rollout table scans entirely — any DB action
        # (ours or anyone's) dirties the set and re-arms the next pass
        self._had_active = True  # conservative until the first pass

    async def tick(self) -> None:
        await self.reconcile_once()

    # ------------------------------------------------------------------

    @timed(threshold_s=5.0, name="rollout.reconcile")
    async def reconcile_once(self, now: Optional[float] = None) -> None:
        """One reconcile pass; ``now`` is injectable so tests drive a
        synthetic clock over real DB state."""
        now = time.time() if now is None else now
        self.ticks += 1
        changed = self._drain_dirty()
        if not changed and not self._had_active:
            # steady-state no-op: nothing we watch was written since
            # last pass AND no plan was mid-flight — time alone cannot
            # progress anything (gates/windows only matter to ACTIVE
            # plans), so skip the table scans
            self.skipped_ticks += 1
            return
        try:
            models = await Model.filter(limit=None)
            instances = await ModelInstance.filter(limit=None)
            rollouts = await Rollout.filter(limit=None)
        except Exception:
            # the drained dirtiness was consumed but nothing acted on
            # it — re-arm or the next tick would skip pending work
            self._rearm_dirty()
            raise
        self._had_active = any(
            r.state in ACTIVE_ROLLOUT_STATES for r in rollouts
        )
        by_model: Dict[int, List[ModelInstance]] = {}
        for inst in instances:
            by_model.setdefault(inst.model_id, []).append(inst)
        ro_by_model: Dict[int, List[Rollout]] = {}
        for r in rollouts:
            ro_by_model.setdefault(r.model_id, []).append(r)

        latest: Dict[str, RolloutState] = {}
        for model in models:
            insts = by_model.get(model.id, [])
            ros = sorted(
                ro_by_model.get(model.id, []), key=lambda r: r.id
            )
            if ros:
                latest[model.name] = ros[-1].state
            active = [
                r for r in ros if r.state in ACTIVE_ROLLOUT_STATES
            ]
            try:
                if active:
                    await self._advance(model, active[-1], insts, now)
                    fresh = await Rollout.get(active[-1].id)
                    if fresh is not None:
                        latest[model.name] = fresh.state
                elif self._needs_rollout(model, insts, ros):
                    rollout = await self._start(model, insts, now)
                    latest[model.name] = rollout.state
            except Exception:
                # one model's broken rollout must not starve the rest;
                # re-arm the dirty-set so the no-op skip can't shelve
                # this model's still-pending work
                self._rearm_dirty()
                logger.exception(
                    "rollout reconcile failed for model %s", model.name
                )
        # a model deleted mid-rollout orphans its active plan — close
        # it so nothing reads as "in flight" forever
        model_ids = {m.id for m in models}
        for r in rollouts:
            if (
                r.state in ACTIVE_ROLLOUT_STATES
                and r.model_id not in model_ids
            ):
                # no `latest` touch: the gauge cache is built from the
                # EXISTING models list above, so the orphan was never
                # added — and popping by name would wrongly drop the
                # sample of a new model that reused the deleted name
                await r.update(
                    state=RolloutState.FAILED,
                    state_message="model deleted mid-rollout",
                )
        # bound the table: finished plans beyond the newest ROLLOUT_KEEP
        # per model are deleted — otherwise every reconcile (and
        # _sync_replicas' rollout-active check) scans a set that grows
        # for the life of the model. A finished plan targeting the
        # model's CURRENT generation is kept regardless: it is what
        # stops _needs_rollout from auto-retrying a failed spec.
        gen_by_model = {m.id: m.generation for m in models}
        for mid, ros in ro_by_model.items():
            done = [
                r for r in sorted(ros, key=lambda r: r.id)
                if r.state not in ACTIVE_ROLLOUT_STATES
                and r.to_generation != gen_by_model.get(mid)
            ]
            for r in done[:-ROLLOUT_KEEP]:
                await r.delete()
        self._latest_state = latest

    # ---- plan lifecycle --------------------------------------------------

    def _needs_rollout(
        self, model: Model, insts: List[ModelInstance], ros: List[Rollout]
    ) -> bool:
        if model.serving_replicas() <= 0 or not insts:
            return False
        if all(i.generation == model.generation for i in insts):
            return False
        # one attempt per target generation: a rolled-back/failed
        # attempt blocks retries until the operator ships a new spec
        # (which bumps the generation) — automatic re-tries of a spec
        # that just failed its canary would flap forever
        return not any(r.to_generation == model.generation for r in ros)

    async def _start(
        self, model: Model, insts: List[ModelInstance], now: float
    ) -> Rollout:
        surge = max(1, model.rollout_surge or self.cfg.rollout_surge)
        evaluator = self.app.get("slo")
        preexisting = (
            list(evaluator.engine.firing_objectives(model.name))
            if evaluator is not None
            else []
        )
        from_gen = max(
            (
                i.generation for i in insts
                if i.generation != model.generation
            ),
            default=max(0, model.generation - 1),
        )
        rollout = await Rollout.create(Rollout(
            model_id=model.id,
            model_name=model.name,
            from_generation=from_gen,
            to_generation=model.generation,
            surge=surge,
            state=RolloutState.SURGING,
            state_message="surging first batch",
            baseline=snapshot_model_requests(model.name),
            preexisting_firing=preexisting,
            history=[{
                "at": now, "event": "started",
                "detail": (
                    f"generation {from_gen} -> {model.generation}, "
                    f"surge {surge}"
                    + (
                        "; already-firing burns excluded from the "
                        f"gate: {'/'.join(preexisting)}"
                        if preexisting else ""
                    )
                ),
            }],
        ))
        self._events.inc(model=model.name, event="started")
        logger.info(
            "rollout %d started: model %s generation %d -> %d",
            rollout.id, model.name, from_gen, model.generation,
        )
        return rollout

    async def _advance(
        self,
        model: Model,
        rollout: Rollout,
        insts: List[ModelInstance],
        now: float,
    ) -> None:
        # disaggregated models roll their full role-tagged population
        # (prefill + decode); surge batches draw roles from the new
        # generation's per-role deficit, so the per-role caps hold
        spec = model.serving_replicas()
        new = [
            i for i in insts if i.generation == rollout.to_generation
        ]
        old = [
            i for i in insts if i.generation != rollout.to_generation
        ]
        if rollout.state == RolloutState.ROLLING_BACK:
            await self._rollback_step(model, rollout, new, now)
            return
        if rollout.rollback_requested:
            # an HA follower served POST /rollback and could only note
            # the request (executing there would strand the incident
            # in the follower's in-memory SLO ring) — the leader
            # executes it
            await self.begin_rollback(
                model, rollout, insts, now,
                rollout.rollback_requested, event="manual_rollback",
            )
            return
        if spec == 0:
            # scaled to zero mid-rollout: the rollout drains EVERY
            # instance itself and completes only once the set is
            # empty. Completing immediately would hand a mixed set to
            # replica sync, whose newest-first retirement keeps the
            # OLD generation — stranded behind this plan's no-retry
            # marker if the spec is raised again before drains land.
            # (If the spec comes back up mid-drain, the normal state
            # machine resumes and converges what survives.)
            await self._drain_old(
                insts, "rollout: model scaled to zero"
            )
            if not insts:
                await self._finish(
                    model, rollout, RolloutState.COMPLETED,
                    "spec scaled to zero mid-rollout", now,
                )
            return

        reason = self._gate_failure(model, rollout, new, now)
        if reason is not None:
            await self.begin_rollback(model, rollout, insts, now, reason)
            return

        if model.generation != rollout.to_generation:
            # superseded: an operator update landed mid-rollout.
            # Advancing would surge replicas that BOOT the newest spec
            # (serve_manager reads the live Model row) while tagged
            # with this plan's stale generation — the tag invariant
            # ("its engine runs THAT spec") breaks and the gates judge
            # a population that is not the generation the plan claims.
            # Fail the plan instead (mirrors begin_rollback's
            # supersede branch); _needs_rollout opens a fresh plan
            # toward the superseding generation on the next pass and
            # converges the stray canaries as old-generation rows.
            # (Checked AFTER the gate so a firing canary still routes
            # through begin_rollback, which records the incident.)
            await self._finish(
                model, rollout, RolloutState.FAILED,
                f"superseded by generation {model.generation} before "
                "completion; a new rollout converges the fleet", now,
                event="superseded",
            )
            return

        if rollout.state == RolloutState.SURGING:
            await self._surge_step(model, rollout, new, old, spec, now)
        elif rollout.state == RolloutState.OBSERVING:
            await self._observe_step(
                model, rollout, old, spec, now
            )
        elif rollout.state == RolloutState.PROMOTING:
            await self._promote_step(model, rollout, new, old, spec, now)

    async def _surge_step(
        self,
        model: Model,
        rollout: Rollout,
        new: List[ModelInstance],
        old: List[ModelInstance],
        spec: int,
        now: float,
    ) -> None:
        batch = min(rollout.surge, spec - rollout.promoted)
        if batch <= 0:
            if old:
                # spec shrank mid-rollout below the batches already
                # promoted: the promoted new-generation capacity covers
                # the whole (smaller) spec, so every remaining old
                # replica is excess — drain them all rather than
                # completing with the generations still mixed. Same
                # atomicity discipline as _observe_step: re-check the
                # plan under the lock so a rollback that landed
                # mid-tick never finds its old generation drained.
                async with self._plan_lock():
                    fresh = await Rollout.get(rollout.id)
                    if fresh is None or fresh.state != rollout.state:
                        return
                    await self._drain_old(old)
                    if await self._record(
                        rollout, now, "batch_promoted",
                        f"spec shrank to {spec}; draining all "
                        f"{len(old)} remaining old replica(s)",
                        state=RolloutState.PROMOTING,
                    ):
                        self._events.inc(
                            model=model.name, event="batch_promoted"
                        )
                return
            await self._finish(
                model, rollout, RolloutState.COMPLETED,
                "all batches promoted", now,
            )
            return
        want_new = rollout.promoted + batch
        if len(new) < want_new:
            # new + old is the model's full instance snapshot for this
            # reconcile pass — the name-collision set needs no re-query
            from gpustack_tpu.server.controllers import role_deficit

            created = await create_pending_instances(
                model, want_new - len(new),
                rollout.to_generation, new + old,
                prefix=f"{model.name}-g{rollout.to_generation}",
                # roles from the NEW generation's deficit vs the role
                # spec: per-role populations never exceed their spec
                # within a rollout (the per-role surge cap)
                roles=role_deficit(model, new)[: want_new - len(new)],
            )
            for inst in created:
                logger.info(
                    "rollout %d: surged instance %s",
                    rollout.id, inst.name,
                )
            return
        running = [
            i for i in new if i.state == ModelInstanceState.RUNNING
        ]
        if len(running) >= want_new:
            snap = snapshot_model_requests(model.name)
            fields: Dict[str, Any] = dict(
                state=RolloutState.OBSERVING,
                observe_since=now,
                canary=snap,
            )
            if not rollout.baseline_end:
                # freeze the baseline window's end at the FIRST
                # observation open: later batches must still be judged
                # against pure old-generation traffic, not windows the
                # new generation already served into
                fields["baseline_end"] = dict(snap)
            await self._record(
                rollout, now, "observing",
                f"batch of {batch} RUNNING; observation window open",
                **fields,
            )

    async def _observe_step(
        self,
        model: Model,
        rollout: Rollout,
        old: List[ModelInstance],
        spec: int,
        now: float,
    ) -> None:
        current = snapshot_model_requests(model.name)
        if (
            current.get("total", 0) < rollout.canary.get("total", 0)
            or current.get("ttft_count", 0)
            < rollout.canary.get("ttft_count", 0)
        ):
            # the in-memory histogram the persisted snapshots came
            # from reset (server restart / HA leader change). No
            # pre-rollout baseline exists anymore, so for THIS batch
            # the delta gates are undecided by construction
            # (baseline == canary → 0-request base window) and only
            # the burn-rate + instance-health gates judge it; from the
            # NEXT batch on the re-anchored baseline has accumulated
            # real traffic and the delta gates recover.
            await self._record(
                rollout, now, "window_reanchored",
                "request-histogram counters regressed (restart or "
                "failover); observation window restarted",
                baseline=current,
                baseline_end={},    # re-frozen at the next observe-open
                canary=dict(current),
                observe_since=now,
            )
            return
        if now - rollout.observe_since < self.cfg.rollout_observe_s:
            return
        quota = spec - rollout.promoted
        if quota <= 0:
            # spec shrank while observing: promoted capacity already
            # covers the whole spec — all remaining old are excess
            batch, doomed = 0, sorted(old, key=lambda i: i.id)
        else:
            batch = min(rollout.surge, quota, len(old))
            doomed = sorted(old, key=lambda i: i.id)[:batch]
        # The drain and the PROMOTING record must be atomic against a
        # manual rollback: begin_rollback holds the plan lock across
        # its body, so re-checking the plan state under the same lock
        # before the instance writes guarantees a rollback that landed
        # mid-tick never sees old-generation replicas we drained —
        # "the old generation never drops below spec" holds.
        async with self._plan_lock():
            fresh = await Rollout.get(rollout.id)
            if fresh is None or fresh.state != rollout.state:
                return
            await self._drain_old(
                doomed,
                "rollout: superseded by generation "
                f"{rollout.to_generation}",
            )
            if await self._record(
                rollout, now, "batch_promoted",
                f"gates passed; draining {len(doomed)} old replica(s)",
                state=RolloutState.PROMOTING,
                promoted=rollout.promoted + batch,
            ):
                self._events.inc(
                    model=model.name, event="batch_promoted"
                )

    async def _drain_old(
        self,
        doomed: List[ModelInstance],
        message: str = "rollout: superseded",
    ) -> None:
        for inst in doomed:
            # re-fetch before writing: Record.update persists the whole
            # document and the agent may have advanced this row since
            # the reconcile pass snapshotted it
            fresh = await ModelInstance.get(inst.id)
            if fresh is None:
                continue
            if fresh.state == ModelInstanceState.RUNNING:
                await fresh.update(
                    state=ModelInstanceState.DRAINING,
                    state_message=message,
                )
            elif fresh.state != ModelInstanceState.DRAINING:
                # a non-running old row (e.g. parked ERROR) has no
                # stream to drain — retire it directly
                await fresh.delete()

    async def _promote_step(
        self,
        model: Model,
        rollout: Rollout,
        new: List[ModelInstance],
        old: List[ModelInstance],
        spec: int,
        now: float,
    ) -> None:
        if any(
            i.state == ModelInstanceState.DRAINING for i in old
        ):
            return  # the workers are still retiring the drained batch
        if old:
            # undrained old replicas remain: another surge/observe
            # round — SURGING re-judges with the CURRENT spec, so a
            # mid-rollout resize (grow or shrink) converges instead of
            # wedging on the plan-time arithmetic
            await self._record(
                rollout, now, "next_batch",
                f"{len(old)} old replica(s) remain; surging next batch",
                state=RolloutState.SURGING,
            )
            return
        # old generation fully retired: done. Completion hands the
        # replica set back to _sync_replicas, which reconciles the
        # count to spec — necessary when the spec grew mid-rollout and
        # the surged batches alone cannot reach it
        await self._finish(
            model, rollout, RolloutState.COMPLETED,
            "old generation fully retired", now,
        )

    # ---- gates -----------------------------------------------------------

    def _gate_failure(
        self,
        model: Model,
        rollout: Rollout,
        new: List[ModelInstance],
        now: float,
    ) -> Optional[str]:
        for inst in new:
            if inst.state in (
                ModelInstanceState.ERROR,
                ModelInstanceState.UNREACHABLE,
            ):
                return (
                    f"canary {inst.name} is {inst.state.value}: "
                    f"{inst.state_message or 'no detail'}"
                )
            if inst.state != ModelInstanceState.RUNNING:
                age = _created_age(inst, now)
                if (
                    age is not None
                    and age > self.cfg.rollout_running_deadline
                ):
                    return (
                        f"canary {inst.name} not RUNNING within "
                        f"{self.cfg.rollout_running_deadline:.0f}s "
                        f"(still {inst.state.value} after {age:.0f}s)"
                    )
        evaluator = self.app.get("slo")
        if evaluator is not None:
            # only burns that STARTED after the plan opened gate it: a
            # rollout shipped to fix a firing incident must not be
            # insta-rolled-back (restoring the broken spec, forever)
            # by the very burn it exists to resolve
            known = set(rollout.preexisting_firing)
            firing = [
                o for o in evaluator.engine.firing_objectives(model.name)
                if o not in known
            ]
            if firing:
                return (
                    "slo burn-rate firing on "
                    f"{'/'.join(firing)} during rollout"
                )
        if rollout.canary:
            return delta_gate_failure(
                rollout.baseline,
                # pre-baseline_end plans (or a just-reanchored window)
                # fall back to the batch's own canary snapshot — the
                # first batch's [baseline, canary) window is identical
                rollout.baseline_end or rollout.canary,
                rollout.canary,
                snapshot_model_requests(model.name),
                self.cfg,
            )
        return None

    # ---- rollback --------------------------------------------------------

    async def begin_rollback(
        self,
        model: Model,
        rollout: Rollout,
        insts: List[ModelInstance],
        now: float,
        reason: str,
        event: str = "gate_failed",
    ) -> None:
        """Restore the previous generation's spec and start tearing the
        new generation down. Shared by the automatic gate path and the
        manual ``POST /v2/models/{id}/rollback`` route (which passes
        ``event="manual_rollback"`` so operator actions are not counted
        as health-gate failures)."""
        async with self._plan_lock():
            await self._begin_rollback_locked(
                model, rollout, insts, now, reason, event
            )

    @contextlib.asynccontextmanager
    async def _plan_lock(self):
        task = asyncio.current_task()
        if self._plan_lock_task is task:
            yield                       # reentrant within one task
            return
        async with self._plan_lock_inner:
            self._plan_lock_task = task
            try:
                yield
            finally:
                self._plan_lock_task = None

    async def _begin_rollback_locked(
        self,
        model: Model,
        rollout: Rollout,
        insts: List[ModelInstance],
        now: float,
        reason: str,
        event: str,
    ) -> None:
        # re-fetch before acting: the route (or an HA peer) may race
        # the reconcile loop's completing tick — rolling back a rollout
        # that just COMPLETED would resurrect the plan via a stale
        # whole-document write and drain the entire serving generation.
        # The fetch happens INSIDE the lock, so a concurrent executor
        # that just wrote ROLLING_BACK is seen here and bails.
        fresh_ro = await Rollout.get(rollout.id)
        if (
            fresh_ro is None
            or fresh_ro.state not in ACTIVE_ROLLOUT_STATES
            # already rolling back (e.g. the gate tick beat a manual
            # POST): re-running would bump the generation again and
            # duplicate the revision + incident
            or fresh_ro.state == RolloutState.ROLLING_BACK
        ):
            return
        rollout = fresh_ro
        self._events.inc(model=model.name, event=event)
        revision = await ModelRevision.first(
            model_id=model.id, generation=rollout.from_generation
        )
        if revision is None:
            # nothing to restore onto the Model row: removing the new
            # generation would leave replica sync recreating it from
            # the (bad) live spec — refuse rather than flap
            await self._finish(
                model, rollout, RolloutState.FAILED,
                f"{reason}; rollback impossible: no archived revision "
                f"for generation {rollout.from_generation}", now,
            )
            self._record_incident(model, rollout, now, reason)
            return
        # re-fetch right before the restore write: Record.update
        # persists the WHOLE document, and `model` may be a stale
        # snapshot from the top of the reconcile pass — writing it
        # would silently revert any concurrent operator edit
        fresh_model = await Model.get(model.id)
        if fresh_model is None:
            await self._finish(
                model, rollout, RolloutState.FAILED,
                f"{reason}; model deleted during rollback", now,
            )
            return
        if fresh_model.generation != rollout.to_generation:
            # superseded: an operator update landed mid-rollout (its
            # spec lives only on the Model row — never archived), so
            # restoring this plan's old spec would silently clobber
            # the newer fix and re-tag every instance past it. Finish
            # the stale plan instead; _needs_rollout opens a plan
            # toward the superseding generation on the next pass and
            # converges the stray canaries as old-generation rows.
            await self._finish(
                model, rollout, RolloutState.FAILED,
                f"{reason}; superseded by generation "
                f"{fresh_model.generation} — old spec not restored",
                now,
            )
            self._record_incident(model, rollout, now, reason)
            return
        restored_gen = fresh_model.generation + 1
        spec_fields = {
            k: v for k, v in revision.spec.items()
            if k in ROLLOUT_FIELDS
        }
        await ModelRevision.create(ModelRevision(
            model_id=model.id,
            generation=restored_gen,
            spec=dict(spec_fields),
        ))
        await fresh_model.update(
            **spec_fields, generation=restored_gen
        )
        # re-tag surviving old-generation instances BEFORE draining the
        # new generation: they run exactly the restored spec, and the
        # tag match keeps replica sync and _needs_rollout quiet
        # (re-fetched per row — whole-document writes on the stale
        # snapshots could revert concurrent agent state reports)
        for inst in insts:
            if inst.generation == rollout.to_generation:
                continue
            fresh = await ModelInstance.get(inst.id)
            if (
                fresh is not None
                and fresh.generation != rollout.to_generation
            ):
                await fresh.update(generation=restored_gen)
        await self._record(
            rollout, now, "rollback_started", reason,
            state=RolloutState.ROLLING_BACK,
            state_message=reason[:500],
        )
        self._record_incident(model, rollout, now, reason)
        logger.warning(
            "rollout %d rolling back model %s: %s",
            rollout.id, model.name, reason,
        )
        # start the new-generation teardown in the same pass — the
        # canary should stop taking traffic NOW, not a tick later
        fresh = await Rollout.get(rollout.id) or rollout
        await self._rollback_step(
            model, fresh,
            [i for i in insts if i.generation == rollout.to_generation],
            now,
        )

    async def _rollback_step(
        self,
        model: Model,
        rollout: Rollout,
        new: List[ModelInstance],
        now: float,
    ) -> None:
        await self._drain_old(new, "rollout rollback")
        if not new:
            await self._finish(
                model, rollout, RolloutState.ROLLED_BACK,
                "new generation removed; previous spec restored", now,
                event="rolled_back",
            )

    def _record_incident(
        self, model: Model, rollout: Rollout, now: float, reason: str
    ) -> None:
        evaluator = self.app.get("slo")
        if evaluator is None:
            return
        try:
            evidence = evaluator._evidence(model.name, "rollout")
        except Exception:  # noqa: BLE001 — evidence is best-effort
            evidence = {}
        evidence["rollout"] = {
            "id": rollout.id,
            "from_generation": rollout.from_generation,
            "to_generation": rollout.to_generation,
            "promoted_batches": rollout.promoted,
            "reason": reason,
        }
        evaluator.engine.record_incident(
            model.name, "rollout",
            now=now, detail=reason, evidence=evidence,
        )

    # ---- shared writes ---------------------------------------------------

    async def _record(
        self,
        rollout: Rollout,
        now: float,
        event: str,
        detail: str,
        **fields,
    ) -> bool:
        # State-machine guard + CAS: every caller holds a snapshot that
        # awaited (instance drains, revision writes) since it was
        # read. If the plan's state moved under us — e.g. a manual
        # POST /rollback landed mid-_observe_step — a stale forward
        # write would resurrect the pre-rollback state and re-surge
        # the bad generation. Only a ROLLING_BACK transition may
        # override a concurrent forward move; every other stale
        # writer defers to the next tick's fresh read. The write
        # itself is CAS-guarded (Record.save, PR 10) with retries OFF:
        # a conflict means the plan moved between our fresh read and
        # the write (an HA peer, a route) — same verdict as the state
        # guard, so the pre-CAS re-fetch dance is gone and even its
        # residual fetch→write window is closed. Returns whether the
        # write landed so callers can gate side effects (metrics,
        # logs, instance writes) on the transition actually happening.
        from gpustack_tpu.orm.record import ConflictError

        async with self._plan_lock():
            fresh = await Rollout.get(rollout.id)
            if fresh is None:
                return False
            if fresh.state != rollout.state and not (
                fields.get("state") == RolloutState.ROLLING_BACK
                and fresh.state in ACTIVE_ROLLOUT_STATES
                and fresh.state != RolloutState.ROLLING_BACK
            ):
                return False
            history = list(fresh.history) + [{
                "at": now, "event": event, "detail": detail,
            }]
            try:
                await fresh.update(
                    _retries=0, history=history[-HISTORY_CAP:], **fields
                )
            except ConflictError:
                return False
            return True

    async def _finish(
        self,
        model: Model,
        rollout: Rollout,
        state: RolloutState,
        detail: str,
        now: float,
        event: Optional[str] = None,
    ) -> None:
        if not await self._record(
            rollout, now, event or state.value, detail,
            state=state, state_message=detail[:500],
        ):
            # the plan moved under us (e.g. a manual rollback beat a
            # COMPLETED write): counting/logging the terminal state
            # anyway would corrupt the event stream operators audit
            return
        self._events.inc(
            model=model.name, event=event or state.value
        )
        logger.info(
            "rollout %d for model %s %s: %s",
            rollout.id, model.name, state.value, detail,
        )

    # ---- reads -----------------------------------------------------------

    def metrics_lines(self) -> List[str]:
        """``gpustack_rollout_state`` per model with rollout history
        (the events counter renders via the shared registry)."""
        lines: List[str] = []
        for model, state in sorted(self._latest_state.items()):
            lines.append(
                "gpustack_rollout_state"
                f'{{model="{escape_label_value(model)}"}} '
                f"{ROLLOUT_STATE_CODES.get(state, 6)}"
            )
        if not lines:
            return []
        kind = METRIC_FAMILIES["gpustack_rollout_state"]
        return [f"# TYPE gpustack_rollout_state {kind}"] + lines
