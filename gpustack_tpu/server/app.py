"""aiohttp application assembly (reference gpustack/server/app.py:26
create_app with its middleware stack + router mounting)."""

from __future__ import annotations

import json
import logging

import aiohttp
import pydantic
from aiohttp import web

from gpustack_tpu.api.middlewares import auth_middleware, timing_middleware
from gpustack_tpu.api import auth as auth_mod
from gpustack_tpu.config import Config
from gpustack_tpu.routes.auth_routes import (
    add_auth_routes,
    add_worker_facing_routes,
)
from gpustack_tpu.routes.crud import add_crud_routes, json_error
from gpustack_tpu.routes.openai_proxy import add_openai_routes
from gpustack_tpu.schemas import (
    Benchmark,
    Cluster,
    InferenceBackend,
    Model,
    ModelFile,
    ModelInstance,
    ModelInstanceState,
    ModelProvider,
    ModelRoute,
    Org,
    OrgMember,
    User,
    Worker,
    WorkerState,
)
from gpustack_tpu.schemas.usage import ModelUsage

logger = logging.getLogger(__name__)


@web.middleware
async def record_binding_middleware(request: web.Request, handler):
    """Pin this request's ORM binding to the owning server's db/bus.

    A no-op for the common one-server-per-process case; with several
    in-process HA servers (chaos harness) it guarantees a handler
    writes through — and publishes onto — the server that actually
    received the request, not whichever server bound last."""
    binding = request.app.get("record_binding")
    if binding is not None:
        from gpustack_tpu.orm.record import Record

        Record.bind_context(*binding)
    return await handler(request)


def create_app(cfg: Config) -> web.Application:
    # timing (the trace edge) is OUTERMOST so auth latency and auth
    # failures are traced and every response — 401s included — carries
    # X-Request-ID; the binding middleware sits outside even that so
    # auth's own DB reads resolve against the right server
    app = web.Application(
        middlewares=[
            record_binding_middleware, timing_middleware, auth_middleware,
        ],
        client_max_size=64 * 2**20,
    )
    app["config"] = cfg

    from gpustack_tpu.observability import LifecycleTracker, tracing

    tracing.get_store("server").configure(cfg.trace_ring_size)
    # embedded-worker mode shares this process: size its ring too (a
    # standalone worker sizes it from its own cfg in WorkerServer)
    tracing.get_store("worker").configure(cfg.trace_ring_size)
    app["lifecycle"] = LifecycleTracker("server")

    async def healthz(request):
        payload = {"status": "ok"}
        coordinator = app.get("coordinator")
        if coordinator is not None:
            payload["leader"] = coordinator.is_leader
            payload["ha_epoch"] = getattr(coordinator, "epoch", 0)
        # A dead embedded worker means this node can't serve anything —
        # surface it here instead of leaving the worker row silently
        # not_ready (the round-3 failure mode).
        worker_error = app.get("embedded_worker_error")
        if worker_error:
            payload["status"] = "degraded"
            payload["embedded_worker_error"] = worker_error
        return web.json_response(payload)

    async def readyz(request):
        return web.json_response({"status": "ready"})

    app.router.add_get("/healthz", healthz)
    app.router.add_get("/readyz", readyz)

    add_auth_routes(app)
    add_worker_facing_routes(app)
    add_openai_routes(app)
    from gpustack_tpu.tunnel.server import add_tunnel_route

    add_tunnel_route(app)
    from gpustack_tpu.server.exporter import add_metrics_route

    add_metrics_route(app)
    from gpustack_tpu.routes.extras import add_extra_routes

    add_extra_routes(app)

    # instance log streaming through the worker's http server (reference
    # routes/worker/logs.py path, proxied server-side)
    async def instance_logs(request: web.Request):
        from gpustack_tpu.server.worker_request import worker_fetch

        inst = await ModelInstance.get(int(request.match_info["id"]))
        if inst is None:
            return json_error(404, "instance not found")
        worker = await Worker.get(inst.worker_id or 0)
        if worker is None:
            return json_error(409, "instance is not placed on a worker")
        tail = request.query.get("tail", "200")
        follow = request.query.get("follow") in ("1", "true")
        path = f"/v2/instances/{inst.id}/logs?tail={tail}"
        if follow:
            path += "&follow=1"
        try:
            # tail reads are short idempotent control RPCs (retry tier);
            # follow is a streaming relay and keeps the long budget
            resp = await worker_fetch(
                app, worker, "GET", path,
                timeout=3600 if follow else 10,
                control=not follow,
            )
        except aiohttp.ClientError as e:
            return json_error(502, f"worker unreachable: {e}")
        if not follow:
            try:
                body = await resp.read()
            except aiohttp.ClientError as e:
                return json_error(502, f"worker unreachable: {e}")
            finally:
                resp.release()
            return web.Response(
                text=body.decode(errors="replace"), status=resp.status
            )
        out = web.StreamResponse(
            status=resp.status,
            headers={
                "Content-Type": "text/plain; charset=utf-8",
                "Cache-Control": "no-cache",
            },
        )
        await out.prepare(request)
        try:
            async for chunk in resp.content.iter_any():
                await out.write(chunk)
        except (ConnectionResetError, aiohttp.ClientError):
            pass
        finally:
            resp.release()
        return out

    app.router.add_get("/v2/model-instances/{id:\\d+}/logs", instance_logs)

    # ---- management CRUD ------------------------------------------------

    async def model_create_hook(request, obj: Model, body):
        if not obj.name:
            return json_error(400, "model name is required")
        if await Model.first(name=obj.name):
            return json_error(409, f"model {obj.name!r} already exists")
        if not obj.cluster_id:
            cluster = await Cluster.first()
            if cluster:
                obj.cluster_id = cluster.id
        if not obj.categories:
            # architecture auto-detection (reference model_registry.py)
            import asyncio as _asyncio

            from gpustack_tpu.scheduler.model_registry import (
                detect_categories,
            )

            obj.categories = await _asyncio.get_running_loop(
            ).run_in_executor(None, detect_categories, obj)
        return None

    async def catalog_deploy(request: web.Request):
        """One-call deploy from a catalog entry (the reference's
        catalog-as-primary-UX flow, server/catalog.py:50): resolves the
        entry's suggested defaults into a Model spec, merges request
        overrides field-by-field, and runs the SAME create path as
        POST /v2/models (hook included) so catalog deploys can't skirt
        validation."""
        from gpustack_tpu.routes.crud import require_admin
        from gpustack_tpu.server.catalog import (
            find_entry,
            model_fields_from_entry,
        )

        if err := require_admin(request):
            return err
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return json_error(400, "invalid JSON body")
        if not isinstance(body, dict):
            return json_error(400, "body must be a JSON object")
        entry = find_entry(str(body.get("name", "")))
        if entry is None:
            return json_error(
                404, f"catalog entry {body.get('name')!r} not found"
            )
        overrides = body.get("overrides") or {}
        if not isinstance(overrides, dict):
            return json_error(400, "'overrides' must be an object")
        unknown = [
            k for k in overrides
            if k not in Model.model_fields or k in ("id", "created_at")
        ]
        if unknown:
            return json_error(400, f"unknown override fields: {unknown}")
        fields = model_fields_from_entry(entry, overrides)
        try:
            obj = Model.model_validate(fields)
        except pydantic.ValidationError as e:
            return json_error(400, str(e))
        obj.id = 0
        # the FULL create-hook chain (name/cluster/category + org
        # validation) — same as POST /v2/models, so catalog deploys
        # can't skirt any of it
        if err := await model_create_and_org_hook(request, obj, fields):
            return err
        await Model.create(obj)
        return web.json_response(obj.model_dump(mode="json"), status=201)

    app.router.add_post("/v2/model-catalog/deploy", catalog_deploy)

    async def user_create_hook(request, obj: User, body):
        password = (body or {}).get("password", "")
        if not obj.username:
            return json_error(400, "username is required")
        if await User.first(username=obj.username):
            return json_error(409, "username taken")
        if password:
            obj.password_hash = auth_mod.hash_password(password)
        return None

    # Placement is written by the scheduler in-process; a worker that could
    # rewrite it (or worker_ip/port) would redirect all proxy traffic for
    # the model to an address of its choosing.
    INSTANCE_PLACEMENT_FIELDS = frozenset(
        {
            "worker_id", "worker_name", "worker_ip", "chip_indexes",
            "computed_resource_claim", "subordinate_workers",
            "model_id", "model_name", "cluster_id", "name",
            # rollout bookkeeping: which spec generation the instance
            # serves is controller-owned, never agent-reported
            "generation",
        }
    )
    # Runtime endpoint fields only the leading (placed-on) worker reports.
    INSTANCE_LEADER_FIELDS = frozenset(
        {"port", "coordinator_address", "pid"}
    )

    def instance_worker_owns(principal, inst, new_fields) -> bool:
        if inst is None:
            # role gate (fields None) passes; creates (fields set) are the
            # controller's job, never a worker's
            return new_fields is None
        touched = set(new_fields or ())
        if touched & INSTANCE_PLACEMENT_FIELDS:
            return False
        if inst.worker_id == principal.worker_id:
            return True
        is_subordinate = any(
            s.worker_id == principal.worker_id
            for s in inst.subordinate_workers
        )
        # followers report state only — endpoint fields are leader-owned
        return is_subordinate and not (touched & INSTANCE_LEADER_FIELDS)

    from gpustack_tpu.api.tenant import accessible_org_ids, model_accessible

    async def model_visible(request, obj: Model) -> bool:
        return await model_accessible(request.get("principal"), obj)

    async def model_org_check(request, obj: Model, fields):
        org_id = (
            fields.get("org_id", obj.org_id)
            if isinstance(fields, dict) else obj.org_id
        )
        if org_id and await Org.get(org_id) is None:
            return json_error(400, f"org {org_id} does not exist")
        return None

    async def model_create_and_org_hook(request, obj: Model, body):
        if err := await model_create_hook(request, obj, body):
            return err
        return await model_org_check(request, obj, body)

    async def model_update_hook(request, obj: Model, fields):
        """Org check + rollout versioning: a change to any serving-
        relevant field (schemas/models.py ROLLOUT_FIELDS) on a deployed
        model archives the current spec as a ModelRevision (the
        rollback source) and bumps ``generation`` — which is what the
        RolloutController converges instances onto. Replica counts,
        SLO targets, autoscale bounds etc. reconcile without a
        rollout."""
        if err := await model_org_check(request, obj, fields):
            return err
        from gpustack_tpu.schemas import ModelRevision
        from gpustack_tpu.schemas.models import ROLLOUT_FIELDS

        touched = set(fields) & set(ROLLOUT_FIELDS)
        if "generation" in fields:
            # generation is server-owned: derived here, never client-set
            fields.pop("generation")
        # the durable wake marker is written by the proxy's 503 path
        # and consumed by the leader's autoscaler — never client-set
        fields.pop("wake_requested_at", None)
        if not touched:
            return None
        try:
            candidate = Model.model_validate(
                {**obj.model_dump(), **fields}
            )
        except pydantic.ValidationError as e:
            return json_error(400, str(e))
        if all(
            getattr(candidate, k) == getattr(obj, k) for k in touched
        ):
            return None  # no-op writes don't version
        if await ModelRevision.first(
            model_id=obj.id, generation=obj.generation
        ) is None:
            await ModelRevision.create(ModelRevision(
                model_id=obj.id,
                generation=obj.generation,
                spec={k: getattr(obj, k) for k in ROLLOUT_FIELDS},
            ))
        # bounded history: the rollback source only ever needs recent
        # generations — but a generation an ACTIVE rollout would
        # restore on gate failure is pinned regardless of age, or a
        # burst of updates mid-rollout would turn its rollback into
        # FAILED-with-the-bad-spec-live
        from gpustack_tpu.schemas import Rollout
        from gpustack_tpu.schemas.rollouts import ACTIVE_ROLLOUT_STATES

        pinned = {
            r.from_generation
            for r in await Rollout.filter(model_id=obj.id)
            if r.state in ACTIVE_ROLLOUT_STATES
        }
        revisions = sorted(
            await ModelRevision.filter(model_id=obj.id),
            key=lambda r: r.generation,
        )
        for stale in revisions[:-8]:
            if stale.generation not in pinned:
                await stale.delete()
        # derive the bump from a generation re-read AFTER this hook's
        # awaits: a rollback restore racing this request would have
        # bumped the row already, and writing obj.generation+1 from
        # the stale snapshot would give two different specs the same
        # generation number — the operator's update would then never
        # roll out (instances already tagged with it). A short window
        # remains until the route's final write; an honest 409 beats
        # a silent no-op.
        current = await Model.get(obj.id)
        if current is None:
            return json_error(404, "model deleted concurrently")
        if current.generation != obj.generation:
            return json_error(
                409, "model generation changed concurrently; retry"
            )
        fields["generation"] = obj.generation + 1
        return None

    add_crud_routes(
        app, Model, "models",
        create_hook=model_create_and_org_hook,
        update_hook=model_update_hook,
        visible=model_visible,
    )

    # orgs: non-admins see only orgs they belong to; members likewise
    async def org_visible(request, obj: Org) -> bool:
        orgs = await accessible_org_ids(request.get("principal"))
        return orgs is None or obj.id in orgs

    async def org_member_visible(request, obj: OrgMember) -> bool:
        orgs = await accessible_org_ids(request.get("principal"))
        return orgs is None or obj.org_id in orgs

    async def org_delete_hook(request, obj: Org):
        if await Model.first(org_id=obj.id):
            return json_error(
                409, "org still owns models; reassign or delete them first"
            )
        for m in await OrgMember.filter(org_id=obj.id, limit=10**6):
            await m.delete()
        return None

    add_crud_routes(
        app, Org, "orgs",
        visible=org_visible, delete_hook=org_delete_hook,
    )

    async def org_member_create_hook(request, obj: OrgMember, body):
        if await Org.get(obj.org_id) is None:
            return json_error(400, f"org {obj.org_id} does not exist")
        if await User.get(obj.user_id) is None:
            return json_error(400, f"user {obj.user_id} does not exist")
        if await OrgMember.first(org_id=obj.org_id, user_id=obj.user_id):
            return json_error(409, "already a member")
        return None

    add_crud_routes(
        app, OrgMember, "org-members",
        create_hook=org_member_create_hook,
        visible=org_member_visible,
    )
    async def instance_transition_hook(request, obj: ModelInstance, fields):
        """Enforce the declared lifecycle at the API boundary. In-process
        writers (scheduler, controllers) are trusted; HTTP writers race
        the controllers — e.g. an agent's RUNNING report landing after
        the server parked the row UNREACHABLE — and an illegal write
        here used to silently corrupt the state machine (chaos-harness
        finding: the transition-legality invariant tripped on exactly
        this race). The agent recovers via its post-recovery reconcile,
        which re-drives through a declared path."""
        new_state = (fields or {}).get("state")
        if new_state is None:
            return None
        try:
            target = ModelInstanceState(new_state)
        except ValueError:
            return json_error(400, f"unknown instance state {new_state!r}")
        if target == obj.state:
            return None  # idempotent re-assert
        from gpustack_tpu.schemas import validate_instance_transition

        if not validate_instance_transition(obj.state, target):
            return json_error(
                409,
                f"illegal instance state transition "
                f"{obj.state.value} -> {target.value}",
            )
        if (
            obj.state == ModelInstanceState.UNREACHABLE
            and target == ModelInstanceState.RUNNING
        ):
            # un-parking is only legal once the worker itself is back:
            # an agent's in-flight RUNNING report squeezing through a
            # closing partition would otherwise park a RUNNING row on a
            # dead worker forever (no worker-state edge fires again,
            # and the rescuer scans only UNREACHABLE/ERROR rows)
            worker = await Worker.get(obj.worker_id or 0)
            if worker is None or worker.state != WorkerState.READY:
                return json_error(
                    409,
                    "instance cannot resume running while its worker "
                    "is not ready",
                )
        return None

    add_crud_routes(
        app, ModelInstance, "model-instances",
        worker_write=True, worker_owns=instance_worker_owns,
        update_hook=instance_transition_hook,
    )
    add_crud_routes(app, Worker, "workers", redact=("proxy_secret",))
    add_crud_routes(app, Cluster, "clusters")
    add_crud_routes(app, ModelRoute, "model-routes")
    add_crud_routes(app, ModelFile, "model-files", worker_write=True)
    add_crud_routes(
        app, User, "users",
        create_hook=user_create_hook,
        admin_read=True, redact=("password_hash",),
    )
    async def benchmark_create_hook(request, obj: Benchmark, body):
        if await Model.get(obj.model_id) is None:
            return json_error(
                400, f"model {obj.model_id} does not exist"
            )
        # server-owned fields cannot be seeded by the client
        from gpustack_tpu.schemas import BenchmarkState

        obj.state = BenchmarkState.PENDING
        obj.state_message = ""
        obj.metrics = None
        obj.raw_report = {}
        obj.worker_id = 0
        obj.model_instance_id = 0
        return None

    # workers update benchmark state/metrics with their worker tokens
    add_crud_routes(
        app, Benchmark, "benchmarks",
        worker_write=True, create_hook=benchmark_create_hook,
    )
    add_crud_routes(app, InferenceBackend, "inference-backends")

    async def provider_visible(request, obj) -> bool:
        from gpustack_tpu.api.tenant import org_scoped_accessible

        return await org_scoped_accessible(request.get("principal"), obj)

    async def provider_check(name, base_url, org_id, existing_id):
        if not name:
            return json_error(400, "provider name is required")
        if not str(base_url).startswith(("http://", "https://")):
            return json_error(400, "base_url must be http(s)")
        dup = await ModelProvider.first(name=name, org_id=org_id)
        if dup is not None and dup.id != existing_id:
            return json_error(
                409, f"provider {name!r} already exists in this org"
            )
        return None

    async def provider_create_hook(request, obj, body):
        return await provider_check(obj.name, obj.base_url, obj.org_id, 0)

    async def provider_update_hook(request, obj, fields):
        # the same invariants hold on update (name/base_url/org moves);
        # obj is pre-update here, so check the effective merged values
        return await provider_check(
            fields.get("name", obj.name),
            fields.get("base_url", obj.base_url),
            fields.get("org_id", obj.org_id),
            obj.id,
        )

    # External model providers (reference schemas/model_provider.py):
    # admin-managed; api_key write-only (never serialized, watch included)
    add_crud_routes(
        app, ModelProvider, "model-providers",
        create_hook=provider_create_hook,
        update_hook=provider_update_hook,
        visible=provider_visible,
        redact=("api_key",),
    )

    async def worker_pool_create_hook(request, obj, body):
        from gpustack_tpu.cloud.providers import _PROVIDERS

        if not obj.name:
            return json_error(400, "pool name is required")
        if obj.provider not in _PROVIDERS:
            return json_error(
                400,
                f"unknown provider {obj.provider!r} "
                f"(available: {sorted(_PROVIDERS)})",
            )
        from gpustack_tpu.schemas import WorkerPool as _WP

        if await _WP.first(name=obj.name):
            return json_error(409, f"pool {obj.name!r} already exists")
        return None

    from gpustack_tpu.schemas import CloudWorker, WorkerPool

    # provider_config may hold credentials → admin-only reads
    add_crud_routes(
        app, WorkerPool, "worker-pools",
        create_hook=worker_pool_create_hook, admin_read=True,
    )
    # lifecycle rows are controller-owned: read-only over the API; the
    # provider snapshot can carry credentials
    add_crud_routes(
        app, CloudWorker, "cloud-workers",
        readonly=True, admin_read=True, redact=("provider_config",),
    )

    # -- dev instances (reference gpu_instances role) ---------------------
    from gpustack_tpu.schemas import DevInstance, DevInstanceState

    DEV_PLACEMENT_FIELDS = frozenset(
        {"worker_id", "worker_name", "chip_indexes", "chips",
         "name", "cluster_id", "user_id", "command", "env"}
    )

    def dev_worker_owns(principal, dev, new_fields) -> bool:
        if dev is None:
            return new_fields is None  # workers never create these
        if set(new_fields or ()) & DEV_PLACEMENT_FIELDS:
            return False
        return dev.worker_id == principal.worker_id

    async def dev_create_hook(request, obj: DevInstance, body):
        if not obj.name:
            return json_error(400, "dev instance name is required")
        if await DevInstance.first(name=obj.name):
            return json_error(409, f"dev instance {obj.name!r} exists")
        if obj.chips < 1:
            return json_error(400, "chips must be >= 1")
        # server-owned fields can't be seeded by the client
        obj.state = DevInstanceState.PENDING
        obj.state_message = ""
        obj.worker_id = 0
        obj.worker_name = ""
        obj.chip_indexes = []
        obj.pid = 0
        principal = request.get("principal")
        if principal is not None and principal.user is not None:
            obj.user_id = principal.user.id
        return None

    add_crud_routes(
        app, DevInstance, "dev-instances",
        create_hook=dev_create_hook,
        worker_write=True, worker_owns=dev_worker_owns,
    )

    async def dev_exec(request: web.Request) -> web.Response:
        """Exec inside a dev instance, relayed through the owning
        worker's authenticated proxy. Admin or the instance's creator."""
        principal = request.get("principal")
        dev = await DevInstance.get(int(request.match_info["id"]))
        if dev is None:
            return json_error(404, "dev instance not found")
        is_owner = bool(
            principal and principal.user
            and principal.user.id == dev.user_id
        )
        if not (principal and principal.is_admin or is_owner):
            return json_error(403, "admin or instance owner required")
        if dev.state != DevInstanceState.RUNNING:
            return json_error(
                409, f"dev instance is {dev.state.value}, not running"
            )
        worker = await Worker.get(dev.worker_id)
        if worker is None:
            return json_error(503, "owning worker not found")
        try:
            body = await request.json()
        except ValueError:
            return json_error(400, "invalid JSON")
        from gpustack_tpu.server.worker_request import worker_fetch

        try:
            upstream = await worker_fetch(
                app, worker, "POST",
                f"/v2/dev-instances/{dev.id}/exec",
                json_body=body,
            )
        except aiohttp.ClientError as e:
            return json_error(502, f"worker unreachable: {e}")
        payload = await upstream.read()
        upstream.release()
        return web.Response(
            body=payload,
            status=upstream.status,
            content_type=upstream.content_type,
        )

    app.router.add_post("/v2/dev-instances/{id:\\d+}/exec", dev_exec)
    # per-user usage rows: /v2/usage/summary already scopes non-admins to
    # their own usage (extras.py); raw rows are admin-only to match.
    add_crud_routes(
        app, ModelUsage, "model-usage", readonly=True, admin_read=True
    )
    from gpustack_tpu.server.collectors import (
        ResourceEvent,
        SystemLoad,
        UsageArchive,
    )

    from gpustack_tpu.schemas import ModelRevision, Rollout

    # rollout plans + per-generation spec archive: controller-owned
    # (mutations go through /v2/models/{id}/rollback), read-only here
    add_crud_routes(
        app, Rollout, "rollouts", readonly=True, admin_read=True
    )
    add_crud_routes(
        app, ModelRevision, "model-revisions",
        readonly=True, admin_read=True,
    )
    add_crud_routes(
        app, ResourceEvent, "resource-events",
        readonly=True, admin_read=True,
    )
    add_crud_routes(
        app, SystemLoad, "system-load", readonly=True, admin_read=True
    )
    add_crud_routes(
        app, UsageArchive, "usage-archive",
        readonly=True, admin_read=True,
    )

    # plugins mount last: they may override nothing but can add routes
    # (reference server/app.py:88 plugin load)
    from gpustack_tpu.extension import load_plugins

    app["plugins"] = load_plugins()
    for plugin in app["plugins"]:
        try:
            plugin.setup_app(app, cfg)
        except Exception:
            logger.exception(
                "plugin %s setup failed", plugin.name or type(plugin)
            )

    # multi-server tunnel federation registry (tunnel/federation.py):
    # config-seeded, runtime-adjustable via /v2/federation/peers
    from gpustack_tpu.tunnel.federation import FederationRegistry

    app["federation"] = FederationRegistry.from_config(
        cfg.federation_peers
    )

    # data-plane resilience: breaker/health view + least-outstanding
    # selection + load shedding for the OpenAI proxy (server/resilience.py)
    from gpustack_tpu.server.resilience import ResilienceRegistry

    app["resilience"] = ResilienceRegistry.from_config(cfg)

    # tenant QoS: per-key quotas, token budgets, weighted-fair
    # admission + priority shedding for the OpenAI surface
    # (server/tenancy.py; docs/TENANCY.md)
    from gpustack_tpu.server.tenancy import (
        TenancyRegistry,
        durable_budget_spend,
    )

    app["tenancy"] = TenancyRegistry.from_config(cfg)
    # rolling token budgets survive restarts: the first admission per
    # tenant re-seeds the window from durable model_usage rows (the
    # PR 14 process-local-budget residual, closed)
    app["tenancy"].rehydrator = durable_budget_spend

    # control-plane write combiner: worker heartbeat/status writes
    # coalesce into batched column writes so DB write rate grows
    # sub-linearly in workers (server/write_combiner.py). Constructed
    # per app — leader AND follower, heartbeats land wherever the load
    # balancer sends them; the Server starts/drains its flush loop.
    from gpustack_tpu.server.write_combiner import ControlWriteCombiner

    app["write_combiner"] = ControlWriteCombiner.from_config(cfg)

    # shared client session for the OpenAI proxy
    async def on_startup(app: web.Application):
        import asyncio as _asyncio

        app["proxy_session"] = aiohttp.ClientSession()
        # lifecycle timelines ride the lossless bus tap (same mechanism
        # as the chaos harness's invariant observer) — attached here,
        # after the ORM layer is bound to its bus
        from gpustack_tpu.orm.record import Record

        try:
            app["lifecycle"].attach(Record.bus())
        except Exception as e:
            # an app mounted without a bound Record (bare unit-test
            # mounts) simply runs without timelines
            logger.warning("lifecycle tracker not attached: %s", e)
        # feed the health view from instance/worker lifecycle events
        # (heartbeat staleness → worker UNREACHABLE → breakers trip
        # without waiting for request traffic to fail)
        app["resilience_watch"] = _asyncio.create_task(
            app["resilience"].watch(), name="resilience-watch"
        )
        # fleet KV fabric (server/kv_directory.py): scrape each
        # KV-capable replica's prefix-key summary on a period, and arm
        # the drain-time prefetch trigger (resilience watch fires it)
        from gpustack_tpu.server.kv_directory import (
            directory_refresh_loop,
            prefetch_for_drain,
        )

        reg = app["resilience"]

        async def _drain_prefetch(instance_id, keys):
            try:
                await prefetch_for_drain(
                    app, reg.kv_directory, instance_id, keys=keys
                )
            except Exception:
                logger.exception(
                    "drain prefetch for instance %s failed",
                    instance_id,
                )

        reg.kv_prefetch = _drain_prefetch
        app["kv_directory_task"] = _asyncio.create_task(
            directory_refresh_loop(app, reg.kv_directory),
            name="kv-directory-refresh",
        )
        app["plugin_tasks"] = []
        for plugin in app["plugins"]:
            try:
                coros = plugin.tasks(app, cfg)
            except Exception:
                # one faulty plugin must not abort server startup (same
                # tolerance as load/setup)
                logger.exception(
                    "plugin %s tasks() failed",
                    plugin.name or type(plugin),
                )
                continue
            for coro in coros:
                app["plugin_tasks"].append(_asyncio.create_task(coro))

    async def on_cleanup(app: web.Application):
        import asyncio as _asyncio

        tracker = app.get("lifecycle")
        if tracker is not None:
            tracker.detach()
        watch = app.get("resilience_watch")
        if watch is not None:
            watch.cancel()
            try:
                await watch
            except (
                _asyncio.CancelledError,
                Exception,
            ):
                pass
        kv_task = app.get("kv_directory_task")
        if kv_task is not None:
            kv_task.cancel()
            try:
                await kv_task
            except (
                _asyncio.CancelledError,
                Exception,
            ):
                pass
        tasks = app.get("plugin_tasks", [])
        for task in tasks:
            task.cancel()
        if tasks:
            # cancellation must be delivered before the loop closes —
            # plugin finally blocks run here
            await _asyncio.gather(*tasks, return_exceptions=True)
        await app["proxy_session"].close()

    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)
    return app
