"""In-process event bus: per-subscriber bounded queues with coalescing.

Semantics carried over from the reference bus (reference
gpustack/server/bus.py:53-199): per-subscriber bounded queue, UPDATED
events coalesce by (kind, id) while queued, delivery order preserved.

One deliberate divergence: the reference applies *blocking* backpressure to
publishers when a subscriber's queue fills (reference bus.py:130-138 — a
known bug-history hotspot). Here a slow subscriber instead overflows onto a
RESYNC marker: its queue is cleared and it receives one RESYNC event,
telling it to re-list from the DB (k8s watch-bookmark style). Publishers
never block, and correctness folds into the re-list path every controller
needs anyway.
"""

from __future__ import annotations

import asyncio
import dataclasses
import enum
import time
from collections import deque
from typing import (
    Any,
    AsyncIterator,
    Callable,
    Dict,
    List,
    Optional,
    Set,
    Tuple,
)


class EventType(str, enum.Enum):
    CREATED = "CREATED"
    UPDATED = "UPDATED"
    DELETED = "DELETED"
    HEARTBEAT = "HEARTBEAT"
    RESYNC = "RESYNC"


@dataclasses.dataclass
class Event:
    kind: str                       # record kind, e.g. "model_instance"
    type: EventType
    id: int = 0
    data: Optional[Dict[str, Any]] = None
    changes: Optional[Dict[str, Any]] = None   # field -> (old, new)
    ts: float = dataclasses.field(default_factory=time.time)
    # True for events an HA coordinator re-published from a PEER's
    # change-log entry: consumers treat them like local events, but
    # per-write auditors (the chaos transition observer) skip them so
    # each write is judged exactly once cluster-wide, at its origin
    remote: bool = False

    def to_wire(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "type": self.type.value,
            "id": self.id,
            "data": self.data,
            "changes": self.changes,
            "ts": self.ts,
        }

    @staticmethod
    def from_wire(d: Dict[str, Any]) -> "Event":
        return Event(
            kind=d["kind"],
            type=EventType(d["type"]),
            id=d.get("id", 0),
            data=d.get("data"),
            changes=d.get("changes"),
            ts=d.get("ts", 0.0),
        )


class Subscriber:
    """Bounded event queue with UPDATED-coalescing and overflow→RESYNC."""

    def __init__(
        self, bus: "EventBus", kinds: Optional[Set[str]], max_size: int
    ):
        self._bus = bus
        self.kinds = kinds
        self.max_size = max_size
        self._queue: deque = deque()
        self._pending_updates: Dict[Tuple[str, int], Event] = {}
        self._overflowed = False
        self._waiter: Optional[asyncio.Future] = None
        self.delivered = 0
        self.coalesced = 0
        self.resyncs = 0

    # called by the bus (event-loop thread)
    def _offer(self, event: Event) -> None:
        if event.type == EventType.RESYNC:
            # broadcast re-list marker (e.g. HA followers poll-refresh):
            # bypasses kind filtering
            self._queue.clear()
            self._pending_updates.clear()
            self._overflowed = True
            self.resyncs += 1
            self._wake()
            return
        if self.kinds is not None and event.kind not in self.kinds:
            return
        if event.type == EventType.UPDATED:
            key = (event.kind, event.id)
            pending = self._pending_updates.get(key)
            if pending is not None:
                # Coalesce in place: newest data, merged change keys,
                # original queue position.
                if pending.changes and event.changes:
                    merged = dict(pending.changes)
                    for f, (old, _new) in merged.items():
                        if event.changes and f in event.changes:
                            event.changes[f] = (old, event.changes[f][1])
                    merged.update(event.changes or {})
                    event.changes = merged
                pending.data = event.data
                pending.changes = event.changes
                pending.ts = event.ts
                self.coalesced += 1
                return
        if len(self._queue) >= self.max_size:
            # Slow subscriber: drop everything, force a re-list.
            self._queue.clear()
            self._pending_updates.clear()
            self._overflowed = True
            self.resyncs += 1
            self._wake()
            return
        self._queue.append(event)
        if event.type == EventType.UPDATED:
            self._pending_updates[(event.kind, event.id)] = event
        self._wake()

    def _wake(self) -> None:
        if self._waiter is not None and not self._waiter.done():
            self._waiter.set_result(None)

    async def get(self, timeout: Optional[float] = None) -> Event:
        """Next event; HEARTBEAT on timeout; RESYNC after overflow."""
        while True:
            if self._overflowed:
                self._overflowed = False
                return Event(kind="*", type=EventType.RESYNC)
            if self._queue:
                event = self._queue.popleft()
                if event.type == EventType.UPDATED:
                    self._pending_updates.pop(
                        (event.kind, event.id), None
                    )
                self.delivered += 1
                return event
            self._waiter = asyncio.get_running_loop().create_future()
            try:
                await asyncio.wait_for(
                    self._waiter, timeout=timeout
                )
            except asyncio.TimeoutError:
                return Event(kind="*", type=EventType.HEARTBEAT)
            finally:
                self._waiter = None

    async def __aiter__(self) -> AsyncIterator[Event]:
        while True:
            yield await self.get()

    def close(self) -> None:
        self._bus._subscribers.discard(self)


class DirtySet:
    """Synchronous bus tap accumulating changed record ids per kind.

    Reconcile loops that full-scan tables every tick (rollout,
    autoscaler) drain this instead: an empty drain on a steady-state
    pass means NOTHING they watch changed since the last tick, so the
    cached snapshot from that tick is still exact and the scan can be
    skipped. Conservative by construction: a RESYNC marker (subscriber
    overflow, HA re-list) reads as everything-dirty. Taps are lossless
    (no coalescing), so a single write can never slip through."""

    def __init__(self, bus: "EventBus", kinds: Set[str]):
        self._bus = bus
        self.kinds = set(kinds)
        self._dirty: Dict[str, Set[int]] = {}
        self._all = False
        bus.add_tap(self._tap)

    def _tap(self, event: "Event") -> None:
        if event.type == EventType.RESYNC:
            self._all = True
            return
        if event.kind in self.kinds and event.type in (
            EventType.CREATED, EventType.UPDATED, EventType.DELETED
        ):
            self._dirty.setdefault(event.kind, set()).add(event.id)

    def drain(self) -> Tuple[bool, Dict[str, Set[int]]]:
        """(everything_dirty, {kind: ids}) since the last drain."""
        dirty, self._dirty = self._dirty, {}
        all_, self._all = self._all, False
        return all_, dirty

    def mark_all(self) -> None:
        """Re-arm after a FAILED pass: the drained events were consumed
        but never acted on — without this, the next tick would read an
        empty set and skip work that is still pending."""
        self._all = True

    def close(self) -> None:
        self._bus.remove_tap(self._tap)


class EventBus:
    """Publish/subscribe hub. ``publish`` is sync and must run on the event
    loop thread (DB layer publishes post-commit from the loop)."""

    def __init__(self, default_queue_size: int = 1024):
        self._subscribers: Set[Subscriber] = set()
        self.default_queue_size = default_queue_size
        self.published: Dict[Tuple[str, str], int] = {}
        # Synchronous, LOSSLESS observation taps. Subscriber queues
        # coalesce UPDATED events (by design — consumers re-read state
        # anyway), which folds consecutive writes into multi-hop change
        # pairs; anything auditing per-write properties (the chaos
        # harness's transition-legality observer) needs every single
        # event in publish order. Taps must be fast and non-raising;
        # a tap exception is contained so it can never break commits.
        self._taps: List[Callable[[Event], None]] = []

    def subscribe(
        self,
        kinds: Optional[Set[str]] = None,
        max_size: Optional[int] = None,
    ) -> Subscriber:
        sub = Subscriber(self, kinds, max_size or self.default_queue_size)
        self._subscribers.add(sub)
        return sub

    def add_tap(self, fn: Callable[[Event], None]) -> None:
        self._taps.append(fn)

    def remove_tap(self, fn: Callable[[Event], None]) -> None:
        if fn in self._taps:
            self._taps.remove(fn)

    def publish(self, event: Event) -> None:
        key = (event.kind, event.type.value)
        self.published[key] = self.published.get(key, 0) + 1
        for fn in list(self._taps):
            try:
                fn(event)
            except Exception:  # noqa: BLE001 — taps never break commits
                import logging

                logging.getLogger(__name__).exception(
                    "event tap failed"
                )
        for sub in list(self._subscribers):
            sub._offer(event)

    def publish_threadsafe(
        self, loop: asyncio.AbstractEventLoop, event: Event
    ) -> None:
        loop.call_soon_threadsafe(self.publish, event)
