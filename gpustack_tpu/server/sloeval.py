"""Periodic SLO evaluation: wire live signals into the SLO engine.

The engine (observability/slo.py) is pure; this evaluator is the impure
side of the split — every tick it reads the signals the system already
emits and feeds them in as good/total counts:

- **availability** — RUNNING replicas vs the model's spec, straight
  from the instance table (works even against chaos-harness stub
  workers, which is what the tier-1 chaos e2e leans on);
- **error_rate** — ``gpustack_request_duration_seconds`` cumulative
  counts (phase=total), outcome ``ok`` vs everything else, per model;
- **ttft** — the same histogram's phase=ttft bucket counts: requests
  at-or-under the model's TTFT threshold vs all (the threshold snaps
  down to a bucket boundary — pick thresholds on them);
- **queue_wait** — READY workers' normalized
  ``gpustack_tpu:queue_oldest_wait_seconds`` gauges (the fleet-rollup
  signal), sampled per tick against the model's threshold. Scraped
  only when some model actually enables the objective;
- **invariants** — the chaos harness's always-scope convergence checks
  (testing/invariants.py) as a cluster-wide objective under the
  pseudo-model ``_cluster``.

On every escalation the engine calls back into :meth:`_evidence`,
which snapshots what a responder needs in one place: matching trace
exemplars from the PR 5 trace store, the instance lifecycle timelines,
the last scraped engine metrics, and the invariant report — the
incident ring served at ``GET /v2/debug/incidents`` is self-contained.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Tuple

from gpustack_tpu.config import Config
from gpustack_tpu.observability.metrics import get_registry
from gpustack_tpu.observability.slo import ObjectiveSpec, SLOEngine
from gpustack_tpu.schemas import (
    Model,
    ModelInstance,
    ModelInstanceState,
    Worker,
    WorkerState,
)
from gpustack_tpu.server.collectors import PeriodicTask
from gpustack_tpu.utils.profiling import timed

logger = logging.getLogger(__name__)

# cluster-scope objectives (invariants) live under this pseudo-model so
# one status/metric surface covers both granularities
CLUSTER_MODEL = "_cluster"

# tenant-scoped objectives (tenancy admission layer) live under
# pseudo-models "tenant:<id>" — a noisy neighbor's burn alert is keyed
# to the tenant, not to any model and not to _cluster
TENANT_MODEL_PREFIX = "tenant:"

# the "p95" in slo_ttft_p95_ms / slo_queue_wait_p95_ms: 95% of
# requests (or ticks) must be at-or-under the threshold
LATENCY_GOOD_RATIO = 0.95

QUEUE_WAIT_METRIC = "gpustack_tpu:queue_oldest_wait_seconds"


def resolve_target(
    model_value: float, default: float
) -> Optional[float]:
    """Per-model override semantics: negative disables the objective
    for this model, 0 inherits the config default, and a non-positive
    default means off-unless-configured."""
    value = default if model_value == 0 else model_value
    if value is None or value <= 0:
        return None
    return value


class SLOEvaluator(PeriodicTask):
    task_name = "slo-evaluator"

    def __init__(self, app, cfg: Config):
        super().__init__(max(0.05, cfg.slo_eval_interval))
        self.app = app
        self.cfg = cfg
        self.engine = SLOEngine(
            window_scale=cfg.slo_window_scale,
            min_hold=cfg.slo_min_hold,
            incident_ring=cfg.slo_incident_ring,
            evidence_hook=self._evidence,
        )
        self.ticks = 0
        # evidence caches refreshed each tick (read synchronously by
        # the evidence hook mid-evaluate)
        self._model_instances: Dict[str, List[int]] = {}
        self._last_engine_metrics: Dict[str, Dict[str, Dict]] = {}
        self._last_violations: List[Dict[str, str]] = []
        # (model, objective) pairs enabled this tick — everything
        # else is pruned, so disabling an objective per model retires
        # its tracker instead of leaving stale gauges behind
        self._active: set = set()

    async def tick(self) -> None:
        await self.evaluate_once()

    # ------------------------------------------------------------------

    @timed(threshold_s=5.0, name="sloeval.evaluate")
    async def evaluate_once(
        self, now: Optional[float] = None
    ) -> List[Dict[str, Any]]:
        """One evaluation pass; ``now`` is injectable so tests drive
        synthetic clocks through real DB state. Returns the alert
        transitions that fired."""
        now = time.time() if now is None else now
        self.ticks += 1
        cfg = self.cfg
        models = await Model.filter(limit=None)
        instances = await ModelInstance.filter(limit=None)

        by_model: Dict[int, List[ModelInstance]] = {}
        for inst in instances:
            by_model.setdefault(inst.model_id, []).append(inst)
        self._model_instances = {
            m.name: [i.id for i in by_model.get(m.id, [])]
            for m in models
        }

        self._active = set()
        # one histogram copy per tick, shared by every model's
        # error-rate/ttft extraction (snapshot() rebuilds cumulative
        # arrays for every labeled series — never per model)
        request_snap = get_registry("server").histogram(
            "gpustack_request_duration_seconds",
            label_names=("phase", "model", "outcome"),
        ).snapshot()
        queue_targets: Dict[str, float] = {}
        for model in models:
            self._feed_availability(model, by_model, now)
            self._feed_requests(model, request_snap, now)
            thr = resolve_target(
                model.slo_queue_wait_p95_ms,
                cfg.slo_default_queue_wait_p95_ms,
            )
            if thr is not None:
                queue_targets[model.name] = thr
        if queue_targets:
            await self._feed_queue_wait(queue_targets, now)
        else:
            self._last_engine_metrics = {}
        await self._feed_invariants(models, instances, now)
        self._feed_tenants(now)

        self.engine.retain(sorted(self._active), now)
        transitions = self.engine.evaluate(now)
        for t in transitions:
            logger.info(
                "slo alert: model=%s objective=%s %s -> %s burns=%s",
                t["model"], t["objective"], t["from"], t["to"],
                t["burns"],
            )
        return transitions

    # ---- signal feeds ----------------------------------------------------

    def _enable(self, model: str, spec: ObjectiveSpec) -> None:
        """Register a configured objective for this tick. Called even
        when the tick has no data for it — a tracker must survive a
        signal outage (its alert holds state) and retire only when
        the objective is disabled or the model deleted."""
        self.engine.set_objective(model, spec)
        self._active.add((model, spec.objective))

    def _feed_availability(
        self,
        model: Model,
        by_model: Dict[int, List[ModelInstance]],
        now: float,
    ) -> None:
        target = resolve_target(
            model.slo_availability, self.cfg.slo_default_availability
        )
        # serving_replicas(): role counts for a disaggregated model
        # (whose `replicas` field is ignored and may be 0), plain
        # `replicas` otherwise — the same denominator replica sync,
        # rollouts and the invariants converge toward
        replicas = model.serving_replicas()
        if target is None or replicas == 0:
            return
        running = sum(
            1
            for inst in by_model.get(model.id, [])
            if inst.state == ModelInstanceState.RUNNING
        )
        self._enable(
            model.name,
            ObjectiveSpec(
                "availability", target,
                description="RUNNING replicas / spec replicas "
                            "per evaluator tick",
            ),
        )
        self.engine.record_sample(
            model.name, "availability",
            min(running, replicas), replicas, now,
        )

    def _feed_requests(self, model: Model, snap, now: float) -> None:
        """error_rate + ttft from the server's cumulative request
        histogram snapshot (taken once per tick in evaluate_once)."""
        cfg = self.cfg
        error_budget = resolve_target(
            model.slo_error_rate, cfg.slo_default_error_rate
        )
        ttft_ms = resolve_target(
            model.slo_ttft_p95_ms, cfg.slo_default_ttft_p95_ms
        )
        if error_budget is None and ttft_ms is None:
            return
        err_good = err_total = 0
        ttft_good = ttft_total = 0
        ttft_s = (ttft_ms or 0.0) / 1000.0
        for (phase, m, outcome), (cum, _sum, count) in snap.items():
            if m != model.name:
                continue
            if phase == "total":
                err_total += count
                if outcome == "ok":
                    err_good += count
            elif phase == "ttft":
                ttft_total += count
                ttft_good += self._count_at_or_under(cum, ttft_s)
        if error_budget is not None:
            # an error budget >= 1 would be a degenerate always-good
            # objective; clamp into (0, 1)
            target = min(0.999999, max(1e-6, 1.0 - error_budget))
            self._enable(
                model.name,
                ObjectiveSpec(
                    "error_rate", target, threshold=error_budget,
                    description="proxy outcome=ok ratio "
                                "(phase=total)",
                ),
            )
            self.engine.record_cumulative(
                model.name, "error_rate", err_good, err_total, now,
            )
        if ttft_ms is not None:
            self._enable(
                model.name,
                ObjectiveSpec(
                    "ttft", LATENCY_GOOD_RATIO, threshold=ttft_ms,
                    description="requests with TTFT at-or-under "
                                "the threshold",
                ),
            )
            self.engine.record_cumulative(
                model.name, "ttft", ttft_good, ttft_total, now,
            )

    @staticmethod
    def _count_at_or_under(
        cum: List[Tuple[float, int]], threshold_s: float
    ) -> int:
        """Cumulative count of the largest bucket bound <= threshold
        (conservative: a threshold between bounds snaps down)."""
        good = 0
        for ub, count in cum:
            if ub <= threshold_s:
                good = count
            else:
                break
        return good

    async def _feed_queue_wait(
        self, targets: Dict[str, float], now: float
    ) -> None:
        """Sample each model's worst replica queue wait from READY
        workers' normalized engine series — the SAME scrape pipeline
        the fleet rollup uses (server/fleet.py), so this signal and
        ``GET /v2/debug/fleet`` cannot drift apart."""
        from gpustack_tpu.server.fleet import (
            scrape_normalized_samples,
        )

        workers = [
            w for w in await Worker.filter(limit=None)
            if w.state == WorkerState.READY
        ]
        inst_model = {
            str(iid): name
            for name, ids in self._model_instances.items()
            for iid in ids
        }
        _, samples = await scrape_normalized_samples(
            self.app, workers, inst_model
        )
        per_model: Dict[str, Dict[str, Dict]] = {}
        worst: Dict[str, float] = {}
        for (model, iid), metrics in samples.items():
            if not model:
                continue
            per_model.setdefault(model, {})[iid] = dict(
                sorted(metrics.items())
            )
            wait = metrics.get(QUEUE_WAIT_METRIC)
            if wait is not None:
                worst[model] = max(worst.get(model, 0.0), wait)
        self._last_engine_metrics = per_model
        for model, threshold_ms in targets.items():
            # always enabled while configured (the tracker must hold
            # its state through a scrape outage)...
            self._enable(
                model,
                ObjectiveSpec(
                    "queue_wait", LATENCY_GOOD_RATIO,
                    threshold=threshold_ms,
                    description="ticks with worst replica queue "
                                "wait at-or-under the threshold",
                ),
            )
            # ...but a tick only samples when the queue-wait gauge
            # itself was scraped: replicas that report other series
            # without it must read as no-data, not as zero wait
            if model not in worst:
                continue
            self.engine.record_sample(
                model, "queue_wait",
                1.0 if worst[model] * 1000.0 <= threshold_ms else 0.0,
                1.0, now,
            )

    async def _feed_invariants(
        self, models, instances, now: float
    ) -> None:
        target = self.cfg.slo_invariants_target
        if target <= 0:
            self._last_violations = []
            return
        from gpustack_tpu.schemas import DevInstance, Rollout
        from gpustack_tpu.testing import invariants as inv

        workers = await Worker.filter(limit=None)
        devs = await DevInstance.filter(limit=None)
        rollouts = await Rollout.filter(limit=None)
        violations = inv.snapshot_violations(
            models, workers, instances, devs,
            rollouts=rollouts,
            include_eventual=False,
        )
        self._last_violations = [v.to_dict() for v in violations]
        self._enable(
            CLUSTER_MODEL,
            ObjectiveSpec(
                "invariants", min(0.999999, target),
                description="ticks with zero always-scope "
                            "invariant violations",
            ),
        )
        self.engine.record_sample(
            CLUSTER_MODEL, "invariants",
            0.0 if violations else 1.0, 1.0, now,
        )

    def _feed_tenants(self, now: float) -> None:
        """Tenant-scoped shed objectives under pseudo-models
        ``tenant:<id>`` (server/tenancy.py): a tenant's admitted/shed
        cumulative counts become an error-budget objective, so a noisy
        neighbor burning through its quota fires THEIR burn alert —
        never ``_cluster``'s and never the model's. Bounded to the
        most recently active tenants (label cardinality is an operator
        budget, like model names)."""
        budget = self.cfg.slo_tenant_shed_budget
        tenancy = self.app.get("tenancy")
        if budget <= 0 or tenancy is None:
            return
        target = min(0.999999, max(1e-6, 1.0 - budget))
        for tenant, admitted, shed in tenancy.slo_samples(
            limit=self.cfg.slo_tenant_max_objectives
        ):
            model = f"{TENANT_MODEL_PREFIX}{tenant}"
            self._enable(
                model,
                ObjectiveSpec(
                    "tenant_shed", target, threshold=budget,
                    description="tenant requests admitted vs shed "
                                "(tenancy admission layer)",
                ),
            )
            self.engine.record_cumulative(
                model, "tenant_shed", admitted, admitted + shed, now,
            )

    # ---- evidence capture (sync; called inside engine.evaluate) ---------

    def _evidence(self, model: str, objective: str) -> Dict[str, Any]:
        """Correlated snapshot for an incident: trace exemplars,
        lifecycle timelines, last engine metrics, invariant report."""
        from gpustack_tpu.observability import tracing

        store = tracing.get_store("server")
        if model == CLUSTER_MODEL:
            traces = store.query(limit=5)
        else:
            # the model's own hops first; fall back to the slowest
            # recent traces so an incident never ships evidence-free
            traces = store.query(model=model, limit=5) or store.query(
                min_duration_ms=1.0, limit=3
            )
        timelines = []
        tracker = self.app.get("lifecycle")
        if tracker is not None:
            for iid in self._model_instances.get(model, [])[:8]:
                timeline = tracker.timeline(iid)
                if timeline is not None:
                    timelines.append(timeline)
        out: Dict[str, Any] = {
            "captured_at": time.time(),
            "traces": traces,
            "lifecycle": timelines,
        }
        engine_metrics = self._last_engine_metrics.get(model)
        if engine_metrics:
            out["engine_metrics"] = engine_metrics
        if model == CLUSTER_MODEL or self._last_violations:
            out["invariants"] = list(self._last_violations)
        return out

    # ---- reads -----------------------------------------------------------

    def status(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = time.time() if now is None else now
        out = self.engine.status(now)
        out["interval_seconds"] = self.interval
        out["ticks"] = self.ticks
        return out

    def metrics_lines(self) -> List[str]:
        return self.engine.metrics_lines(time.time())
