"""Cluster KV directory: which replica holds which prefix blocks.

The fleet half of the KV fabric (docs/KV_CACHE.md "Fleet KV fabric").
Every engine with a host KV cache keeps a bounded conversation index
(engine/kv_fabric.ConvIndex); a server-side refresh loop scrapes each
RUNNING instance's ``POST /kv/summary`` through the worker reverse
proxy and folds the result here: conversation-prefix hash →
``(instance, resident block depth, deepest RAM chain key)``.

The directory is deliberately APPROXIMATE and bounded:

- summaries are refreshed on a period (``kv_directory_refresh_s``), so
  an entry can say a replica holds blocks it just evicted — routing on
  it is an optimization, and the engine's radix walk is the ground
  truth (a stale hit degrades to a partial/cold prefill, counted as
  ``gpustack_kv_directory_stale_routes_total``);
- per-instance key counts are capped (``kv_directory_max_keys``), most
  recent conversations first;
- instances are dropped on exit from RUNNING / deletion — the same
  lifecycle hooks that invalidate :class:`PrefixAffinityMap` entries
  (ResilienceRegistry.watch drives both).

Routing on cached-prefix MASS: ``lookup(chain)`` walks a request's
conversation-prefix hashes deepest-first and returns the replica
holding the deepest (then largest) resident run — so a shared system
prompt used by thousands of tenants becomes a cross-replica hit even
though no replica ever saw this exact conversation.

The scrape is also the directory's write-back channel: each refresh
POSTs the fleet-wide sharing counts (hash → number of holding
replicas) to the engine, which folds them into its two-tier eviction
economics (bytes × recency / sharing) — widely-shared blocks outlive
single-tenant ones.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

DEFAULT_REFRESH_S = 5.0
DEFAULT_MAX_KEYS = 4096


class DirectoryHit:
    """One routing answer: the replica, how deep in the request's
    chain it matched, and its advertised residency."""

    __slots__ = ("instance_id", "model_id", "depth", "blocks", "tail")

    def __init__(self, instance_id, model_id, depth, blocks, tail):
        self.instance_id = instance_id
        self.model_id = model_id
        self.depth = depth
        self.blocks = blocks
        self.tail = tail


class _Replica:
    __slots__ = ("model_id", "keys", "refreshed_at", "conversations")

    def __init__(self, model_id: int):
        self.model_id = model_id
        # hash -> (blocks, tail hex)
        self.keys: Dict[str, Tuple[int, str]] = {}
        self.refreshed_at = 0.0
        self.conversations = 0


class ClusterKVDirectory:
    """Bounded approximate fleet index of prefix-key residency."""

    def __init__(
        self,
        max_keys_per_instance: int = DEFAULT_MAX_KEYS,
        clock=time.monotonic,
    ):
        self.max_keys_per_instance = max(16, int(max_keys_per_instance))
        self._clock = clock
        self._replicas: Dict[int, _Replica] = {}
        # counters (server /metrics via resilience metrics_lines)
        self.refreshes = 0
        self.refresh_failures = 0
        self.invalidations = 0
        self.hits = 0
        self.misses = 0
        self.stale_routes = 0
        self.prefetches = 0

    # ---- feed ------------------------------------------------------------

    def update(
        self, instance_id: int, model_id: int, summary: dict
    ) -> int:
        """Fold one replica's scraped summary in. Returns the key
        count retained (bounded — deepest runs win past the cap)."""
        keys = summary.get("keys") or {}
        rep = _Replica(model_id)
        items: List[Tuple[str, Tuple[int, str]]] = []
        for h, entry in keys.items():
            try:
                blocks = int(entry.get("blocks") or 0)
            except (AttributeError, TypeError, ValueError):
                continue
            if blocks <= 0:
                continue
            items.append((str(h), (blocks, str(entry.get("tail") or ""))))
        if len(items) > self.max_keys_per_instance:
            items.sort(key=lambda kv: kv[1][0], reverse=True)
            items = items[: self.max_keys_per_instance]
        rep.keys = dict(items)
        rep.refreshed_at = self._clock()
        try:
            rep.conversations = int(summary.get("conversations") or 0)
        except (TypeError, ValueError):
            rep.conversations = 0
        self._replicas[instance_id] = rep
        self.refreshes += 1
        return len(rep.keys)

    def invalidate_instance(self, instance_id: int) -> int:
        """Instance left RUNNING (or was deleted): its engine — and
        every block it advertised — is gone."""
        rep = self._replicas.pop(instance_id, None)
        if rep is None:
            return 0
        self.invalidations += 1
        return len(rep.keys)

    # ---- routing ---------------------------------------------------------

    def lookup(
        self,
        chain: Sequence[str],
        candidate_ids=None,
    ) -> Optional[DirectoryHit]:
        """Deepest-prefix-first: the first chain hash (walking from
        the newest message prefix down) that ANY replica advertises
        wins; among holders of that hash the largest resident run
        wins. ``candidate_ids`` (when given) restricts holders to the
        dialable serving set. ONE hit or miss counted per call."""
        best: Optional[DirectoryHit] = None
        for depth in range(len(chain) - 1, -1, -1):
            h = chain[depth]
            for iid, rep in self._replicas.items():
                if candidate_ids is not None and iid not in candidate_ids:
                    continue
                entry = rep.keys.get(h)
                if entry is None:
                    continue
                if best is None or entry[0] > best.blocks:
                    best = DirectoryHit(
                        iid, rep.model_id, depth, entry[0], entry[1]
                    )
            if best is not None:
                break
        if best is None:
            self.misses += 1
        else:
            self.hits += 1
        return best

    # ---- fleet aggregates ------------------------------------------------

    def sharing(self, model_id: Optional[int] = None) -> Dict[str, int]:
        """hash → number of replicas advertising it (the eviction-
        economics boost shipped back to engines on the next scrape)."""
        counts: Dict[str, int] = {}
        for rep in self._replicas.values():
            if model_id is not None and rep.model_id != model_id:
                continue
            for h in rep.keys:
                counts[h] = counts.get(h, 0) + 1
        return counts

    def instance_keys(self, instance_id: int) -> Dict[str, Tuple[int, str]]:
        rep = self._replicas.get(instance_id)
        return dict(rep.keys) if rep else {}

    @property
    def instances(self) -> int:
        return len(self._replicas)

    @property
    def total_keys(self) -> int:
        return sum(len(r.keys) for r in self._replicas.values())

    def snapshot(self) -> Dict[str, int]:
        return {
            "instances": self.instances,
            "keys": self.total_keys,
            "refreshes": self.refreshes,
            "refresh_failures": self.refresh_failures,
            "invalidations": self.invalidations,
            "hits": self.hits,
            "misses": self.misses,
            "stale_routes": self.stale_routes,
            "prefetches": self.prefetches,
        }

    def metrics_lines(self) -> List[str]:
        return [
            "# TYPE gpustack_kv_directory_instances gauge",
            f"gpustack_kv_directory_instances {self.instances}",
            "# TYPE gpustack_kv_directory_keys gauge",
            f"gpustack_kv_directory_keys {self.total_keys}",
            "# TYPE gpustack_kv_directory_refreshes_total counter",
            f"gpustack_kv_directory_refreshes_total {self.refreshes}",
            "# TYPE gpustack_kv_directory_refresh_failures_total counter",
            f"gpustack_kv_directory_refresh_failures_total "
            f"{self.refresh_failures}",
            "# TYPE gpustack_kv_directory_invalidations_total counter",
            f"gpustack_kv_directory_invalidations_total "
            f"{self.invalidations}",
            "# TYPE gpustack_kv_directory_hits_total counter",
            f"gpustack_kv_directory_hits_total {self.hits}",
            "# TYPE gpustack_kv_directory_misses_total counter",
            f"gpustack_kv_directory_misses_total {self.misses}",
            "# TYPE gpustack_kv_directory_stale_routes_total counter",
            f"gpustack_kv_directory_stale_routes_total "
            f"{self.stale_routes}",
            "# TYPE gpustack_kv_directory_prefetches_total counter",
            f"gpustack_kv_directory_prefetches_total {self.prefetches}",
        ]


# ---------------------------------------------------------------------------
# Server-side refresh loop + drain-time prefetch
# ---------------------------------------------------------------------------


async def _kv_capable_instances():
    """(instance, model) pairs whose engines run a host KV cache —
    the only replicas with anything to summarize."""
    from gpustack_tpu.schemas import (
        Model,
        ModelInstance,
        ModelInstanceState,
    )

    out = []
    models = {m.id: m for m in await Model.all()}
    for inst in await ModelInstance.filter(
        state=ModelInstanceState.RUNNING
    ):
        model = models.get(inst.model_id or 0)
        if model is None or not model.host_kv_cache_mb:
            continue
        out.append((inst, model))
    return out


async def refresh_directory_once(app, directory) -> int:
    """One scrape round: POST each KV-capable RUNNING instance's
    /kv/summary (carrying the current fleet sharing counts down),
    fold the returned summaries in. Per-instance failures count and
    skip — one wedged worker must not starve the rest of the fleet's
    refresh. Returns instances refreshed."""
    import aiohttp

    from gpustack_tpu.schemas import Worker

    session = app.get("proxy_session")
    if session is None or session.closed:
        return 0
    cfg = app.get("config")
    max_keys = int(
        getattr(cfg, "kv_directory_max_keys", DEFAULT_MAX_KEYS)
    )
    refreshed = 0
    for inst, model in await _kv_capable_instances():
        worker = await Worker.get(inst.worker_id or 0)
        if worker is None or not worker.ip or not worker.port:
            continue
        url = (
            f"http://{worker.ip}:{worker.port}"
            f"/proxy/instances/{inst.id}/kv/summary"
        )
        headers = {}
        if worker.proxy_secret:
            headers["Authorization"] = f"Bearer {worker.proxy_secret}"
        try:
            async with session.post(
                url,
                json={
                    "sharing": directory.sharing(model.id),
                    "max_keys": max_keys,
                },
                headers=headers,
                timeout=aiohttp.ClientTimeout(total=5.0),
            ) as resp:
                if resp.status != 200:
                    raise RuntimeError(f"HTTP {resp.status}")
                summary = await resp.json()
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — per-replica fault
            directory.refresh_failures += 1
            logger.debug(
                "kv directory refresh of instance %s failed: %s",
                inst.id, str(e) or type(e).__name__,
            )
            continue
        directory.update(inst.id, model.id, summary)
        refreshed += 1
        # affinity-staleness fix: an entry steering turns at this
        # replica for a conversation whose blocks EVICTED is worse
        # than a directory lookup — demote it now, on eviction
        # evidence, not only on instance exit
        reg = app.get("resilience")
        if reg is not None:
            reg.affinity.demote_stale(
                inst.id, set((summary.get("keys") or {}).keys())
            )
    return refreshed


async def directory_refresh_loop(app, directory) -> None:
    """The background scrape: period from ``kv_directory_refresh_s``.
    Transient failures (DB, worker, decode) never kill the loop."""
    cfg = app.get("config")
    interval = float(
        getattr(cfg, "kv_directory_refresh_s", DEFAULT_REFRESH_S)
    )
    while True:
        try:
            await refresh_directory_once(app, directory)
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("kv directory refresh round failed")
        await asyncio.sleep(max(0.5, interval))


async def prefetch_for_drain(
    app, directory, instance_id: int, keys=None, limit: int = 0
) -> int:
    """Drain-time warm-ahead: the draining replica's hottest
    conversations (largest advertised runs) are pulled to the
    least-outstanding RUNNING sibling BEFORE the engine exits — turn
    N+1 lands warm instead of re-prefilling the fleet's hottest
    prefixes. Advisory end to end: any failure leaves the fleet cold,
    never blocks the drain. Returns pulls triggered."""
    import aiohttp

    from gpustack_tpu.api.auth import mint_kv_token
    from gpustack_tpu.schemas import (
        ModelInstance,
        ModelInstanceState,
        Worker,
    )

    cfg = app.get("config")
    if limit <= 0:
        limit = int(getattr(cfg, "kv_prefetch_conversations", 0))
    if limit <= 0:
        return 0
    if keys is None:
        # callers on the DRAINING edge snapshot keys BEFORE the
        # directory drops the instance; direct callers let us look
        keys = directory.instance_keys(instance_id)
    if not keys:
        return 0
    src = await ModelInstance.get(instance_id)
    if src is None:
        return 0
    model_id = src.model_id or 0
    src_worker = await Worker.get(src.worker_id or 0)
    if src_worker is None or not src_worker.ip or not src_worker.port:
        return 0
    # target: the least-outstanding RUNNING sibling (skip the drainer)
    reg = app.get("resilience")
    siblings = [
        i for i in await ModelInstance.filter(
            model_id=model_id, state=ModelInstanceState.RUNNING
        )
        if i.id != instance_id
    ]
    if not siblings or reg is None:
        return 0
    target = reg.order(siblings)[0]
    dst_worker = await Worker.get(target.worker_id or 0)
    if dst_worker is None or not dst_worker.ip or not dst_worker.port:
        return 0
    session = app.get("proxy_session")
    if session is None or session.closed:
        return 0
    # deepest advertised runs first; dedup by tail key (many
    # conversation-prefix hashes share one deepest block)
    ranked = sorted(
        keys.items(), key=lambda kv: kv[1][0], reverse=True
    )
    source_url = (
        f"http://{src_worker.ip}:{src_worker.port}"
        f"/proxy/instances/{src.id}/kv/export"
    )
    ttl = float(getattr(cfg, "kv_token_ttl", 60.0))
    auth = ""
    if src_worker.proxy_secret:
        auth = "Bearer " + mint_kv_token(
            src_worker.proxy_secret, src.id, ttl
        )
    headers = {}
    if dst_worker.proxy_secret:
        headers["Authorization"] = (
            f"Bearer {dst_worker.proxy_secret}"
        )
    pull_url = (
        f"http://{dst_worker.ip}:{dst_worker.port}"
        f"/proxy/instances/{target.id}/kv/pull"
    )
    triggered = 0
    seen_tails = set()
    for _h, (_blocks, tail) in ranked:
        if triggered >= limit:
            break
        if not tail or tail in seen_tails:
            continue
        seen_tails.add(tail)
        try:
            async with session.post(
                pull_url,
                json={
                    "source": source_url,
                    "auth": auth,
                    "tail_key": tail,
                },
                headers=headers,
                timeout=aiohttp.ClientTimeout(total=5.0),
            ) as resp:
                if resp.status not in (200, 202):
                    raise RuntimeError(f"HTTP {resp.status}")
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — advisory
            logger.debug(
                "drain prefetch %s -> %s failed: %s",
                instance_id, target.id, str(e) or type(e).__name__,
            )
            continue
        triggered += 1
        directory.prefetches += 1
    if triggered:
        logger.info(
            "drain prefetch: %d conversation(s) of instance %s "
            "pulled ahead to instance %s", triggered, instance_id,
            target.id,
        )
    return triggered
