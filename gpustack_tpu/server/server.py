"""Server bootstrap (reference gpustack/server/server.py:254 Server.start):
migrations → data init (admin user, default cluster, backend catalog) →
app → leader tasks (controllers, scheduler, syncer) → HTTP site →
optional embedded worker.

The embedded worker runs as an asyncio task in-process talking to
localhost over HTTP — same contract as a remote worker (the reference
spawns a multiprocessing.Process instead, cmd/start.py:736-755; our engine
processes are the true process boundary)."""

from __future__ import annotations

import asyncio
import logging
import os
import secrets
from typing import List, Optional

from aiohttp import web

from gpustack_tpu.api import auth as auth_mod
from gpustack_tpu.config import Config
from gpustack_tpu.orm.db import Database, run_migrations
from gpustack_tpu.orm.record import Record
from gpustack_tpu.scheduler.scheduler import Scheduler
from gpustack_tpu.schemas import Cluster, InferenceBackend, User
from gpustack_tpu.schemas.inference_backends import BackendVersionConfig
from gpustack_tpu.server.app import create_app
from gpustack_tpu.server.bus import EventBus
from gpustack_tpu.server.controllers import (
    InstanceRescuer,
    ModelController,
    ModelProviderController,
    WorkerController,
    WorkerSyncer,
)

logger = logging.getLogger(__name__)


BUILTIN_BACKEND = InferenceBackend(
    name="tpu-native",
    description="Built-in JAX/XLA serving engine (gpustack_tpu.engine)",
    builtin=True,
    versions=[
        BackendVersionConfig(
            version="latest",
            command=[
                "{python}", "-m", "gpustack_tpu.engine.api_server",
                "--port", "{port}",
                "--served-name", "{served_name}",
                "--max-seq-len", "{max_seq_len}",
                "--max-slots", "{max_slots}",
            ],
            health_path="/healthz",
        )
    ],
)


class Server:
    def __init__(self, cfg: Config):
        self.cfg = cfg
        self.db: Optional[Database] = None
        self.bus = EventBus()
        self._tasks: List = []
        self._runner: Optional[web.AppRunner] = None
        self._stop = asyncio.Event()
        self.worker_agent = None

    async def start(self) -> None:
        cfg = self.cfg
        self.db = Database(cfg.database_path)
        run_migrations(self.db)
        # record classes register at module import; collector-owned
        # tables (resource_event, system_load, usage_archive) must be
        # registered BEFORE create_all_tables or they silently miss
        import gpustack_tpu.server.collectors  # noqa: F401
        Record.bind(self.db, self.bus)
        # context-local binding too: the in-process multi-server chaos
        # harness boots several Servers in one process — every task this
        # coroutine spawns (coordinator, controllers, HTTP accept path)
        # inherits THIS server's db/bus instead of whichever server
        # bound last; request handlers additionally re-bind via the app
        # middleware below
        Record.bind_context(self.db, self.bus)
        Record.create_all_tables(self.db)
        if not cfg.ha:
            # HA: bootstrap writes are leader-only (racing get-or-create
            # on a shared DB would duplicate the admin user/cluster)
            await self._init_data()

        app = create_app(cfg)
        self.app = app
        app["record_binding"] = (self.db, self.bus)
        # bounded shutdown: a restart must not hang behind long-lived
        # watch/log-follow streams (chaos finding: the default 60 s
        # connection drain made restart-mid-reconcile a minute-long
        # op). On the runner, not the site — the site-level parameter
        # is deprecated in aiohttp 3.11.
        self._runner = web.AppRunner(
            app, shutdown_timeout=cfg.shutdown_timeout
        )
        await self._runner.setup()
        site = web.TCPSite(self._runner, cfg.host, cfg.port)

        # leader-only tasks gate on the coordinator (reference
        # server/server.py:1256-1339): LocalCoordinator for single-server,
        # LeaseCoordinator for shared-DB HA
        from gpustack_tpu.server.coordinator import (
            LeaseCoordinator,
            LocalCoordinator,
        )

        # a plugin may supply the coordinator (reference: distributed
        # coordinators ship as plugins, server/server.py:1166-1194)
        plugin_coordinator = None
        for plugin in app.get("plugins", []):
            try:
                plugin_coordinator = plugin.coordinator(cfg)
            except Exception:
                logger.exception(
                    "plugin %s coordinator() failed",
                    plugin.name or type(plugin),
                )
            if plugin_coordinator is not None:
                break
        self.coordinator = plugin_coordinator or (
            LeaseCoordinator(self.db, bus=self.bus, ttl=cfg.ha_ttl)
            if cfg.ha else LocalCoordinator()
        )
        if cfg.ha:
            # replicate every post-commit event to HA peers through the
            # shared change_log table (id-only; peers re-fetch). A sync
            # bus tap: publish_remote only enqueues.
            self.bus.add_tap(self.coordinator.publish_remote)
        from gpustack_tpu.cloud.controller import WorkerPoolController

        from gpustack_tpu.server.controllers import RouteTargetController

        self.controllers = [
            ModelController(),
            ModelProviderController(),
            RouteTargetController(),
            WorkerController(),
            WorkerPoolController(
                server_url=cfg.advertised_url
                or f"http://{cfg.host}:{cfg.port}",
                registration_token=cfg.registration_token,
            ),
        ]
        self.scheduler = Scheduler()
        self.syncer = WorkerSyncer(
            stale_after=cfg.heartbeat_interval * 4.5,
            interval=cfg.heartbeat_interval,
            # degraded-mode safety: heartbeats this server has SEEN but
            # not yet flushed must never read as stale (the combiner's
            # in-memory freshness map is ahead of the DB by design)
            freshness_source=app["write_combiner"].freshness_for,
        )
        self.rescuer = InstanceRescuer(
            grace=cfg.unreachable_rescue_after,
            interval=cfg.heartbeat_interval,
        )

        from gpustack_tpu.server.collectors import (
            ResourceEventLogger,
            SystemLoadCollector,
            UsageArchiver,
        )

        # heartbeat/status write combiner (constructed in create_app so
        # unit mounts have the debug/metrics surface): flushes on every
        # server, leader or follower — heartbeats land wherever the
        # load balancer sends them
        self.write_combiner = app["write_combiner"]
        self.write_combiner.start()
        # reload-config propagates rotated tokens/URLs into controllers
        # that copied them at construction (routes/extras.py)
        app["controllers"] = self.controllers
        self.usage_archiver = UsageArchiver()
        self.resource_events = ResourceEventLogger()
        self.system_load = SystemLoadCollector()
        from gpustack_tpu.server.sloeval import SLOEvaluator

        # per-model SLO engine: burn-rate alerting + incident ring
        # (observability/slo.py). Constructed unconditionally so the
        # /v2/debug/slo surface and /metrics families exist on every
        # server; evaluation ticks are leader-only like the other
        # collectors (two HA peers double-judging would double-count
        # availability samples).
        self.slo_evaluator = SLOEvaluator(app, cfg)
        app["slo"] = self.slo_evaluator
        from gpustack_tpu.server.autoscaler import Autoscaler
        from gpustack_tpu.server.rollout import RolloutController

        # rollouts + autoscaling consume the SLO/fleet signals above;
        # constructed always (debug surfaces + manual rollback need
        # them on every server), reconcile ticks leader-only
        self.rollout_controller = RolloutController(app, cfg)
        app["rollout"] = self.rollout_controller
        self.autoscaler = Autoscaler(app, cfg)
        app["autoscaler"] = self.autoscaler
        from gpustack_tpu.server.update_check import UpdateChecker

        self.update_checker = UpdateChecker()
        self.update_checker.start()  # no-op without GPUSTACK_TPU_UPDATE_URL

        from gpustack_tpu.server.backend_catalog import BackendCatalogSync

        self.backend_catalog = BackendCatalogSync(
            cfg.backend_catalog_url
            or os.environ.get("GPUSTACK_TPU_BACKEND_CATALOG", "")
        )

        async def on_leadership(leading: bool) -> None:
            if leading:
                if cfg.ha:
                    if cfg.ha_epoch_fence and getattr(
                        self.coordinator, "epoch", 0
                    ):
                        # stamp this context with the acquired epoch
                        # BEFORE starting leader-only tasks: every task
                        # below inherits it, so their writes reject
                        # atomically once a successor bumps the lease
                        # epoch (orm/fencing.py)
                        from gpustack_tpu.orm import fencing

                        fencing.set_fence(self.coordinator.epoch)
                    await self._init_data()
                for c in self.controllers:
                    c.start()
                self.scheduler.start()
                self.syncer.start()
                self.rescuer.start()
                self.usage_archiver.start()
                self.resource_events.start()
                self.system_load.start()
                self.backend_catalog.start()
                self.slo_evaluator.start()
                self.rollout_controller.start()
                self.autoscaler.start()

        self.coordinator.on_leadership_change(on_leadership)
        await self.coordinator.start()
        app["coordinator"] = self.coordinator

        await site.start()
        logger.info("server listening on %s:%d", cfg.host, cfg.port)

        if not cfg.disable_worker:
            from gpustack_tpu.worker.worker import WorkerAgent

            worker_cfg = cfg.model_copy()
            worker_cfg.server_url = f"http://127.0.0.1:{cfg.port}"
            self.worker_agent = WorkerAgent(worker_cfg)
            worker_task = asyncio.create_task(
                self.worker_agent.start(), name="embedded-worker"
            )

            def _on_worker_done(t: asyncio.Task) -> None:
                # An embedded worker that dies at startup (e.g. its HTTP
                # port is already taken) must be LOUD: round-3 postmortem
                # was an entire e2e tier red with zero diagnostics
                # because this task swallowed its exception. Log it and
                # flip /healthz to degraded so operators and tests see it.
                if t.cancelled():
                    return
                exc = t.exception()
                if exc is not None:
                    logger.error(
                        "embedded worker died during startup: %s", exc,
                        exc_info=exc,
                    )
                    app["embedded_worker_error"] = repr(exc)

            worker_task.add_done_callback(_on_worker_done)
            self._tasks.append(worker_task)

    async def run_forever(self) -> None:
        await self.start()
        await self._stop.wait()

    async def stop(self) -> None:
        await self._shutdown(release_lease=True)

    async def abort(self) -> None:
        """Hard stop without releasing the leadership lease — the fatal
        path (lost lease) and the chaos harness's leader-kill both come
        through here. A crashed leader deletes nothing: its lease row
        must EXPIRE before a follower may acquire, which is exactly the
        failover the TTL contract promises."""
        await self._shutdown(release_lease=False)

    async def _shutdown(self, release_lease: bool) -> None:
        if self.worker_agent:
            await self.worker_agent.stop()
        if hasattr(self, "coordinator"):
            halt = getattr(self.coordinator, "halt", None)
            if release_lease or halt is None:
                await self.coordinator.stop()
            else:
                await halt()
        for c in getattr(self, "controllers", []):
            c.stop()
        if hasattr(self, "scheduler"):
            self.scheduler.stop()
        if hasattr(self, "syncer"):
            self.syncer.stop()
        if hasattr(self, "rescuer"):
            self.rescuer.stop()
        if hasattr(self, "write_combiner"):
            # shared drain contract: buffered heartbeat/status writes
            # land now or fail LOUDLY with the same typed error a
            # write queued behind Database.close() gets
            try:
                await self.write_combiner.drain()
            except Exception:
                logger.exception(
                    "write combiner drain dropped buffered writes"
                )
        if hasattr(self, "usage_archiver"):
            self.usage_archiver.stop()
        if hasattr(self, "update_checker"):
            self.update_checker.stop()
        if hasattr(self, "backend_catalog"):
            self.backend_catalog.stop()
        if hasattr(self, "resource_events"):
            self.resource_events.stop()
        if hasattr(self, "system_load"):
            self.system_load.stop()
        if hasattr(self, "slo_evaluator"):
            self.slo_evaluator.stop()
        if hasattr(self, "rollout_controller"):
            self.rollout_controller.stop()
        if hasattr(self, "autoscaler"):
            self.autoscaler.stop()
        for t in self._tasks:
            t.cancel()
        if self._runner:
            await self._runner.cleanup()
        if self.db:
            self.db.close()
        self._stop.set()

    # ------------------------------------------------------------------

    async def _init_data(self) -> None:
        """Admin user, default cluster, builtin backend catalog (reference
        server/server.py:714-1141 _init_data)."""
        cfg = self.cfg
        admin = await User.first(username="admin")
        if admin is None:
            password = cfg.bootstrap_password or secrets.token_urlsafe(12)
            await User.create(
                User(
                    username="admin",
                    is_admin=True,
                    password_hash=auth_mod.hash_password(password),
                    require_password_change=not cfg.bootstrap_password,
                )
            )
            if not cfg.bootstrap_password:
                logger.warning("generated admin password: %s", password)

        cluster = await Cluster.first()
        if cluster is None:
            await Cluster.create(
                Cluster(
                    name="default",
                    registration_token_hash=auth_mod.hash_secret(
                        cfg.registration_token
                    ),
                )
            )
        else:
            # keep the persisted token authoritative across restarts
            expected = auth_mod.hash_secret(cfg.registration_token)
            if cluster.registration_token_hash != expected:
                await cluster.update(registration_token_hash=expected)

        backend = await InferenceBackend.first(name="tpu-native")
        if backend is None:
            b = BUILTIN_BACKEND.model_copy(deep=True)
            await InferenceBackend.create(b)
