"""Shared READY-worker metrics scrape.

Both the fleet saturation rollup (``GET /v2/debug/fleet``,
routes/extras.py) and the SLO evaluator's queue-wait feed
(server/sloeval.py) read the workers' normalized ``gpustack_tpu:*``
engine series; this is the ONE implementation of that scrape so the
two surfaces cannot drift apart (same histogram-series exclusion,
same instance→model resolution, same ``name|kind`` folding for
kind-labeled counters).
"""

from __future__ import annotations

import asyncio
from typing import Dict, Optional, Tuple

import aiohttp


async def scrape_normalized_samples(
    app,
    workers,
    inst_model: Dict[str, str],
) -> Tuple[
    Dict[int, dict], Dict[Tuple[str, str], Dict[str, float]]
]:
    """Scrape each worker's ``/metrics`` concurrently.

    Returns ``(workers_out, samples)``:

    - ``workers_out[worker.id]`` = ``{"name", "reachable", "error"?}``;
    - ``samples[(model, instance_id)][metric]`` = value, where
      ``metric`` is the normalized name, suffixed ``|<kind>`` when the
      sample carries a ``kind`` label. Histogram series
      (``_bucket``/``_sum``/``_count``) stay per-engine and are
      excluded — the rollup doesn't merge them, and keying them by
      bare name would fold per-mode series into one value. ``model``
      is ``""`` when neither the series label nor ``inst_model``
      resolves it — callers decide whether to skip or bucket those.
    """
    from gpustack_tpu.server.worker_request import worker_fetch
    from gpustack_tpu.worker.metrics_map import (
        NORMALIZED_PREFIX,
        parse_metric_line,
    )

    async def scrape(w):
        try:
            resp = await worker_fetch(
                app, w, "GET", "/metrics", control=True,
            )
            try:
                return w, (await resp.read()).decode(
                    errors="replace"
                ), ""
            finally:
                resp.release()
        except (
            aiohttp.ClientError, OSError, asyncio.TimeoutError,
        ) as e:
            return w, None, str(e)[:200]

    workers_out: Dict[int, dict] = {}
    samples: Dict[Tuple[str, str], Dict[str, float]] = {}
    # concurrent: one partitioned worker must cost the scrape its own
    # timeout, not a per-worker serial sum
    for w, body, err in await asyncio.gather(
        *(scrape(w) for w in workers)
    ):
        if body is None:
            workers_out[w.id] = {
                "name": w.name, "reachable": False, "error": err,
            }
            continue
        workers_out[w.id] = {"name": w.name, "reachable": True}
        for line in body.splitlines():
            parsed = parse_metric_line(line)
            if parsed is None:
                continue
            name, labels, value = parsed
            if not name.startswith(NORMALIZED_PREFIX):
                continue
            if "le" in labels or name.endswith(
                ("_bucket", "_sum", "_count")
            ):
                continue
            iid = labels.get("instance_id", "")
            model = labels.get("model") or inst_model.get(iid) or ""
            try:
                val = float(value)
            except ValueError:
                continue
            kind: Optional[str] = labels.get("kind")
            metric = f"{name}|{kind}" if kind else name
            samples.setdefault((model, iid), {})[metric] = val
    return workers_out, samples
