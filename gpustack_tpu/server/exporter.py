"""Server Prometheus exporter: DB-derived cluster gauges + bus counters.

Reference parity: gpustack/exporter/exporter.py:32-56 (cluster/worker/model
gauges recomputed on scrape with a small cache) + exporter/bus_metrics.py
(bus publish counters)."""

from __future__ import annotations

import time
from typing import List

from aiohttp import web

from gpustack_tpu.schemas import (
    Model,
    ModelInstance,
    Worker,
    WorkerState,
)

_CACHE_TTL = 3.0


class ServerExporter:
    def __init__(self, bus=None):
        self._bus = bus
        self._cache: str = ""
        self._cached_at: float = 0.0

    @property
    def bus(self):
        if self._bus is not None:
            return self._bus
        from gpustack_tpu.orm.record import Record

        return Record.bus()

    async def metrics_text(self) -> str:
        now = time.monotonic()
        if self._cache and now - self._cached_at < _CACHE_TTL:
            return self._cache
        lines: List[str] = []

        workers = await Worker.all()
        ready = [w for w in workers if w.state == WorkerState.READY]
        total_chips = sum(w.total_chips for w in workers)
        lines += [
            "# TYPE gpustack_workers gauge",
            f'gpustack_workers{{state="ready"}} {len(ready)}',
            f'gpustack_workers{{state="other"}} {len(workers) - len(ready)}',
            "# TYPE gpustack_tpu_chips_total gauge",
            f"gpustack_tpu_chips_total {total_chips}",
        ]

        instances = await ModelInstance.all()
        by_state: dict = {}
        used_chips = 0
        for inst in instances:
            by_state[inst.state.value] = by_state.get(inst.state.value, 0) + 1
            if inst.state.value in (
                "running", "starting", "scheduled", "draining"
            ):
                used_chips += len(inst.chip_indexes)
                for sub in inst.subordinate_workers:
                    used_chips += len(sub.chip_indexes)
        lines.append("# TYPE gpustack_model_instances gauge")
        for state, count in sorted(by_state.items()):
            lines.append(
                f'gpustack_model_instances{{state="{state}"}} {count}'
            )
        lines += [
            "# TYPE gpustack_tpu_chips_used gauge",
            f"gpustack_tpu_chips_used {used_chips}",
            "# TYPE gpustack_models gauge",
            f"gpustack_models {len(await Model.all())}",
        ]

        # SQL aggregate: the usage table grows one row per request; never
        # materialize it for a scrape
        from gpustack_tpu.orm.record import Record

        db = Record.db()
        rows = await db.execute(
            "SELECT COUNT(*) AS n, "
            f"COALESCE(SUM({db.json_num('total_tokens')}), 0) AS tok "
            "FROM model_usage"
        )
        lines += [
            "# TYPE gpustack_usage_total_tokens counter",
            f"gpustack_usage_total_tokens {int(rows[0]['tok'])}",
            "# TYPE gpustack_usage_requests counter",
            f"gpustack_usage_requests {int(rows[0]['n'])}",
        ]

        lines.append("# TYPE gpustack_bus_events_published counter")
        for (kind, etype), count in sorted(self.bus.published.items()):
            lines.append(
                f'gpustack_bus_events_published{{kind="{kind}",'
                f'type="{etype}"}} {count}'
            )
        self._cache = "\n".join(lines) + "\n"
        self._cached_at = now
        return self._cache


def add_metrics_route(app: web.Application) -> None:
    exporter = ServerExporter()

    async def metrics(request: web.Request):
        text = await exporter.metrics_text()
        # data-plane resilience counters (failovers/shed/breaker state)
        # live in the per-app registry, not the DB — append uncached
        registry = request.app.get("resilience")
        if registry is not None:
            text += "\n".join(registry.metrics_lines()) + "\n"
        # tenant QoS admission/shed/token series (server/tenancy.py) —
        # per-tenant labels, bounded to the busiest N + "_other"
        tenancy = request.app.get("tenancy")
        if tenancy is not None:
            text += "\n".join(tenancy.metrics_lines()) + "\n"
        # observability histograms (per-phase request latency, instance
        # time-in-state) + slow-call stats (utils/profiling.CallStats,
        # recorded by @timed call sites) — in-memory, appended uncached
        from gpustack_tpu.observability.metrics import (
            get_registry,
            slow_call_lines,
        )

        obs_lines = get_registry("server").render_lines()
        obs_lines += slow_call_lines()
        # control-plane HA: election + fencing state (coordinator.py /
        # orm/fencing.py) — always rendered so dashboards don't gap
        # when a server runs single-node (LocalCoordinator: leader=1,
        # epoch=0, transitions=0)
        coordinator = request.app.get("coordinator")
        if coordinator is not None:
            from gpustack_tpu.observability.metrics import (
                METRIC_FAMILIES,
            )
            from gpustack_tpu.orm import fencing

            obs_lines += [
                "# TYPE gpustack_ha_is_leader "
                f"{METRIC_FAMILIES['gpustack_ha_is_leader']}",
                "gpustack_ha_is_leader "
                f"{1 if coordinator.is_leader else 0}",
                "# TYPE gpustack_ha_epoch "
                f"{METRIC_FAMILIES['gpustack_ha_epoch']}",
                "gpustack_ha_epoch "
                f"{getattr(coordinator, 'epoch', 0)}",
                "# TYPE gpustack_ha_leader_transitions_total "
                f"{METRIC_FAMILIES['gpustack_ha_leader_transitions_total']}",
                "gpustack_ha_leader_transitions_total "
                f"{getattr(coordinator, 'transitions', 0)}",
                "# TYPE gpustack_ha_fenced_writes_total "
                f"{METRIC_FAMILIES['gpustack_ha_fenced_writes_total']}",
                "gpustack_ha_fenced_writes_total "
                f"{fencing.fenced_writes_total()}",
            ]
        # control-plane write combiner: pressure ladder + coalescing
        # counters (server/write_combiner.py)
        combiner = request.app.get("write_combiner")
        if combiner is not None:
            obs_lines += combiner.metrics_lines()
        # SLO engine gauges (compliance / burn rate / alert state) —
        # in-memory judgment over the series above, appended uncached
        slo = request.app.get("slo")
        if slo is not None:
            obs_lines += slo.metrics_lines()
        # rollout / autoscaler gauges (their event counters render
        # through the shared registry above)
        for key in ("rollout", "autoscaler"):
            component = request.app.get(key)
            if component is not None:
                obs_lines += component.metrics_lines()
        if obs_lines:
            text += "\n".join(obs_lines) + "\n"
        return web.Response(text=text)

    app.router.add_get("/metrics", metrics)
