"""Data-plane resilience: circuit breakers, health view, replica selection.

The reference delegates request-path resilience to its Envoy/Higress
gateway (outlier detection, retries, connection limits — PAPER.md §1);
with an in-process gateway we own that layer ourselves. One
``ResilienceRegistry`` per server app holds:

- a per-instance **circuit breaker** (closed → open → half-open with a
  jittered probe window and exponential re-open backoff),
- an **outstanding-request count** per instance, used for
  least-outstanding-requests replica selection (replacing the blind
  round-robin the proxy shipped with),
- a per-model outstanding total for **load shedding** (429 +
  ``Retry-After`` instead of queueing unboundedly),
- Prometheus-style counters surfaced through the server's existing
  ``/metrics`` exporter (``gpustack_proxy_failovers_total``,
  ``gpustack_proxy_shed_total``, ``gpustack_proxy_breaker_state``, …).

The view is fed from two directions: proxy outcomes
(``record_success``/``record_failure`` per dial) and the control plane's
own failure detection (``watch()`` subscribes to instance/worker events,
so a heartbeat-staleness UNREACHABLE trips the breakers of every
instance on that worker without waiting for a request to fail).
"""

from __future__ import annotations

import asyncio
import collections
import enum
import hashlib
import json
import logging
import random
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)


def conversation_chain(model_name: str, messages: Sequence) -> List[str]:
    """Rolling hex digests of a chat conversation's message prefixes:
    ``chain[k]`` keys ``messages[:k+1]``. The affinity map records the
    FULL chain head when a request is routed and looks up the longest
    recorded prefix on the next turn — turn N+1's ``messages[:len_N]``
    equals turn N's full message list, so the lookup finds the replica
    whose radix KV cache already holds the conversation. Rolling
    (chained) hashing keeps the whole chain O(total bytes)."""
    chain: List[str] = []
    h = hashlib.sha256(model_name.encode())
    for msg in messages:
        if isinstance(msg, dict):
            payload = json.dumps(
                {
                    "role": msg.get("role", ""),
                    "content": msg.get("content", ""),
                },
                sort_keys=True, default=str,
            )
        else:
            payload = str(msg)
        h.update(payload.encode())
        chain.append(h.hexdigest())
    return chain


class PrefixAffinityMap:
    """Bounded map: conversation-prefix hash head → instance id.

    One map per :class:`ResilienceRegistry` (keys embed the model
    name via :func:`conversation_chain`, entries also carry the model
    id for targeted invalidation). LRU eviction bounds memory under
    many concurrent conversations; entries pointing at a replica that
    drained, errored, was deleted, or was re-tagged by a rollout are
    invalidated by the registry's watch feed. Hit/miss counters are
    exported on /metrics."""

    def __init__(self, max_entries: int = 4096):
        self.max_entries = max(16, int(max_entries))
        # key -> (instance_id, model_id); OrderedDict = LRU order
        self._entries: "collections.OrderedDict[str, Tuple[int, int]]" = (
            collections.OrderedDict()
        )
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0

    def __len__(self) -> int:
        return len(self._entries)

    def record(self, key: str, instance_id: int, model_id: int) -> None:
        if not key:
            return
        if key in self._entries:
            self._entries.pop(key)
        self._entries[key] = (instance_id, model_id)
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)
            self.evictions += 1

    def lookup(self, chain: Sequence[str]) -> Optional[int]:
        """Longest recorded prefix wins: walk the chain from the
        newest prefix down. Counts ONE hit or miss per lookup."""
        for key in reversed(chain):
            hit = self._entries.get(key)
            if hit is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return hit[0]
        self.misses += 1
        return None

    def invalidate_instance(self, instance_id: int) -> int:
        """Drop every entry pointing at ``instance_id`` (drained,
        deleted, errored, or re-tagged replica — its KV is gone or its
        role changed out from under the conversation)."""
        doomed = [
            k for k, (iid, _) in self._entries.items()
            if iid == instance_id
        ]
        for k in doomed:
            del self._entries[k]
        self.invalidations += len(doomed)
        return len(doomed)

    def demote_stale(self, instance_id: int, live_keys) -> int:
        """Eviction-driven invalidation (the affinity-staleness fix):
        drop entries steering conversations at ``instance_id`` whose
        prefix hash is NOT in its freshly scraped summary — the
        replica evicted those blocks, so "sticky" routing there buys a
        cold prefill while blinding the router to a warmer holder the
        directory knows about. Entries still advertised stay sticky
        (exact-holder routing beats a directory lookup when both
        agree)."""
        doomed = [
            k for k, (iid, _) in self._entries.items()
            if iid == instance_id and k not in live_keys
        ]
        for k in doomed:
            del self._entries[k]
        self.invalidations += len(doomed)
        return len(doomed)


class BreakerState(str, enum.Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


# numeric encoding for the breaker_state gauge (0 is healthy so alerts
# can be written as `> 0`)
_STATE_GAUGE = {
    BreakerState.CLOSED: 0,
    BreakerState.HALF_OPEN: 1,
    BreakerState.OPEN: 2,
}


class CircuitBreaker:
    """Per-instance breaker: N consecutive failures open it; after a
    jittered window one probe request is admitted (half-open); the
    probe's outcome closes it or re-opens with exponential backoff."""

    def __init__(
        self,
        failure_threshold: int = 3,
        open_seconds: float = 10.0,
        max_open_seconds: float = 120.0,
        clock=time.monotonic,
    ):
        self.failure_threshold = max(1, failure_threshold)
        self.open_seconds = open_seconds
        self.max_open_seconds = max_open_seconds
        self._clock = clock
        self.state = BreakerState.CLOSED
        self.consecutive_failures = 0
        self.open_count = 0          # consecutive opens → probe backoff
        self.probe_at = 0.0
        self.probing = False

    def would_allow(self) -> bool:
        """Pure peek for candidate ordering — never consumes the probe
        slot (``allow`` does, at dial time)."""
        if self.state is BreakerState.CLOSED:
            return True
        if self.state is BreakerState.OPEN:
            return self._clock() >= self.probe_at
        return not self.probing

    def allow(self) -> bool:
        """Stateful admission: an OPEN breaker past its window moves to
        HALF_OPEN and admits exactly one probe until its outcome lands."""
        if self.state is BreakerState.CLOSED:
            return True
        if self.state is BreakerState.OPEN:
            if self._clock() < self.probe_at:
                return False
            self.state = BreakerState.HALF_OPEN
            self.probing = True
            return True
        if self.probing:
            return False
        self.probing = True
        return True

    def record_success(self) -> None:
        self.probing = False
        self.consecutive_failures = 0
        self.open_count = 0
        self.state = BreakerState.CLOSED

    def record_failure(self) -> None:
        self.probing = False
        self.consecutive_failures += 1
        if (
            self.state is BreakerState.HALF_OPEN
            or self.consecutive_failures >= self.failure_threshold
        ):
            self.trip()

    def trip(self) -> None:
        """Force-open (also the worker-lost path: don't wait for dials
        to a dead host to time out one by one)."""
        self.state = BreakerState.OPEN
        self.probing = False
        self.open_count += 1
        base = min(
            self.max_open_seconds,
            self.open_seconds * (2 ** (self.open_count - 1)),
        )
        # jittered probe: replicas broken by one event must not all
        # probe (and all re-fail) in the same instant
        self.probe_at = self._clock() + base * random.uniform(0.8, 1.2)

    def seconds_until_probe(self) -> float:
        if self.state is not BreakerState.OPEN:
            return 0.0
        return max(0.0, self.probe_at - self._clock())


class InstanceHealth:
    __slots__ = ("breaker", "outstanding")

    def __init__(self, breaker: CircuitBreaker):
        self.breaker = breaker
        self.outstanding = 0


class ResilienceRegistry:
    """In-memory health view + selection + shed policy for the proxy."""

    def __init__(
        self,
        *,
        failover_attempts: int = 3,
        failover_deadline: float = 10.0,
        headers_timeout: float = 600.0,
        breaker_failure_threshold: int = 3,
        breaker_open_seconds: float = 10.0,
        model_max_outstanding: int = 256,
        affinity_max_entries: int = 4096,
        kv_directory_max_keys: int = 4096,
        clock=time.monotonic,
    ):
        self.failover_attempts = max(1, failover_attempts)
        self.failover_deadline = failover_deadline
        self.headers_timeout = headers_timeout
        self.breaker_failure_threshold = breaker_failure_threshold
        self.breaker_open_seconds = breaker_open_seconds
        self.model_max_outstanding = model_max_outstanding
        self._clock = clock
        self._instances: Dict[int, InstanceHealth] = {}
        self._model_outstanding: Dict[int, int] = {}
        # prefix-affinity routing (docs/KV_CACHE.md): conversation →
        # the replica whose radix KV cache already holds its prefix
        self.affinity = PrefixAffinityMap(affinity_max_entries)
        # fleet block directory (server/kv_directory.py): prefix-hash
        # residency across replicas, refreshed by the server's scrape
        # loop and invalidated by the SAME watch hooks as affinity
        from gpustack_tpu.server.kv_directory import ClusterKVDirectory

        self.kv_directory = ClusterKVDirectory(
            max_keys_per_instance=kv_directory_max_keys,
            clock=clock,
        )
        # drain-time prefetch trigger: async callable
        # (instance_id, keys) set by the server app when the fabric is
        # wired (server/app.py); None = prefetch disabled
        self.kv_prefetch = None
        # counters (exported via server /metrics)
        self.failovers_total = 0
        self.shed_total = 0
        self.breaker_opens_total = 0

    @classmethod
    def from_config(cls, cfg) -> "ResilienceRegistry":
        return cls(
            failover_attempts=int(
                getattr(cfg, "proxy_failover_attempts", 3)
            ),
            failover_deadline=float(
                getattr(cfg, "proxy_failover_deadline", 10.0)
            ),
            headers_timeout=float(
                getattr(cfg, "proxy_headers_timeout", 600.0)
            ),
            breaker_failure_threshold=int(
                getattr(cfg, "breaker_failure_threshold", 3)
            ),
            breaker_open_seconds=float(
                getattr(cfg, "breaker_open_seconds", 10.0)
            ),
            model_max_outstanding=int(
                getattr(cfg, "model_max_outstanding", 256)
            ),
            affinity_max_entries=int(
                getattr(cfg, "affinity_max_entries", 4096)
            ),
            kv_directory_max_keys=int(
                getattr(cfg, "kv_directory_max_keys", 4096)
            ),
        )

    # ---- per-instance state ---------------------------------------------

    def health(self, instance_id: int) -> InstanceHealth:
        h = self._instances.get(instance_id)
        if h is None:
            h = InstanceHealth(
                CircuitBreaker(
                    failure_threshold=self.breaker_failure_threshold,
                    open_seconds=self.breaker_open_seconds,
                    clock=self._clock,
                )
            )
            self._instances[instance_id] = h
        return h

    def breaker_state(self, instance_id: int) -> BreakerState:
        return self.health(instance_id).breaker.state

    def forget(self, instance_id: int) -> None:
        """Instance deleted: drop its state (ids are never reused by the
        autoincrement PK, so stale entries are pure leak) and its
        affinity entries (its KV died with its engine)."""
        self._instances.pop(instance_id, None)
        self.affinity.invalidate_instance(instance_id)
        self.kv_directory.invalidate_instance(instance_id)

    def reset(self, instance_id: int) -> None:
        """Instance freshly RUNNING (restart recovered): clean slate so a
        previous life's open breaker doesn't shadow the new engine."""
        h = self._instances.get(instance_id)
        if h is not None:
            h.breaker.record_success()

    def trip(self, instance_id: int, reason: str = "") -> None:
        h = self.health(instance_id)
        if h.breaker.state is BreakerState.OPEN:
            # already open: re-tripping would inflate the counter and
            # double the probe backoff without any probe having failed
            return
        logger.info(
            "circuit breaker for instance %d opened%s",
            instance_id, f" ({reason})" if reason else "",
        )
        h.breaker.trip()
        self.breaker_opens_total += 1

    # ---- proxy outcome feed ---------------------------------------------

    def record_success(self, instance_id: int) -> None:
        self.health(instance_id).breaker.record_success()

    def record_failure(self, instance_id: int) -> None:
        b = self.health(instance_id).breaker
        was_open = b.state is BreakerState.OPEN
        b.record_failure()
        if b.state is BreakerState.OPEN and not was_open:
            self.breaker_opens_total += 1
            logger.warning(
                "circuit breaker for instance %d opened after %d "
                "consecutive failures", instance_id,
                b.consecutive_failures,
            )

    def admit(self, instance_id: int) -> bool:
        return self.health(instance_id).breaker.allow()

    def abort_probe(self, instance_id: int) -> None:
        """A dial admitted by ``admit`` ended with NO outcome (the
        caller was cancelled mid-request): release the half-open probe
        slot. Without this the breaker wedges — probing stays True and
        ``allow`` refuses every future request forever."""
        h = self._instances.get(instance_id)
        if h is not None:
            h.breaker.probing = False

    # ---- selection --------------------------------------------------------

    def order(self, instances: Sequence, preferred: int = 0) -> List:
        """Preference order for a dial: breaker-admittable replicas
        first, the prefix-affinity ``preferred`` replica ahead of its
        group, then least-outstanding-requests (random tie-break so
        equal replicas share load). Breaker-open replicas stay in the
        list (last) purely so ``seconds_until_any_probe`` and callers
        can report on them — ``admit`` still refuses them. An affinity
        hit on a broken replica therefore falls back to the normal
        least-outstanding pick, never waits on the breaker."""

        def key(inst):
            h = self.health(inst.id)
            return (
                0 if h.breaker.would_allow() else 1,
                0 if inst.id == preferred else 1,
                h.outstanding,
                random.random(),
            )

        return sorted(instances, key=key)

    def seconds_until_any_probe(self, instances: Iterable) -> float:
        waits = [
            self.health(i.id).breaker.seconds_until_probe()
            for i in instances
        ]
        return min(waits) if waits else 0.0

    # ---- outstanding accounting + shedding --------------------------------

    def begin(self, model_id: int, instance_id: int) -> None:
        self.health(instance_id).outstanding += 1
        self._model_outstanding[model_id] = (
            self._model_outstanding.get(model_id, 0) + 1
        )

    def end(self, model_id: int, instance_id: int) -> None:
        h = self._instances.get(instance_id)
        if h is not None and h.outstanding > 0:
            h.outstanding -= 1
        n = self._model_outstanding.get(model_id, 0) - 1
        if n <= 0:
            self._model_outstanding.pop(model_id, None)
        else:
            self._model_outstanding[model_id] = n

    def outstanding(self, instance_id: int) -> int:
        h = self._instances.get(instance_id)
        return h.outstanding if h else 0

    def model_outstanding(self, model_id: int) -> int:
        return self._model_outstanding.get(model_id, 0)

    def try_shed(self, model_id: int) -> Optional[float]:
        """None = admitted; a float = shed, with the suggested
        ``Retry-After`` seconds. The cap bounds in-flight work per model
        so a stalled engine turns into fast 429s, not an unbounded queue
        of blocked clients."""
        cap = self.model_max_outstanding
        if cap <= 0:
            return None
        if self._model_outstanding.get(model_id, 0) < cap:
            return None
        self.shed_total += 1
        return 1.0

    # ---- control-plane feed ----------------------------------------------

    async def watch(self) -> None:
        """Subscribe to instance + worker events and keep the health
        view honest without request traffic: a worker whose heartbeats
        went stale (WorkerSyncer → UNREACHABLE) trips every breaker on
        it; an instance re-entering RUNNING gets a clean slate; deleted
        instances are forgotten."""
        from gpustack_tpu.schemas import (
            ModelInstance,
            ModelInstanceState,
            Worker,
            WorkerState,
        )
        from gpustack_tpu.server.bus import EventType

        async def instance_loop():
            agen = ModelInstance.subscribe(heartbeat=30.0)
            try:
                async for event in agen:
                    if event.type == EventType.RESYNC:
                        break
                    if event.type == EventType.HEARTBEAT:
                        continue
                    if event.type == EventType.DELETED:
                        self.forget(event.id)
                        continue
                    changes = event.changes or {}
                    # rollout re-tag (generation flip on a rollback's
                    # surviving replicas) or a role change: the
                    # conversation map must not keep steering turns at
                    # an instance whose spec/role moved under it
                    if "generation" in changes or "role" in changes:
                        self.affinity.invalidate_instance(event.id)
                    # TRANSITIONS only: keying off the absolute state
                    # would let any unrelated row update while RUNNING
                    # close a legitimately open breaker (and re-trip an
                    # open one on repeated ERROR-state writes)
                    changed = changes.get("state")
                    if not changed:
                        continue
                    state = changed[1]
                    if state == ModelInstanceState.RUNNING.value:
                        self.reset(event.id)
                    else:
                        # any exit from RUNNING (drain, error,
                        # unreachable, re-drive) invalidates affinity:
                        # the engine — and its radix KV — is going away
                        self.affinity.invalidate_instance(event.id)
                        # drain-time warm-ahead rides the same edge:
                        # snapshot the directory's view of this replica
                        # BEFORE dropping it, so the prefetcher knows
                        # which conversations are worth pulling to a
                        # sibling while the engine still answers
                        if (
                            state == ModelInstanceState.DRAINING.value
                            and self.kv_prefetch is not None
                        ):
                            keys = self.kv_directory.instance_keys(
                                event.id
                            )
                            if keys:
                                asyncio.create_task(
                                    self.kv_prefetch(event.id, keys)
                                )
                        self.kv_directory.invalidate_instance(event.id)
                    if state in (
                        ModelInstanceState.ERROR.value,
                        ModelInstanceState.UNREACHABLE.value,
                    ):
                        self.trip(event.id, f"instance {state}")
            finally:
                await agen.aclose()

        async def worker_loop():
            agen = Worker.subscribe(heartbeat=30.0)
            try:
                async for event in agen:
                    if event.type == EventType.RESYNC:
                        break
                    if event.type != EventType.UPDATED:
                        continue
                    changed = (event.changes or {}).get("state")
                    if not changed:
                        continue
                    if changed[1] != WorkerState.UNREACHABLE.value:
                        continue
                    for inst in await ModelInstance.filter(
                        worker_id=event.id
                    ):
                        self.trip(inst.id, "worker unreachable")
            finally:
                await agen.aclose()

        async def forever(loop_fn):
            # one transient DB/subscribe error must not silently
            # disable the control-plane breaker feed for the rest of
            # the server's life (the controllers use the same pattern)
            while True:
                try:
                    await loop_fn()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    logger.exception(
                        "resilience %s failed; retrying",
                        loop_fn.__name__,
                    )
                    await asyncio.sleep(2.0)

        loops = [
            asyncio.create_task(
                forever(instance_loop), name="resilience-inst"
            ),
            asyncio.create_task(
                forever(worker_loop), name="resilience-worker"
            ),
        ]
        try:
            await asyncio.gather(*loops)
        finally:
            for t in loops:
                t.cancel()

    # ---- metrics ----------------------------------------------------------

    def metrics_lines(self) -> List[str]:
        lines = [
            "# TYPE gpustack_proxy_failovers_total counter",
            f"gpustack_proxy_failovers_total {self.failovers_total}",
            "# TYPE gpustack_proxy_shed_total counter",
            f"gpustack_proxy_shed_total {self.shed_total}",
            "# TYPE gpustack_proxy_breaker_opens_total counter",
            f"gpustack_proxy_breaker_opens_total "
            f"{self.breaker_opens_total}",
            # prefix-affinity routing (conversation → KV-holding
            # replica): consult outcomes + map churn
            "# TYPE gpustack_proxy_affinity_hits_total counter",
            f"gpustack_proxy_affinity_hits_total {self.affinity.hits}",
            "# TYPE gpustack_proxy_affinity_misses_total counter",
            f"gpustack_proxy_affinity_misses_total "
            f"{self.affinity.misses}",
            "# TYPE gpustack_proxy_affinity_entries gauge",
            f"gpustack_proxy_affinity_entries {len(self.affinity)}",
            "# TYPE gpustack_proxy_affinity_evictions_total counter",
            f"gpustack_proxy_affinity_evictions_total "
            f"{self.affinity.evictions}",
            "# TYPE gpustack_proxy_affinity_invalidations_total counter",
            f"gpustack_proxy_affinity_invalidations_total "
            f"{self.affinity.invalidations}",
        ]
        if self._instances:
            lines.append("# TYPE gpustack_proxy_breaker_state gauge")
            for iid, h in sorted(self._instances.items()):
                lines.append(
                    f'gpustack_proxy_breaker_state{{instance_id="{iid}"}} '
                    f"{_STATE_GAUGE[h.breaker.state]}"
                )
            lines.append(
                "# TYPE gpustack_proxy_outstanding_requests gauge"
            )
            for iid, h in sorted(self._instances.items()):
                lines.append(
                    f"gpustack_proxy_outstanding_requests"
                    f'{{instance_id="{iid}"}} {h.outstanding}'
                )
        # fleet KV directory (server/kv_directory.py) rides the same
        # exporter append
        lines.extend(self.kv_directory.metrics_lines())
        return lines
