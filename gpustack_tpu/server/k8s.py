"""K8s worker-join manifests (reference k8s/manifest_template.py +
routes/clusters.py get_cluster_manifests).

``GET /v2/clusters/{id}/manifests`` renders a ready-to-apply YAML bundle
that joins TPU nodes to this cluster: a namespace, a secret holding the
cluster registration token, and a DaemonSet running the worker agent on
TPU nodes (selected by the standard ``cloud.google.com/gke-tpu-*``
labels, hostNetwork so ICI/DCN addressing matches the node).
"""

from __future__ import annotations

import jinja2

TEMPLATE = jinja2.Template(
    """\
apiVersion: v1
kind: Namespace
metadata:
  name: {{ namespace }}
---
apiVersion: v1
kind: Secret
metadata:
  name: gpustack-tpu-registration
  namespace: {{ namespace }}
type: Opaque
stringData:
  registration-token: "{{ registration_token }}"
---
apiVersion: apps/v1
kind: DaemonSet
metadata:
  name: gpustack-tpu-worker
  namespace: {{ namespace }}
spec:
  selector:
    matchLabels:
      app: gpustack-tpu-worker
  template:
    metadata:
      labels:
        app: gpustack-tpu-worker
    spec:
      nodeSelector:
        cloud.google.com/gke-tpu-accelerator: "{{ tpu_accelerator }}"
      hostNetwork: true
      dnsPolicy: ClusterFirstWithHostNet
      containers:
        - name: worker
          image: "{{ image }}"
          args:
            - start
            - --server-url={{ server_url }}
            - --worker-port={{ worker_port }}
{%- if tunnel %}
            - --tunnel
{%- endif %}
          env:
            - name: GPUSTACK_TPU_REGISTRATION_TOKEN
              valueFrom:
                secretKeyRef:
                  name: gpustack-tpu-registration
                  key: registration-token
          ports:
            - containerPort: {{ worker_port }}
              name: worker-http
          securityContext:
            privileged: true   # /dev/accel* TPU device access
          volumeMounts:
            - name: models
              mountPath: /var/lib/gpustack-tpu
      volumes:
        - name: models
          hostPath:
            path: /var/lib/gpustack-tpu
            type: DirectoryOrCreate
"""
)


def render_manifests(
    server_url: str,
    registration_token: str,
    *,
    namespace: str = "gpustack-tpu",
    image: str = "gpustack/gpustack-tpu:latest",
    tpu_accelerator: str = "tpu-v5-lite-podslice",
    worker_port: int = 10151,
    tunnel: bool = False,
) -> str:
    return TEMPLATE.render(
        server_url=server_url,
        registration_token=registration_token,
        namespace=namespace,
        image=image,
        tpu_accelerator=tpu_accelerator,
        worker_port=worker_port,
        tunnel=tunnel,
    )
