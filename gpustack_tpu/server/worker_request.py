"""Unified server→worker HTTP: direct dial or tunnel, always authenticated.

Reference parity: server/worker_request.py:153,214 (direct vs
tunnel-proxied request helpers). Every server→worker request carries the
worker's proxy secret as a bearer token — the worker's HTTP server
rejects anything else, which closes the round-1 hole where engine ports
answered unauthenticated inference to anyone who could reach them.
"""

from __future__ import annotations

import json as jsonlib
from typing import Any, Dict, Optional

import aiohttp
from aiohttp import web

from gpustack_tpu.schemas import Worker


class DirectResponse:
    """aiohttp response pass-through with the tunnel adapter's surface."""

    def __init__(self, resp: aiohttp.ClientResponse):
        self._resp = resp
        self.status = resp.status
        self.headers = resp.headers

    @property
    def content_type(self) -> str:
        return self._resp.content_type

    @property
    def content(self):
        return self._resp.content

    async def read(self) -> bytes:
        return await self._resp.read()

    def release(self) -> None:
        self._resp.release()


async def worker_fetch(
    app: web.Application,
    worker: Worker,
    method: str,
    path: str,
    *,
    json_body: Optional[Dict[str, Any]] = None,
    raw_body: bytes = b"",
    content_type: str = "",
    timeout: float = 600.0,
):
    """Send an authenticated request to a worker; returns a response
    adapter (.status/.headers/.content.iter_any()/.read()/.release()).

    Prefers the worker's tunnel when connected (NAT'd workers have no
    other path); otherwise dials ``worker.ip:worker.port`` directly.
    Raises ``aiohttp.ClientError`` when neither path works.
    """
    headers = {}
    if worker.proxy_secret:
        headers["Authorization"] = f"Bearer {worker.proxy_secret}"
    body = b""
    if json_body is not None:
        body = jsonlib.dumps(json_body).encode()
        headers["Content-Type"] = "application/json"
    elif raw_body:
        body = raw_body
        if content_type:
            headers["Content-Type"] = content_type

    hub = app.get("tunnel_hub")
    session = hub.get(worker.id) if hub else None
    if session is not None:
        return await session.request(
            method, path, headers, body, timeout=timeout
        )

    if not worker.ip:
        raise aiohttp.ClientError(
            f"worker {worker.id} has no address and no tunnel"
        )
    url = f"http://{worker.ip}:{worker.port}{path}"
    resp = await app["proxy_session"].request(
        method,
        url,
        data=body or None,
        headers=headers,
        timeout=aiohttp.ClientTimeout(total=timeout),
    )
    return DirectResponse(resp)
