"""Unified server→worker HTTP: direct dial or tunnel, always authenticated.

Reference parity: server/worker_request.py:153,214 (direct vs
tunnel-proxied request helpers). Every server→worker request carries the
worker's proxy secret as a bearer token — the worker's HTTP server
rejects anything else, which closes the round-1 hole where engine ports
answered unauthenticated inference to anyone who could reach them.
"""

from __future__ import annotations

import json as jsonlib
from typing import Any, Dict, Optional

import aiohttp
from aiohttp import web

from gpustack_tpu.schemas import Worker


class DirectResponse:
    """aiohttp response pass-through with the tunnel adapter's surface."""

    def __init__(self, resp: aiohttp.ClientResponse):
        self._resp = resp
        self.status = resp.status
        self.headers = resp.headers

    @property
    def content_type(self) -> str:
        return self._resp.content_type

    @property
    def content(self):
        return self._resp.content

    async def read(self) -> bytes:
        return await self._resp.read()

    def release(self) -> None:
        self._resp.release()


async def worker_fetch(
    app: web.Application,
    worker: Worker,
    method: str,
    path: str,
    *,
    json_body: Optional[Dict[str, Any]] = None,
    raw_body: bytes = b"",
    content_type: str = "",
    timeout: float = 600.0,
    allow_federation: bool = True,
):
    """Send an authenticated request to a worker; returns a response
    adapter (.status/.headers/.content.iter_any()/.read()/.release()).

    Route order: the worker's LOCAL tunnel when connected (NAT'd workers
    have no other path) → a federation peer whose registered CIDR
    longest-prefix-matches the worker's IP (multi-server deployments,
    tunnel/federation.py — the hop the reference's distributed
    websocket proxy performs) → direct dial of ``worker.ip:worker.port``.
    ``allow_federation=False`` is the loop guard used by the peer-side
    forward handler. Raises ``aiohttp.ClientError`` when no path works.
    """
    headers = {}
    if worker.proxy_secret:
        headers["Authorization"] = f"Bearer {worker.proxy_secret}"
    body = b""
    if json_body is not None:
        body = jsonlib.dumps(json_body).encode()
        headers["Content-Type"] = "application/json"
    elif raw_body:
        body = raw_body
        if content_type:
            headers["Content-Type"] = content_type

    hub = app.get("tunnel_hub")
    session = hub.get(worker.id) if hub else None
    if session is not None:
        return await session.request(
            method, path, headers, body, timeout=timeout
        )

    federation = app.get("federation")
    if allow_federation and federation is not None and worker.ip:
        peer = federation.route(worker.ip)
        if peer is not None:
            from gpustack_tpu.tunnel.federation import forward_via_peer

            resp, err = await forward_via_peer(
                app["proxy_session"], peer, worker, method, path,
                headers, body, timeout,
            )
            if resp is not None:
                return resp
            # a dead/misconfigured peer must not make a
            # directly-dialable worker unreachable — fall through
            import logging

            logging.getLogger(__name__).warning(
                "federation hop failed (%s); trying direct dial", err
            )

    if not worker.ip:
        raise aiohttp.ClientError(
            f"worker {worker.id} has no address and no tunnel"
        )
    url = f"http://{worker.ip}:{worker.port}{path}"
    resp = await app["proxy_session"].request(
        method,
        url,
        data=body or None,
        headers=headers,
        timeout=aiohttp.ClientTimeout(total=timeout),
    )
    return DirectResponse(resp)
