"""Unified server→worker HTTP: direct dial or tunnel, always authenticated.

Reference parity: server/worker_request.py:153,214 (direct vs
tunnel-proxied request helpers). Every server→worker request carries the
worker's proxy secret as a bearer token — the worker's HTTP server
rejects anything else, which closes the round-1 hole where engine ports
answered unauthenticated inference to anyone who could reach them.

Deadline tiers (chaos-harness hardening): one 600 s total timeout used
to serve both quick control calls and long streaming relays, so a
partitioned worker could park a status probe for ten minutes. Now:

- every dial separates the CONNECT budget (``worker_connect_timeout``,
  default 5 s — a host that won't even accept the TCP handshake should
  fail fast) from the total budget;
- ``control=True`` marks a short idempotent control RPC: the total
  budget drops to ``worker_control_timeout`` and, for GET/HEAD only,
  failures retry with jittered exponential backoff up to
  ``worker_control_retries`` times (non-idempotent methods never
  retry — a repeated POST could double-apply);
- callers that relay streams (log follow, inference proxy) keep passing
  their own long ``timeout`` and are never retried here.
"""

from __future__ import annotations

import asyncio
import json as jsonlib
import random
from typing import Any, Awaitable, Callable, Dict, Optional

import aiohttp
from aiohttp import web

from gpustack_tpu.schemas import Worker

# Defaults used when the app carries no Config (unit tests that mount a
# bare aiohttp app around this helper).
DEFAULT_CONNECT_TIMEOUT = 5.0
DEFAULT_CONTROL_TIMEOUT = 15.0
DEFAULT_CONTROL_RETRIES = 2
DEFAULT_STREAM_TIMEOUT = 600.0

# Fault-injection hook (testing/chaos.py installs one; ALWAYS None in
# production). Called before every dial attempt with
# (worker, method, path); it may sleep (RPC delay) or raise
# aiohttp.ClientError (RPC drop). Retries treat an injected failure
# exactly like a network one — which is the point: the chaos harness
# proves the retry tier rides through transient drops.
rpc_fault_hook: Optional[
    Callable[[Worker, str, str], Awaitable[None]]
] = None


class DirectResponse:
    """aiohttp response pass-through with the tunnel adapter's surface."""

    def __init__(self, resp: aiohttp.ClientResponse):
        self._resp = resp
        self.status = resp.status
        self.headers = resp.headers

    @property
    def content_type(self) -> str:
        return self._resp.content_type

    @property
    def content(self):
        return self._resp.content

    async def read(self) -> bytes:
        return await self._resp.read()

    def release(self) -> None:
        self._resp.release()


async def worker_fetch(
    app: web.Application,
    worker: Worker,
    method: str,
    path: str,
    *,
    json_body: Optional[Dict[str, Any]] = None,
    raw_body: bytes = b"",
    content_type: str = "",
    timeout: Optional[float] = None,
    connect_timeout: Optional[float] = None,
    control: bool = False,
    allow_federation: bool = True,
    extra_headers: Optional[Dict[str, str]] = None,
):
    """Send an authenticated request to a worker; returns a response
    adapter (.status/.headers/.content.iter_any()/.read()/.release()).

    Route order: the worker's LOCAL tunnel when connected (NAT'd workers
    have no other path) → a federation peer whose registered CIDR
    longest-prefix-matches the worker's IP (multi-server deployments,
    tunnel/federation.py — the hop the reference's distributed
    websocket proxy performs) → direct dial of ``worker.ip:worker.port``.
    ``allow_federation=False`` is the loop guard used by the peer-side
    forward handler. Raises ``aiohttp.ClientError`` when no path works.

    ``timeout=None`` resolves per tier: short (``worker_control_timeout``)
    when ``control=True``, long (600 s) for streaming relays.
    """
    cfg = app.get("config") if hasattr(app, "get") else None
    if connect_timeout is None:
        connect_timeout = getattr(
            cfg, "worker_connect_timeout", DEFAULT_CONNECT_TIMEOUT
        )
    if timeout is None:
        timeout = (
            getattr(cfg, "worker_control_timeout", DEFAULT_CONTROL_TIMEOUT)
            if control
            else DEFAULT_STREAM_TIMEOUT
        )
    retries = 0
    if control and method.upper() in ("GET", "HEAD"):
        retries = max(
            0,
            int(getattr(
                cfg, "worker_control_retries", DEFAULT_CONTROL_RETRIES
            )),
        )

    headers: Dict[str, str] = {}
    if extra_headers:
        # trace propagation (traceparent / X-Request-ID) — merged first
        # so protocol headers below always win
        headers.update(extra_headers)
    if worker.proxy_secret:
        headers["Authorization"] = f"Bearer {worker.proxy_secret}"
    body = b""
    if json_body is not None:
        body = jsonlib.dumps(json_body).encode()
        headers["Content-Type"] = "application/json"
    elif raw_body:
        body = raw_body
        if content_type:
            headers["Content-Type"] = content_type

    # ``timeout`` is the TOTAL budget across every attempt and backoff,
    # not per attempt — a worker that accepts connections but hangs
    # responses must not turn a "15 s control RPC" into 3×15 s + sleeps.
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    attempt = 0
    while True:
        remaining = deadline - loop.time()
        try:
            if rpc_fault_hook is not None:
                await rpc_fault_hook(worker, method, path)
            return await _dial_once(
                app, worker, method, path, headers, body,
                timeout=max(0.05, remaining),
                connect_timeout=connect_timeout,
                allow_federation=allow_federation,
            )
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            if attempt >= retries:
                raise
            attempt += 1
            # jittered: a worker briefly mid-restart shouldn't be
            # re-hit by every pending control RPC in lockstep
            backoff = min(1.0, 0.1 * (2 ** (attempt - 1))) * (
                random.uniform(0.5, 1.5)
            )
            if loop.time() + backoff >= deadline - 0.05:
                raise  # no budget left for another attempt
            await asyncio.sleep(backoff)


async def _dial_once(
    app: web.Application,
    worker: Worker,
    method: str,
    path: str,
    headers: Dict[str, str],
    body: bytes,
    *,
    timeout: float,
    connect_timeout: float,
    allow_federation: bool,
):
    hub = app.get("tunnel_hub")
    session = hub.get(worker.id) if hub else None
    if session is not None:
        return await session.request(
            method, path, headers, body, timeout=timeout
        )

    federation = app.get("federation")
    if allow_federation and federation is not None and worker.ip:
        peer = federation.route(worker.ip)
        if peer is not None:
            from gpustack_tpu.tunnel.federation import forward_via_peer

            resp, err = await forward_via_peer(
                app["proxy_session"], peer, worker, method, path,
                headers, body, timeout,
            )
            if resp is not None:
                return resp
            # a dead/misconfigured peer must not make a
            # directly-dialable worker unreachable — fall through
            import logging

            logging.getLogger(__name__).warning(
                "federation hop failed (%s); trying direct dial", err
            )

    if not worker.ip:
        raise aiohttp.ClientError(
            f"worker {worker.id} has no address and no tunnel"
        )
    url = f"http://{worker.ip}:{worker.port}{path}"
    resp = await app["proxy_session"].request(
        method,
        url,
        data=body or None,
        headers=headers,
        timeout=aiohttp.ClientTimeout(
            total=timeout, connect=connect_timeout
        ),
    )
    return DirectResponse(resp)
