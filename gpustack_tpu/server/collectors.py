"""Server-side collectors: lifecycle audit, load samples, usage archival.

Reference parity:
- ``UsageArchiver`` — server/usage_archiver.py + TableArchiver: hot
  ``model_usage`` rows older than the retention window aggregate into
  daily ``usage_archive`` rows and are deleted (hot→cold archival keeps
  the request-rate table bounded).

(The old ``WorkerStatusBuffer`` — reference worker_status_buffer.py —
grew into the control write combiner, server/write_combiner.py: same
batching idea, but set_field-shaped column writes, a deadline bound,
and an overload-degradation ladder so DB write rate stays sub-linear
at 1000+ workers.)
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Optional, Tuple

from gpustack_tpu.orm.record import Record, register_record
from gpustack_tpu.schemas import Worker, WorkerState
from gpustack_tpu.schemas.usage import ModelUsage
from gpustack_tpu.utils.profiling import timed

logger = logging.getLogger(__name__)


class BackgroundTask:
    """start/stop + run-loop-with-exception-logging shared by every
    collector (one place to fix lifecycle semantics, not four)."""

    task_name = "background-task"

    def __init__(self) -> None:
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(
                self._run(), name=self.task_name
            )

    def stop(self) -> None:
        if self._task:
            self._task.cancel()
            self._task = None

    async def _run(self) -> None:
        raise NotImplementedError


class PeriodicTask(BackgroundTask):
    """BackgroundTask ticking ``tick()`` every ``interval`` seconds."""

    def __init__(self, interval: float):
        super().__init__()
        self.interval = interval

    async def tick(self) -> None:
        raise NotImplementedError

    async def _run(self) -> None:
        while True:
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("%s iteration failed", self.task_name)
            await asyncio.sleep(self.interval)


class DirtyTrackedTask(PeriodicTask):
    """PeriodicTask with a bus-tap dirty-set (server/bus.py DirtySet)
    so steady-state no-op ticks can skip their table scans.

    One home for the lifecycle the rollout controller and autoscaler
    share: lazy attach at start() from the bound Record bus (unbound
    unit-test mounts simply scan every tick), detach at stop(), a
    drain at tick start, and an exception-path re-arm so drained-but-
    unacted events can never shelve pending work behind the skip."""

    #: record kinds whose writes invalidate the cached snapshot
    dirty_kinds: Tuple[str, ...] = ()

    def __init__(self, interval: float):
        super().__init__(interval)
        self._dirty = None
        self.skipped_ticks = 0

    def attach_dirty(self, bus) -> None:
        from gpustack_tpu.server.bus import DirtySet

        self._dirty = DirtySet(bus, set(self.dirty_kinds))

    def start(self) -> None:
        if self._dirty is None:
            try:
                self.attach_dirty(Record.bus())
            except AssertionError:
                pass
        super().start()

    def stop(self) -> None:
        if self._dirty is not None:
            self._dirty.close()
            self._dirty = None
        super().stop()

    def _drain_dirty(self) -> bool:
        """True when anything watched changed since the last drain —
        or when no dirty-set is attached (always scan then)."""
        if self._dirty is None:
            return True
        dirty_all, dirty = self._dirty.drain()
        return dirty_all or any(dirty.values())

    def _rearm_dirty(self) -> None:
        """A pass failed AFTER draining: the consumed events were
        never acted on — mark everything dirty so the next tick runs."""
        if self._dirty is not None:
            self._dirty.mark_all()


@register_record
class ResourceEvent(Record):
    """Lifecycle audit row (reference resource_events table +
    ResourceEventLogger, server/server.py:505-559): who/what changed
    state, kept as a queryable history separate from logs."""

    __kind__ = "resource_event"
    __indexes__ = ("kind", "resource_id")

    kind: str = ""           # "model_instance" | "worker" | ...
    resource_id: int = 0
    name: str = ""
    event: str = ""          # e.g. "state: scheduled -> running"
    detail: str = ""


class ResourceEventLogger(BackgroundTask):
    """Bus subscriber turning state transitions into ResourceEvent rows."""

    task_name = "resource-events"
    WATCHED = ("model_instance", "worker")
    RETENTION_DAYS = 30.0

    async def _run(self) -> None:
        from gpustack_tpu.orm.record import Record as _Record
        from gpustack_tpu.server.bus import EventType

        subscriber = _Record.bus().subscribe(kinds=set(self.WATCHED))
        try:
            while True:
                event = await subscriber.get()
                try:
                    if event.type == EventType.RESYNC:
                        # bus overflow: the audit trail must show the
                        # gap, not silently skip transitions
                        await ResourceEvent.create(
                            ResourceEvent(
                                kind=event.kind or "*",
                                event="resync (events may be missing)",
                            )
                        )
                        continue
                    if event.type not in (
                        EventType.CREATED, EventType.UPDATED,
                        EventType.DELETED,
                    ):
                        continue
                    await self.record(event)
                except Exception:
                    logger.exception("resource event write failed")
        finally:
            subscriber.close()

    @staticmethod
    async def record(event) -> None:
        data = event.data or {}
        changes = event.changes or {}
        if event.type.value == "DELETED":
            text = "deleted"
        elif event.type.value == "CREATED":
            text = f"created (state: {data.get('state', '')})"
        elif "state" in changes:
            old, new = changes["state"]
            text = f"state: {old} -> {new}"
        else:
            return  # non-state updates are noise, not lifecycle
        await ResourceEvent.create(
            ResourceEvent(
                kind=event.kind,
                resource_id=event.id,
                name=str(data.get("name", "")),
                event=text,
                detail=str(data.get("state_message", ""))[:500],
            )
        )

    @classmethod
    async def prune(cls) -> int:
        """Delete events past retention (called by SystemLoadCollector's
        periodic tick — one pruning heartbeat covers both tables)."""
        return await _prune_old(ResourceEvent, cls.RETENTION_DAYS)


async def _prune_old(record_cls, retention_days: float) -> int:
    import datetime

    cutoff = (
        datetime.datetime.now(datetime.timezone.utc)
        - datetime.timedelta(days=retention_days)
    ).isoformat()
    deleted = 0
    while True:
        old = await record_cls.filter_created_before(cutoff, limit=1000)
        if not old:
            return deleted
        for row in old:
            await row.delete()
        deleted += len(old)


@register_record
class SystemLoad(Record):
    """Periodic fleet-load sample (reference SystemLoadCollector,
    server/system_load.py): dashboard history without re-aggregating the
    live workers table."""

    __kind__ = "system_load"
    __indexes__ = ()

    workers_total: int = 0
    workers_ready: int = 0
    chips_total: int = 0
    chips_allocated: int = 0
    memory_used_bytes: int = 0
    memory_total_bytes: int = 0


class SystemLoadCollector(PeriodicTask):
    task_name = "system-load"
    RETENTION_DAYS = 7.0

    def __init__(self, interval: float = 60.0):
        super().__init__(interval)

    async def tick(self) -> None:
        await self.collect_once()
        await _prune_old(SystemLoad, self.RETENTION_DAYS)
        await ResourceEventLogger.prune()

    @timed(threshold_s=5.0, name="collectors.system_load_sweep")
    async def collect_once(self) -> SystemLoad:
        from gpustack_tpu.policies.allocatable import CLAIMING_STATES
        from gpustack_tpu.schemas import ModelInstance

        workers = await Worker.filter(limit=None)
        # same claiming-state filter as the scheduler's allocatable math:
        # an ERROR instance's chips are free, not allocated
        instances = [
            i for i in await ModelInstance.filter(limit=None)
            if i.state in CLAIMING_STATES
        ]
        allocated = sum(
            len(i.chip_indexes or []) for i in instances
        ) + sum(
            len(s.chip_indexes or [])
            for i in instances
            for s in i.subordinate_workers
        )
        sample = SystemLoad(
            workers_total=len(workers),
            workers_ready=sum(
                1 for w in workers if w.state == WorkerState.READY
            ),
            chips_total=sum(w.total_chips for w in workers),
            chips_allocated=allocated,
            memory_used_bytes=sum(
                w.status.memory_used_bytes for w in workers
            ),
            memory_total_bytes=sum(
                w.status.memory_total_bytes for w in workers
            ),
        )
        return await SystemLoad.create(sample)


@register_record
class UsageArchive(Record):
    """Daily cold aggregate of model usage (reference metered-usage
    archival tables)."""

    __kind__ = "usage_archive"
    __indexes__ = ("day", "model_id", "user_id")

    day: str = ""              # YYYY-MM-DD
    model_id: int = 0
    user_id: int = 0
    operation: str = ""
    requests: int = 0
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0


class UsageArchiver(PeriodicTask):
    task_name = "usage-archiver"

    def __init__(
        self,
        retention_days: float = 7.0,
        interval: float = 3600.0,
    ):
        super().__init__(interval)
        self.retention_days = retention_days

    async def tick(self) -> None:
        await self.archive_once()

    BATCH = 10_000

    @timed(threshold_s=30.0, name="collectors.usage_archive_sweep")
    async def archive_once(self) -> int:
        """Aggregate hot rows older than retention into daily archive
        rows; delete the hot rows. Returns rows archived.

        Hot rows come from an indexed created_at range query in bounded
        batches — never a full-table scan. Per bucket, hot rows are
        deleted BEFORE the aggregate upsert: a crash between the two
        loses at most one bucket's increment, whereas aggregate-first
        would double-count every bucket on the post-crash rerun
        (duplicated metering is worse than a bounded gap).
        """
        import datetime

        cutoff = (
            datetime.datetime.now(datetime.timezone.utc)
            - datetime.timedelta(days=self.retention_days)
        ).isoformat()
        total = 0
        while True:
            old = await ModelUsage.filter_created_before(
                cutoff, limit=self.BATCH
            )
            if not old:
                break
            buckets: Dict[
                Tuple[str, int, int, str],
                Tuple[Dict[str, int], list],
            ] = {}
            for u in old:
                day = u.created_at[:10]
                key = (day, u.model_id, u.user_id, u.operation)
                agg, rows = buckets.setdefault(
                    key,
                    (
                        {
                            "requests": 0, "prompt_tokens": 0,
                            "completion_tokens": 0, "total_tokens": 0,
                        },
                        [],
                    ),
                )
                agg["requests"] += 1
                agg["prompt_tokens"] += u.prompt_tokens
                agg["completion_tokens"] += u.completion_tokens
                agg["total_tokens"] += u.total_tokens
                rows.append(u)
            for (day, model_id, user_id, operation), (
                agg, rows,
            ) in buckets.items():
                for u in rows:
                    await u.delete()
                existing = await UsageArchive.first(
                    day=day, model_id=model_id, user_id=user_id,
                    operation=operation,
                )
                if existing is not None:
                    await existing.update(
                        requests=existing.requests + agg["requests"],
                        prompt_tokens=(
                            existing.prompt_tokens + agg["prompt_tokens"]
                        ),
                        completion_tokens=(
                            existing.completion_tokens
                            + agg["completion_tokens"]
                        ),
                        total_tokens=(
                            existing.total_tokens + agg["total_tokens"]
                        ),
                    )
                else:
                    await UsageArchive.create(
                        UsageArchive(
                            day=day, model_id=model_id, user_id=user_id,
                            operation=operation, **agg,
                        )
                    )
            total += len(old)
            logger.info(
                "archived %d usage rows into %d daily aggregates",
                len(old), len(buckets),
            )
        return total
