"""Server-side collectors: worker-status write buffering + usage archival.

Reference parity:
- ``WorkerStatusBuffer`` — server/worker_status_buffer.py: status POSTs
  land in memory and a single flush loop batches them to the DB (direct
  per-POST writes are fine at 3 workers, not at 300). State TRANSITIONS
  (NOT_READY→READY) flush immediately so deploys stay snappy; steady-state
  refreshes batch.
- ``UsageArchiver`` — server/usage_archiver.py + TableArchiver: hot
  ``model_usage`` rows older than the retention window aggregate into
  daily ``usage_archive`` rows and are deleted (hot→cold archival keeps
  the request-rate table bounded).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Optional, Tuple

from gpustack_tpu.orm.record import Record, register_record
from gpustack_tpu.schemas import Worker, WorkerState
from gpustack_tpu.schemas.usage import ModelUsage

logger = logging.getLogger(__name__)


class WorkerStatusBuffer:
    def __init__(self, flush_interval: float = 2.0):
        self.flush_interval = flush_interval
        # worker_id -> (status, heartbeat_at)
        self._pending: Dict[int, Tuple[object, str]] = {}
        self._task: Optional[asyncio.Task] = None

    async def put(self, worker: Worker, status, heartbeat_at: str) -> None:
        """Buffer a status refresh; flush immediately on a state
        transition (a worker coming READY unblocks scheduling)."""
        if worker.state != WorkerState.READY:
            await worker.update(
                status=status,
                state=WorkerState.READY,
                state_message="",
                heartbeat_at=heartbeat_at,
            )
            self._pending.pop(worker.id, None)
            return
        self._pending[worker.id] = (status, heartbeat_at)

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(
                self._loop(), name="status-buffer"
            )

    def stop(self) -> None:
        if self._task:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.flush_interval)
            try:
                await self.flush()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("status buffer flush failed")

    async def flush(self) -> int:
        pending, self._pending = self._pending, {}
        flushed = 0
        for worker_id, (status, heartbeat_at) in pending.items():
            worker = await Worker.get(worker_id)
            if worker is None:
                continue
            # guard against the snapshot race: a write-through update
            # (state transition) or a newer heartbeat may have landed
            # after this entry was buffered — never regress it
            if worker.state != WorkerState.READY:
                continue
            if worker.heartbeat_at and worker.heartbeat_at >= heartbeat_at:
                continue
            await worker.update(
                status=status, heartbeat_at=heartbeat_at
            )
            flushed += 1
        return flushed


@register_record
class UsageArchive(Record):
    """Daily cold aggregate of model usage (reference metered-usage
    archival tables)."""

    __kind__ = "usage_archive"
    __indexes__ = ("day", "model_id", "user_id")

    day: str = ""              # YYYY-MM-DD
    model_id: int = 0
    user_id: int = 0
    operation: str = ""
    requests: int = 0
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0


class UsageArchiver:
    def __init__(
        self,
        retention_days: float = 7.0,
        interval: float = 3600.0,
    ):
        self.retention_days = retention_days
        self.interval = interval
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(
                self._loop(), name="usage-archiver"
            )

    def stop(self) -> None:
        if self._task:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            try:
                await self.archive_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("usage archival failed")
            await asyncio.sleep(self.interval)

    BATCH = 10_000

    async def archive_once(self) -> int:
        """Aggregate hot rows older than retention into daily archive
        rows; delete the hot rows. Returns rows archived.

        Hot rows come from an indexed created_at range query in bounded
        batches — never a full-table scan. Per bucket, hot rows are
        deleted BEFORE the aggregate upsert: a crash between the two
        loses at most one bucket's increment, whereas aggregate-first
        would double-count every bucket on the post-crash rerun
        (duplicated metering is worse than a bounded gap).
        """
        import datetime

        cutoff = (
            datetime.datetime.now(datetime.timezone.utc)
            - datetime.timedelta(days=self.retention_days)
        ).isoformat()
        total = 0
        while True:
            old = await ModelUsage.filter_created_before(
                cutoff, limit=self.BATCH
            )
            if not old:
                break
            buckets: Dict[
                Tuple[str, int, int, str],
                Tuple[Dict[str, int], list],
            ] = {}
            for u in old:
                day = u.created_at[:10]
                key = (day, u.model_id, u.user_id, u.operation)
                agg, rows = buckets.setdefault(
                    key,
                    (
                        {
                            "requests": 0, "prompt_tokens": 0,
                            "completion_tokens": 0, "total_tokens": 0,
                        },
                        [],
                    ),
                )
                agg["requests"] += 1
                agg["prompt_tokens"] += u.prompt_tokens
                agg["completion_tokens"] += u.completion_tokens
                agg["total_tokens"] += u.total_tokens
                rows.append(u)
            for (day, model_id, user_id, operation), (
                agg, rows,
            ) in buckets.items():
                for u in rows:
                    await u.delete()
                existing = await UsageArchive.first(
                    day=day, model_id=model_id, user_id=user_id,
                    operation=operation,
                )
                if existing is not None:
                    await existing.update(
                        requests=existing.requests + agg["requests"],
                        prompt_tokens=(
                            existing.prompt_tokens + agg["prompt_tokens"]
                        ),
                        completion_tokens=(
                            existing.completion_tokens
                            + agg["completion_tokens"]
                        ),
                        total_tokens=(
                            existing.total_tokens + agg["total_tokens"]
                        ),
                    )
                else:
                    await UsageArchive.create(
                        UsageArchive(
                            day=day, model_id=model_id, user_id=user_id,
                            operation=operation, **agg,
                        )
                    )
            total += len(old)
            logger.info(
                "archived %d usage rows into %d daily aggregates",
                len(old), len(buckets),
            )
        return total
