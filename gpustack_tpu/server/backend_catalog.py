"""Community backend-catalog sync.

Reference parity: InferenceBackendController reconciles the built-in +
community backend catalog into DB rows (reference
server/controllers.py:1481-1634, gpustack-runner catalog role). Here a
leader task loads a catalog document (local file or HTTPS URL —
``backend_catalog_url`` config / ``GPUSTACK_TPU_BACKEND_CATALOG``) and
upserts InferenceBackend rows:

- rows it creates are stamped ``managed=True`` and tracked: edits in the
  catalog update them, removal from the catalog deletes them;
- operator-created rows (managed=False) are NEVER touched — the catalog
  cannot clobber local customizations;
- the builtin ``tpu-native`` backend is seeded elsewhere (server.py) and
  ignored by the sync.

Catalog document shape::

    {"backends": [{"name": ..., "description": ...,
                   "default_version": ...,
                   "versions": [{"version": ..., "command": [...],
                                 "env": {...}, "health_path": ...}]}]}
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
from typing import Any, Dict, List, Optional

from gpustack_tpu.schemas import InferenceBackend
from gpustack_tpu.schemas.inference_backends import BackendVersionConfig

logger = logging.getLogger(__name__)


def parse_catalog(doc: Dict[str, Any]) -> List[InferenceBackend]:
    out = []
    for entry in doc.get("backends", []):
        name = str(entry.get("name", "")).strip()
        if not name:
            continue
        versions = [
            BackendVersionConfig(
                version=str(v.get("version", "latest")),
                command=[str(c) for c in v.get("command", [])],
                env={
                    str(k): str(val)
                    for k, val in (v.get("env") or {}).items()
                },
                health_path=str(v.get("health_path", "/healthz")),
            )
            for v in entry.get("versions", [])
        ]
        if not versions:
            continue
        out.append(
            InferenceBackend(
                name=name,
                description=str(entry.get("description", "")),
                versions=versions,
                default_version=str(
                    entry.get(
                        "default_version", versions[0].version
                    )
                ),
                managed=True,
            )
        )
    return out


class BackendCatalogSync:
    def __init__(self, source: str, interval_s: float = 1800.0) -> None:
        self.source = source
        self.interval_s = interval_s
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if not self.source:
            return
        self._task = asyncio.create_task(
            self._loop(), name="backend-catalog-sync"
        )

    def stop(self) -> None:
        if self._task:
            self._task.cancel()

    async def _loop(self) -> None:
        while True:
            try:
                await self.sync_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("backend catalog sync failed")
            await asyncio.sleep(self.interval_s)

    async def _fetch(self) -> Dict[str, Any]:
        if self.source.startswith(("http://", "https://")):
            import aiohttp

            async with aiohttp.ClientSession() as s:
                async with s.get(
                    self.source,
                    timeout=aiohttp.ClientTimeout(total=30),
                ) as r:
                    r.raise_for_status()
                    return await r.json(content_type=None)
        path = os.path.expanduser(self.source)
        loop = asyncio.get_running_loop()

        def read():
            with open(path) as f:
                return json.load(f)

        return await loop.run_in_executor(None, read)

    async def sync_once(self) -> Dict[str, int]:
        doc = await self._fetch()
        wanted = {b.name: b for b in parse_catalog(doc)}
        stats = {"created": 0, "updated": 0, "deleted": 0, "skipped": 0}
        existing = {
            b.name: b for b in await InferenceBackend.filter(limit=None)
        }
        for name, b in wanted.items():
            cur = existing.get(name)
            if cur is None:
                await InferenceBackend.create(b)
                stats["created"] += 1
            elif not cur.managed or cur.builtin:
                # operator-owned or builtin: hands off
                stats["skipped"] += 1
            else:
                new_versions = [
                    v.model_dump() for v in b.versions
                ]
                if (
                    [v.model_dump() for v in cur.versions]
                    != new_versions
                    or cur.default_version != b.default_version
                    or cur.description != b.description
                ):
                    await cur.update(
                        versions=b.versions,
                        default_version=b.default_version,
                        description=b.description,
                    )
                    stats["updated"] += 1
        for name, cur in existing.items():
            if cur.managed and not cur.builtin and name not in wanted:
                await cur.delete()
                stats["deleted"] += 1
        logger.info("backend catalog sync: %s", stats)
        return stats
