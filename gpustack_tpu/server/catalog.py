"""Built-in model catalog (reference gpustack/server/catalog.py:50
init_model_catalog + assets catalog YAML): curated deployable models with
suggested TPU configs, served at GET /v2/model-catalog."""

from __future__ import annotations

from typing import Any, Dict, List

CATALOG: List[Dict[str, Any]] = [
    {
        "name": "Llama-3-8B-Instruct",
        "preset": "llama3-8b",
        "huggingface_repo_id": "meta-llama/Meta-Llama-3-8B-Instruct",
        "categories": ["llm", "chat"],
        "sizes": {"parameters_b": 8.0},
        "suggested": {
            "quantization": "int8",
            "max_seq_len": 8192,
            "chips": {"v5e": 1, "v5p": 1},
        },
    },
    {
        "name": "Llama-3-70B-Instruct",
        "preset": "llama3-70b",
        "huggingface_repo_id": "meta-llama/Meta-Llama-3-70B-Instruct",
        "categories": ["llm", "chat"],
        "sizes": {"parameters_b": 70.6},
        "suggested": {
            "quantization": "int8",
            "max_seq_len": 8192,
            "chips": {"v5e": 8, "v5p": 2},
        },
    },
    {
        "name": "Qwen2.5-7B-Instruct",
        "preset": "qwen2.5-7b",
        "huggingface_repo_id": "Qwen/Qwen2.5-7B-Instruct",
        "categories": ["llm", "chat"],
        "sizes": {"parameters_b": 7.6},
        "suggested": {
            "quantization": "int8",
            "max_seq_len": 32768,
            "chips": {"v5e": 2, "v5p": 1},
        },
    },
    {
        "name": "Mixtral-8x7B-Instruct",
        "preset": "mixtral-8x7b",
        "huggingface_repo_id": "mistralai/Mixtral-8x7B-Instruct-v0.1",
        "categories": ["llm", "chat", "moe"],
        "sizes": {"parameters_b": 46.7},
        "suggested": {
            "quantization": "int8",
            "max_seq_len": 32768,
            "chips": {"v5e": 4, "v5p": 1},
        },
    },
    {
        "name": "Whisper-Large-v3",
        "preset": "whisper-large-v3",
        "huggingface_repo_id": "openai/whisper-large-v3",
        "categories": ["audio", "speech-to-text"],
        "sizes": {"parameters_b": 1.5},
        "suggested": {
            "max_seq_len": 448,
            "chips": {"v5e": 1, "v5p": 1},
        },
    },
    {
        "name": "Whisper-Small",
        "preset": "whisper-small",
        "huggingface_repo_id": "openai/whisper-small",
        "categories": ["audio", "speech-to-text"],
        "sizes": {"parameters_b": 0.24},
        "suggested": {
            "max_seq_len": 448,
            "chips": {"v5e": 1, "v5p": 1},
        },
    },
    {
        "name": "DeepSeek-V2-Lite",
        "preset": "deepseek-v2-lite",
        "huggingface_repo_id": "deepseek-ai/DeepSeek-V2-Lite",
        "categories": ["llm", "chat", "moe"],
        "sizes": {"parameters_b": 15.7},
        "suggested": {
            "quantization": "int8",
            "max_seq_len": 32768,
            "chips": {"v5e": 2, "v5p": 1},
        },
    },
    {
        "name": "TTS-Base",
        "preset": "tts-base",
        "categories": ["audio", "text-to-speech"],
        "sizes": {"parameters_b": 0.007},
        "suggested": {
            "chips": {"v5e": 1, "v5p": 1},
        },
    },
    {
        "name": "Stable-Diffusion-XL",
        "preset": "sdxl-shaped",
        "huggingface_repo_id": "stabilityai/stable-diffusion-xl-base-1.0",
        "categories": ["image", "text-to-image"],
        "sizes": {"parameters_b": 3.5},
        "suggested": {
            "chips": {"v5e": 1, "v5p": 1},
        },
    },
    {
        "name": "Stable-Diffusion-1.5",
        "preset": "sd15-shaped",
        "huggingface_repo_id": "stable-diffusion-v1-5/stable-diffusion-v1-5",
        "categories": ["image", "text-to-image"],
        "sizes": {"parameters_b": 1.0},
        "suggested": {
            "chips": {"v5e": 1, "v5p": 1},
        },
    },
]


def get_catalog(category: str = "") -> List[Dict[str, Any]]:
    if not category:
        return CATALOG
    return [m for m in CATALOG if category in m["categories"]]
