"""Built-in model catalog (reference gpustack/server/catalog.py:50
init_model_catalog + assets/model-catalog.yaml, 127 entries): curated
deployable checkpoints with suggested TPU deploy configs, served at
GET /v2/model-catalog and deployable in one call via
POST /v2/model-catalog/deploy (the reference treats the catalog as the
primary deploy UX).

Entries are table-driven: one row per checkpoint —
(name, hf_repo, preset, params_b, categories, quant, ctx, v5e, v5p,
extras) — expanded into the wire dict. ``preset`` is set where the
in-repo engine ships a hermetic config of the same architecture
(models/config.py PRESETS); other entries deploy from the checkpoint's
own config.json via config_from_hf. Chat templates come from each
checkpoint's tokenizer_config.json at load (engine/tokenizer.py); GGUF
entries fall back to the neutral role-tag template unless a
tokenizer.json sidecar is present (engine/gguf.py).

Suggested chip counts assume int8 weight-only (1 byte/param) plus KV
headroom on v5e-16GB / v5p-95GB; they are starting points for the
evaluator (/v2/models/evaluate), which does the exact math.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

# (name, hf_repo, preset, params_b, categories, quant, ctx, v5e, v5p,
#  extras)
_ROWS = [
    # ---- Llama family ---------------------------------------------------
    ("Llama-3-8B-Instruct", "meta-llama/Meta-Llama-3-8B-Instruct",
     "llama3-8b", 8.0, ["llm", "chat"], "int8", 8192, 1, 1, {}),
    ("Llama-3-70B-Instruct", "meta-llama/Meta-Llama-3-70B-Instruct",
     "llama3-70b", 70.6, ["llm", "chat"], "int8", 8192, 8, 2,
     {"mesh_plan": "dp1xsp1xep1xtp8"}),
    ("Llama-3.1-8B-Instruct", "meta-llama/Llama-3.1-8B-Instruct",
     "llama3-8b", 8.0, ["llm", "chat", "long-context"], "int8",
     131072, 2, 1, {"rope": "llama3"}),
    ("Llama-3.1-70B-Instruct", "meta-llama/Llama-3.1-70B-Instruct",
     "llama3-70b", 70.6, ["llm", "chat", "long-context"], "int8",
     131072, 8, 2, {"mesh_plan": "dp1xsp1xep1xtp8", "rope": "llama3"}),
    ("Llama-3.2-1B-Instruct", "meta-llama/Llama-3.2-1B-Instruct",
     "", 1.2, ["llm", "chat"], "int8", 131072, 1, 1, {}),
    ("Llama-3.2-3B-Instruct", "meta-llama/Llama-3.2-3B-Instruct",
     "", 3.2, ["llm", "chat"], "int8", 131072, 1, 1, {}),
    ("Llama-3.3-70B-Instruct", "meta-llama/Llama-3.3-70B-Instruct",
     "llama3-70b", 70.6, ["llm", "chat"], "int8", 131072, 8, 2,
     {"mesh_plan": "dp1xsp1xep1xtp8"}),
    # ---- Qwen2.5 dense --------------------------------------------------
    ("Qwen2.5-0.5B-Instruct", "Qwen/Qwen2.5-0.5B-Instruct",
     "", 0.5, ["llm", "chat"], "int8", 32768, 1, 1, {}),
    ("Qwen2.5-1.5B-Instruct", "Qwen/Qwen2.5-1.5B-Instruct",
     "", 1.5, ["llm", "chat"], "int8", 32768, 1, 1, {}),
    ("Qwen2.5-3B-Instruct", "Qwen/Qwen2.5-3B-Instruct",
     "", 3.1, ["llm", "chat"], "int8", 32768, 1, 1, {}),
    ("Qwen2.5-7B-Instruct", "Qwen/Qwen2.5-7B-Instruct",
     "qwen2.5-7b", 7.6, ["llm", "chat"], "int8", 32768, 1, 1, {}),
    ("Qwen2.5-14B-Instruct", "Qwen/Qwen2.5-14B-Instruct",
     "", 14.8, ["llm", "chat"], "int8", 32768, 2, 1, {}),
    ("Qwen2.5-32B-Instruct", "Qwen/Qwen2.5-32B-Instruct",
     "", 32.8, ["llm", "chat"], "int8", 32768, 4, 1,
     {"mesh_plan": "dp1xsp1xep1xtp4"}),
    ("Qwen2.5-72B-Instruct", "Qwen/Qwen2.5-72B-Instruct",
     "", 72.7, ["llm", "chat"], "int8", 32768, 8, 2,
     {"mesh_plan": "dp1xsp1xep1xtp8"}),
    ("Qwen2.5-Coder-7B-Instruct", "Qwen/Qwen2.5-Coder-7B-Instruct",
     "qwen2.5-7b", 7.6, ["llm", "code"], "int8", 32768, 1, 1, {}),
    ("Qwen2.5-Coder-32B-Instruct", "Qwen/Qwen2.5-Coder-32B-Instruct",
     "", 32.8, ["llm", "code"], "int8", 32768, 4, 1,
     {"mesh_plan": "dp1xsp1xep1xtp4"}),
    # ---- Qwen3 ----------------------------------------------------------
    ("Qwen3-0.6B", "Qwen/Qwen3-0.6B", "", 0.6,
     ["llm", "chat"], "int8", 32768, 1, 1, {}),
    ("Qwen3-1.7B", "Qwen/Qwen3-1.7B", "", 1.7,
     ["llm", "chat"], "int8", 32768, 1, 1, {}),
    ("Qwen3-4B", "Qwen/Qwen3-4B", "", 4.0,
     ["llm", "chat"], "int8", 32768, 1, 1, {}),
    ("Qwen3-8B", "Qwen/Qwen3-8B", "qwen3-8b", 8.2,
     ["llm", "chat"], "int8", 32768, 1, 1, {}),
    ("Qwen3-14B", "Qwen/Qwen3-14B", "", 14.8,
     ["llm", "chat"], "int8", 32768, 2, 1, {}),
    ("Qwen3-32B", "Qwen/Qwen3-32B", "", 32.8,
     ["llm", "chat"], "int8", 32768, 4, 1,
     {"mesh_plan": "dp1xsp1xep1xtp4"}),
    ("Qwen3-30B-A3B", "Qwen/Qwen3-30B-A3B", "qwen3-30b-a3b", 30.5,
     ["llm", "chat", "moe"], "int8", 32768, 4, 1,
     {"mesh_plan": "dp1xsp1xep4xtp1"}),
    ("Qwen3-235B-A22B", "Qwen/Qwen3-235B-A22B", "", 235.0,
     ["llm", "chat", "moe"], "int8", 32768, 32, 4,
     {"mesh_plan": "dp1xsp1xep8xtp4", "multi_host": True}),
    ("Qwen2-57B-A14B-Instruct", "Qwen/Qwen2-57B-A14B-Instruct",
     "", 57.4, ["llm", "chat", "moe"], "int8", 32768, 8, 1,
     {"mesh_plan": "dp1xsp1xep4xtp2"}),
    # ---- Gemma ----------------------------------------------------------
    ("Gemma-2-2B-Instruct", "google/gemma-2-2b-it", "", 2.6,
     ["llm", "chat"], "int8", 8192, 1, 1, {}),
    ("Gemma-2-9B-Instruct", "google/gemma-2-9b-it", "gemma2-9b", 9.2,
     ["llm", "chat"], "int8", 8192, 1, 1, {}),
    ("Gemma-2-27B-Instruct", "google/gemma-2-27b-it", "", 27.2,
     ["llm", "chat"], "int8", 8192, 4, 1,
     {"mesh_plan": "dp1xsp1xep1xtp4"}),
    ("Gemma-3-1B-Instruct", "google/gemma-3-1b-it", "", 1.0,
     ["llm", "chat"], "int8", 32768, 1, 1, {}),
    ("Gemma-3-4B-Instruct", "google/gemma-3-4b-it", "", 4.3,
     ["llm", "chat"], "int8", 131072, 1, 1, {}),
    ("Gemma-3-12B-Instruct", "google/gemma-3-12b-it", "", 12.2,
     ["llm", "chat"], "int8", 131072, 2, 1, {}),
    ("Gemma-3-27B-Instruct", "google/gemma-3-27b-it", "", 27.4,
     ["llm", "chat"], "int8", 131072, 4, 1,
     {"mesh_plan": "dp1xsp1xep1xtp4"}),
    # ---- DeepSeek -------------------------------------------------------
    ("DeepSeek-V2-Lite", "deepseek-ai/DeepSeek-V2-Lite",
     "deepseek-v2-lite", 15.7, ["llm", "chat", "moe"], "int8",
     32768, 2, 1, {"attention": "mla", "rope": "yarn"}),
    ("DeepSeek-V2-Lite-Chat", "deepseek-ai/DeepSeek-V2-Lite-Chat",
     "deepseek-v2-lite", 15.7, ["llm", "chat", "moe"], "int8",
     32768, 2, 1, {"attention": "mla", "rope": "yarn"}),
    ("DeepSeek-V2-Chat", "deepseek-ai/DeepSeek-V2-Chat", "", 236.0,
     ["llm", "chat", "moe"], "int8", 131072, 32, 4,
     {"attention": "mla", "rope": "yarn",
      "mesh_plan": "dp1xsp1xep8xtp4", "multi_host": True}),
    ("DeepSeek-V3", "deepseek-ai/DeepSeek-V3", "", 671.0,
     ["llm", "chat", "moe"], "int8", 131072, 64, 8,
     {"attention": "mla", "rope": "yarn",
      "mesh_plan": "dp1xsp1xep16xtp4", "multi_host": True}),
    ("DeepSeek-R1", "deepseek-ai/DeepSeek-R1", "", 671.0,
     ["llm", "chat", "moe", "reasoning"], "int8", 131072, 64, 8,
     {"attention": "mla", "rope": "yarn",
      "mesh_plan": "dp1xsp1xep16xtp4", "multi_host": True}),
    ("DeepSeek-R1-Distill-Qwen-1.5B",
     "deepseek-ai/DeepSeek-R1-Distill-Qwen-1.5B", "", 1.8,
     ["llm", "chat", "reasoning"], "int8", 131072, 1, 1, {}),
    ("DeepSeek-R1-Distill-Qwen-7B",
     "deepseek-ai/DeepSeek-R1-Distill-Qwen-7B", "qwen2.5-7b", 7.6,
     ["llm", "chat", "reasoning"], "int8", 131072, 1, 1, {}),
    ("DeepSeek-R1-Distill-Qwen-14B",
     "deepseek-ai/DeepSeek-R1-Distill-Qwen-14B", "", 14.8,
     ["llm", "chat", "reasoning"], "int8", 131072, 2, 1, {}),
    ("DeepSeek-R1-Distill-Qwen-32B",
     "deepseek-ai/DeepSeek-R1-Distill-Qwen-32B", "", 32.8,
     ["llm", "chat", "reasoning"], "int8", 131072, 4, 1,
     {"mesh_plan": "dp1xsp1xep1xtp4"}),
    ("DeepSeek-R1-Distill-Llama-8B",
     "deepseek-ai/DeepSeek-R1-Distill-Llama-8B", "llama3-8b", 8.0,
     ["llm", "chat", "reasoning"], "int8", 131072, 1, 1, {}),
    ("DeepSeek-R1-Distill-Llama-70B",
     "deepseek-ai/DeepSeek-R1-Distill-Llama-70B", "llama3-70b", 70.6,
     ["llm", "chat", "reasoning"], "int8", 131072, 8, 2,
     {"mesh_plan": "dp1xsp1xep1xtp8"}),
    # ---- GPT-OSS (BASELINE.md headline anchors) ------------------------
    ("GPT-OSS-20B", "openai/gpt-oss-20b", "gpt-oss-20b", 20.9,
     ["llm", "chat", "moe", "reasoning"], "int8", 131072, 2, 1,
     {"attention": "sinks+sliding", "rope": "yarn",
      "mesh_plan": "dp1xsp1xep2xtp1"}),
    ("GPT-OSS-120B", "openai/gpt-oss-120b", "gpt-oss-120b", 116.8,
     ["llm", "chat", "moe", "reasoning"], "int8", 131072, 16, 2,
     {"attention": "sinks+sliding", "rope": "yarn",
      "mesh_plan": "dp1xsp1xep8xtp2"}),
    # ---- Mistral / Mixtral ---------------------------------------------
    ("Mistral-7B-Instruct-v0.3", "mistralai/Mistral-7B-Instruct-v0.3",
     "", 7.2, ["llm", "chat"], "int8", 32768, 1, 1, {}),
    ("Mixtral-8x7B-Instruct", "mistralai/Mixtral-8x7B-Instruct-v0.1",
     "mixtral-8x7b", 46.7, ["llm", "chat", "moe"], "int8", 32768,
     4, 1, {"mesh_plan": "dp1xsp1xep4xtp1"}),
    ("Mixtral-8x22B-Instruct", "mistralai/Mixtral-8x22B-Instruct-v0.1",
     "", 141.0, ["llm", "chat", "moe"], "int8", 65536, 16, 2,
     {"mesh_plan": "dp1xsp1xep8xtp2", "multi_host": True}),
    # ---- GGUF checkpoints (served natively: engine/gguf.py K-quants) ---
    ("Llama-3.1-8B-Instruct-GGUF-Q4_K_M",
     "bartowski/Meta-Llama-3.1-8B-Instruct-GGUF", "", 8.0,
     ["llm", "chat", "gguf"], "", 131072, 1, 1,
     {"file": "Meta-Llama-3.1-8B-Instruct-Q4_K_M.gguf",
      "note": "Q4_K_M dequantized to bf16 at load; rope_freqs honored"}),
    ("Qwen2.5-7B-Instruct-GGUF-Q4_K_M",
     "Qwen/Qwen2.5-7B-Instruct-GGUF", "", 7.6,
     ["llm", "chat", "gguf"], "", 32768, 1, 1,
     {"file": "qwen2.5-7b-instruct-q4_k_m.gguf"}),
    ("Qwen2.5-72B-Instruct-GGUF-Q4_K_M",
     "Qwen/Qwen2.5-72B-Instruct-GGUF", "", 72.7,
     ["llm", "chat", "gguf"], "", 32768, 8, 1,
     {"file": "qwen2.5-72b-instruct-q4_k_m-*.gguf",
      "note": "wildcard matches every gguf-split shard; serving "
              "resolves them via split.count (engine/gguf.py)"}),
    ("Gemma-2-9B-Instruct-GGUF-Q6_K", "bartowski/gemma-2-9b-it-GGUF",
     "", 9.2, ["llm", "chat", "gguf"], "", 8192, 1, 1,
     {"file": "gemma-2-9b-it-Q6_K.gguf"}),
    # ---- Embeddings -----------------------------------------------------
    ("BGE-M3", "BAAI/bge-m3", "", 0.57,
     ["embedding"], "", 8192, 1, 1, {}),
    ("BGE-Large-EN-v1.5", "BAAI/bge-large-en-v1.5", "", 0.34,
     ["embedding"], "", 512, 1, 1, {}),
    ("GTE-Qwen2-1.5B-Instruct", "Alibaba-NLP/gte-Qwen2-1.5B-instruct",
     "", 1.5, ["embedding"], "", 32768, 1, 1, {}),
    ("E5-Mistral-7B-Instruct", "intfloat/e5-mistral-7b-instruct",
     "", 7.1, ["embedding"], "int8", 32768, 1, 1, {}),
    ("Jina-Embeddings-v2-Base", "jinaai/jina-embeddings-v2-base-en",
     "", 0.14, ["embedding"], "", 8192, 1, 1, {}),
    # ---- Rerankers ------------------------------------------------------
    ("BGE-Reranker-v2-M3", "BAAI/bge-reranker-v2-m3", "", 0.57,
     ["reranker"], "", 8192, 1, 1, {}),
    ("BGE-Reranker-Large", "BAAI/bge-reranker-large", "", 0.56,
     ["reranker"], "", 512, 1, 1, {}),
    # ---- Speech-to-text -------------------------------------------------
    ("Whisper-Large-v3", "openai/whisper-large-v3",
     "whisper-large-v3", 1.5, ["audio", "speech-to-text"], "",
     448, 1, 1, {}),
    ("Whisper-Large-v3-Turbo", "openai/whisper-large-v3-turbo",
     "", 0.8, ["audio", "speech-to-text"], "", 448, 1, 1, {}),
    ("Whisper-Medium", "openai/whisper-medium", "", 0.77,
     ["audio", "speech-to-text"], "", 448, 1, 1, {}),
    ("Whisper-Small", "openai/whisper-small", "whisper-small", 0.24,
     ["audio", "speech-to-text"], "", 448, 1, 1, {}),
    ("Whisper-Base", "openai/whisper-base", "", 0.07,
     ["audio", "speech-to-text"], "", 448, 1, 1, {}),
    # ---- Text-to-speech -------------------------------------------------
    ("TTS-Base", "", "tts-base", 0.007,
     ["audio", "text-to-speech"], "", 0, 1, 1, {}),
    # ---- Image generation ----------------------------------------------
    ("Stable-Diffusion-XL", "stabilityai/stable-diffusion-xl-base-1.0",
     "sdxl-shaped", 3.5, ["image", "text-to-image"], "", 0, 1, 1, {}),
    ("Stable-Diffusion-1.5",
     "stable-diffusion-v1-5/stable-diffusion-v1-5", "sd15-shaped",
     1.0, ["image", "text-to-image"], "", 0, 1, 1, {}),
    # ---- Vision-language ------------------------------------------------
    ("LLaVA-1.5-7B", "llava-hf/llava-1.5-7b-hf", "", 7.1,
     ["llm", "vlm", "chat"], "int8", 4096, 1, 1,
     {"note": "image_url content parts via vision-token splicing"}),
    ("LLaVA-1.5-13B", "llava-hf/llava-1.5-13b-hf", "", 13.4,
     ["llm", "vlm", "chat"], "int8", 4096, 2, 1, {}),
]


def _expand(row) -> Dict[str, Any]:
    (name, repo, preset, params_b, cats, quant, ctx, v5e, v5p,
     extras) = row
    suggested: Dict[str, Any] = {
        "chips": {"v5e": v5e, "v5p": v5p},
    }
    if quant:
        suggested["quantization"] = quant
    if ctx:
        suggested["max_seq_len"] = ctx
    for key in ("mesh_plan", "multi_host", "file"):
        if key in extras:
            suggested[key] = extras[key]
    entry: Dict[str, Any] = {
        "name": name,
        "categories": cats,
        "sizes": {"parameters_b": params_b},
        "suggested": suggested,
    }
    if repo:
        entry["huggingface_repo_id"] = repo
    if preset:
        entry["preset"] = preset
    for key in ("attention", "rope", "note"):
        if key in extras:
            entry[key] = extras[key]
    return entry


CATALOG: List[Dict[str, Any]] = [_expand(r) for r in _ROWS]


def get_catalog(category: str = "") -> List[Dict[str, Any]]:
    if not category:
        return CATALOG
    return [m for m in CATALOG if category in m["categories"]]


def find_entry(name: str) -> Optional[Dict[str, Any]]:
    return next((m for m in CATALOG if m["name"] == name), None)


def model_fields_from_entry(
    entry: Dict[str, Any], overrides: Optional[Dict[str, Any]] = None
) -> Dict[str, Any]:
    """Deploy defaults for POST /v2/models derived from a catalog entry
    (the catalog-as-primary-UX flow, reference server/catalog.py:50):
    source, quantization, context and mesh plan come from ``suggested``;
    ``overrides`` (user-provided request fields) win field-by-field."""
    suggested = entry.get("suggested", {})
    fields: Dict[str, Any] = {
        "name": entry["name"].lower(),
        "categories": entry.get("categories", []),
        "replicas": 1,
    }
    if entry.get("preset"):
        fields["preset"] = entry["preset"]
    elif entry.get("huggingface_repo_id"):
        fields["huggingface_repo_id"] = entry["huggingface_repo_id"]
        if suggested.get("file"):
            fields["huggingface_filename"] = suggested["file"]
    if suggested.get("quantization"):
        fields["quantization"] = suggested["quantization"]
    if suggested.get("max_seq_len"):
        fields["max_seq_len"] = suggested["max_seq_len"]
    if suggested.get("mesh_plan"):
        fields["mesh_plan"] = suggested["mesh_plan"]
    fields.update(overrides or {})
    return fields
