"""HA coordinator: leader election + cross-instance event propagation.

Reference parity (gpustack/server/coordinator/base.py:94 Coordinator ABC;
local.py:17 LocalCoordinator; distributed impls ship as plugins,
server/server.py:1166-1194; lost leadership exits the process,
server/server.py:1296-1304).

Single-server deployments use LocalCoordinator (always leader, in-process
bus only). A distributed coordinator implements acquire/renew over a
shared store (Postgres advisory locks, Redis leases) and republishes bus
events across instances; leader-only tasks (scheduler, controllers)
start/stop on leadership transitions.
"""

from __future__ import annotations

import abc
import asyncio
import logging
import os
from typing import Awaitable, Callable, List, Optional

from gpustack_tpu.server.bus import Event

logger = logging.getLogger(__name__)


class Coordinator(abc.ABC):
    """Leadership + cross-instance pub/sub contract."""

    @abc.abstractmethod
    async def start(self) -> None:
        """Begin participating (election loops, subscriptions)."""

    @abc.abstractmethod
    async def stop(self) -> None:
        """Stop participating; release leadership if held."""

    @property
    @abc.abstractmethod
    def is_leader(self) -> bool:
        """Whether this instance currently holds leadership."""

    @abc.abstractmethod
    def on_leadership_change(
        self, callback: Callable[[bool], Awaitable[None]]
    ) -> None:
        """Register a callback invoked with the new leadership state."""

    @abc.abstractmethod
    def publish_remote(self, event: Event) -> None:
        """Propagate a bus event to peer server instances (id-only is
        sufficient: receivers re-fetch from the shared DB — reference
        server/bus.py:312-414 ChangeDetector pattern)."""


class LocalCoordinator(Coordinator):
    """Single-server: always leader, no peers."""

    def __init__(self) -> None:
        self._callbacks: List[Callable[[bool], Awaitable[None]]] = []
        self._started = False
        self._late_tasks: set = set()

    async def start(self) -> None:
        self._started = True
        for cb in self._callbacks:
            await cb(True)

    async def stop(self) -> None:
        self._started = False

    @property
    def is_leader(self) -> bool:
        return True

    def on_leadership_change(
        self, callback: Callable[[bool], Awaitable[None]]
    ) -> None:
        self._callbacks.append(callback)
        if self._started:
            # register-after-start still fires: get_running_loop, not
            # the deprecated get_event_loop (which creates a NEW loop
            # when called off-loop and silently never runs the task).
            # The loop holds only a weak reference to tasks — keep a
            # strong one until done or GC can collect it mid-flight
            # and the component never hears on_leadership(True)
            task = asyncio.get_running_loop().create_task(
                callback(True), name="coordinator-late-callback"
            )
            self._late_tasks.add(task)
            task.add_done_callback(self._late_tasks.discard)

    def publish_remote(self, event: Event) -> None:
        pass  # no peers


class LeaseCoordinator(Coordinator):
    """TTL-lease leader election over the shared sqlite/Postgres DB.

    Multi-server HA without external dependencies: one row in a
    ``leadership`` table holds (holder, expires_at); the leader renews at
    ttl/3, followers try to acquire when the lease lapses. Losing a held
    lease is fatal (reference semantics: os._exit so leader-only tasks
    can't split-brain, server/server.py:1296-1304).
    """

    def __init__(
        self, db, identity: str = "", ttl: float = 0.0, bus=None
    ):
        import secrets
        import socket

        self.db = db
        self.bus = bus
        if not ttl:
            # operational knob (reference envs/__init__.py pattern);
            # e2e failover tests shrink it to keep wall-clock sane
            ttl = float(os.environ.get("GPUSTACK_TPU_HA_TTL", "15"))
        # hostname + random suffix: pids collide across containers (every
        # process is pid 1), which would let a stale leader renew against
        # its successor's row and split-brain
        self.identity = identity or (
            f"{socket.gethostname()}-{os.getpid()}-"
            f"{secrets.token_hex(4)}"
        )
        self.ttl = ttl
        self._leader = False
        self._callbacks: List[Callable[[bool], Awaitable[None]]] = []
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        await self.db.execute(
            "CREATE TABLE IF NOT EXISTS leadership ("
            "id INTEGER PRIMARY KEY CHECK (id = 1), "
            "holder TEXT, expires_at REAL)"
        )
        self._task = asyncio.create_task(self._loop(), name="coordinator")

    async def stop(self) -> None:
        # await the cancelled election task BEFORE touching the lease
        # row: cancel() alone leaves a mid-renewal UPDATE in flight
        # that could re-extend the lease AFTER the delete below, making
        # graceful shutdown hand leadership over only after a full TTL
        # instead of immediately
        task, self._task = self._task, None
        if task:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        if self._leader:
            self._leader = False
            await self.db.execute(
                "DELETE FROM leadership WHERE holder = ?", (self.identity,)
            )

    @property
    def is_leader(self) -> bool:
        return self._leader

    def on_leadership_change(
        self, callback: Callable[[bool], Awaitable[None]]
    ) -> None:
        self._callbacks.append(callback)

    def publish_remote(self, event: Event) -> None:
        # same-DB deployments see each other's state via the DB; watch
        # streams re-list on RESYNC. Cross-instance low-latency event
        # fan-out (Redis/PG LISTEN) slots in here.
        pass

    async def _loop(self) -> None:
        import time

        while True:
            try:
                now = time.time()
                if self._leader:
                    # renew-then-verify instead of UPDATE..RETURNING:
                    # the container's sqlite (3.34) predates RETURNING
                    # (3.35+). The renewal UPDATE is atomic; the
                    # follow-up SELECT can only disagree if the lease
                    # was ALREADY lost — exactly the case that must be
                    # fatal.
                    await self.db.execute(
                        "UPDATE leadership SET expires_at = ? "
                        "WHERE id = 1 AND holder = ?",
                        (now + self.ttl, self.identity),
                    )
                    rows = await self.db.execute(
                        "SELECT holder FROM leadership WHERE id = 1"
                    )
                    if not rows or rows[0]["holder"] != self.identity:
                        # lease lost while held: fatal, never split-brain
                        logger.error(
                            "leadership lease lost; exiting (reference "
                            "semantics: os._exit on lost lease)"
                        )
                        os._exit(1)
                else:
                    # atomic conditional upsert (steal only an expired
                    # lease), then read back who holds it — a fresh
                    # lease cannot be stolen between the two statements
                    await self.db.execute(
                        "INSERT INTO leadership (id, holder, expires_at) "
                        "VALUES (1, ?, ?) "
                        "ON CONFLICT(id) DO UPDATE SET "
                        "holder = excluded.holder, "
                        "expires_at = excluded.expires_at "
                        "WHERE leadership.expires_at < ?",
                        (self.identity, now + self.ttl, now),
                    )
                    rows = await self.db.execute(
                        "SELECT holder FROM leadership WHERE id = 1"
                    )
                    if rows and rows[0]["holder"] == self.identity:
                        logger.info("acquired leadership")
                        self._leader = True
                        for cb in self._callbacks:
                            await cb(True)
                    elif self.bus is not None:
                        # follower: the leader's writes land in the shared
                        # DB but not on this instance's in-process bus —
                        # force local watchers to re-list every cycle
                        # (poll-based propagation; low-latency fan-out via
                        # PG LISTEN/Redis slots into publish_remote later)
                        from gpustack_tpu.server.bus import (
                            Event as _Event,
                            EventType as _EventType,
                        )

                        self.bus.publish(
                            _Event(kind="*", type=_EventType.RESYNC)
                        )
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("coordinator iteration failed")
            await asyncio.sleep(self.ttl / 3)
