"""HA coordinator: leader election + cross-instance event propagation.

Reference parity (gpustack/server/coordinator/base.py:94 Coordinator ABC;
local.py:17 LocalCoordinator; distributed impls ship as plugins,
server/server.py:1166-1194; lost leadership exits the process,
server/server.py:1296-1304).

Single-server deployments use LocalCoordinator (always leader, in-process
bus only). A distributed coordinator implements acquire/renew over a
shared store (Postgres advisory locks, Redis leases) and republishes bus
events across instances; leader-only tasks (scheduler, controllers)
start/stop on leadership transitions.
"""

from __future__ import annotations

import abc
import asyncio
import logging
import os
from typing import Awaitable, Callable, List, Optional

from gpustack_tpu.server.bus import Event

logger = logging.getLogger(__name__)


class Coordinator(abc.ABC):
    """Leadership + cross-instance pub/sub contract."""

    @abc.abstractmethod
    async def start(self) -> None:
        """Begin participating (election loops, subscriptions)."""

    @abc.abstractmethod
    async def stop(self) -> None:
        """Stop participating; release leadership if held."""

    @property
    @abc.abstractmethod
    def is_leader(self) -> bool:
        """Whether this instance currently holds leadership."""

    @abc.abstractmethod
    def on_leadership_change(
        self, callback: Callable[[bool], Awaitable[None]]
    ) -> None:
        """Register a callback invoked with the new leadership state."""

    @abc.abstractmethod
    def publish_remote(self, event: Event) -> None:
        """Propagate a bus event to peer server instances (id-only is
        sufficient: receivers re-fetch from the shared DB — reference
        server/bus.py:312-414 ChangeDetector pattern)."""


class LocalCoordinator(Coordinator):
    """Single-server: always leader, no peers."""

    def __init__(self) -> None:
        self._callbacks: List[Callable[[bool], Awaitable[None]]] = []
        self._started = False

    async def start(self) -> None:
        self._started = True
        for cb in self._callbacks:
            await cb(True)

    async def stop(self) -> None:
        self._started = False

    @property
    def is_leader(self) -> bool:
        return True

    def on_leadership_change(
        self, callback: Callable[[bool], Awaitable[None]]
    ) -> None:
        self._callbacks.append(callback)
        if self._started:
            asyncio.get_event_loop().create_task(callback(True))

    def publish_remote(self, event: Event) -> None:
        pass  # no peers


class LeaseCoordinator(Coordinator):
    """TTL-lease leader election over the shared sqlite/Postgres DB.

    Multi-server HA without external dependencies: one row in a
    ``leadership`` table holds (holder, expires_at); the leader renews at
    ttl/3, followers try to acquire when the lease lapses. Losing a held
    lease is fatal (reference semantics: os._exit so leader-only tasks
    can't split-brain, server/server.py:1296-1304).
    """

    def __init__(
        self, db, identity: str = "", ttl: float = 0.0, bus=None
    ):
        import secrets
        import socket

        self.db = db
        self.bus = bus
        if not ttl:
            # operational knob (reference envs/__init__.py pattern);
            # e2e failover tests shrink it to keep wall-clock sane
            ttl = float(os.environ.get("GPUSTACK_TPU_HA_TTL", "15"))
        # hostname + random suffix: pids collide across containers (every
        # process is pid 1), which would let a stale leader renew against
        # its successor's row and split-brain
        self.identity = identity or (
            f"{socket.gethostname()}-{os.getpid()}-"
            f"{secrets.token_hex(4)}"
        )
        self.ttl = ttl
        self._leader = False
        self._callbacks: List[Callable[[bool], Awaitable[None]]] = []
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        await self.db.execute(
            "CREATE TABLE IF NOT EXISTS leadership ("
            "id INTEGER PRIMARY KEY CHECK (id = 1), "
            "holder TEXT, expires_at REAL)"
        )
        self._task = asyncio.create_task(self._loop(), name="coordinator")

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
        if self._leader:
            await self.db.execute(
                "DELETE FROM leadership WHERE holder = ?", (self.identity,)
            )

    @property
    def is_leader(self) -> bool:
        return self._leader

    def on_leadership_change(
        self, callback: Callable[[bool], Awaitable[None]]
    ) -> None:
        self._callbacks.append(callback)

    def publish_remote(self, event: Event) -> None:
        # same-DB deployments see each other's state via the DB; watch
        # streams re-list on RESYNC. Cross-instance low-latency event
        # fan-out (Redis/PG LISTEN) slots in here.
        pass

    async def _loop(self) -> None:
        import time

        while True:
            try:
                now = time.time()
                if self._leader:
                    rows = await self.db.execute(
                        "UPDATE leadership SET expires_at = ? "
                        "WHERE id = 1 AND holder = ? RETURNING holder",
                        (now + self.ttl, self.identity),
                    )
                    if not rows:
                        # lease lost while held: fatal, never split-brain
                        logger.error(
                            "leadership lease lost; exiting (reference "
                            "semantics: os._exit on lost lease)"
                        )
                        os._exit(1)
                else:
                    rows = await self.db.execute(
                        "INSERT INTO leadership (id, holder, expires_at) "
                        "VALUES (1, ?, ?) "
                        "ON CONFLICT(id) DO UPDATE SET "
                        "holder = excluded.holder, "
                        "expires_at = excluded.expires_at "
                        "WHERE leadership.expires_at < ? "
                        "RETURNING holder",
                        (self.identity, now + self.ttl, now),
                    )
                    if rows and rows[0]["holder"] == self.identity:
                        logger.info("acquired leadership")
                        self._leader = True
                        for cb in self._callbacks:
                            await cb(True)
                    elif self.bus is not None:
                        # follower: the leader's writes land in the shared
                        # DB but not on this instance's in-process bus —
                        # force local watchers to re-list every cycle
                        # (poll-based propagation; low-latency fan-out via
                        # PG LISTEN/Redis slots into publish_remote later)
                        from gpustack_tpu.server.bus import (
                            Event as _Event,
                            EventType as _EventType,
                        )

                        self.bus.publish(
                            _Event(kind="*", type=_EventType.RESYNC)
                        )
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("coordinator iteration failed")
            await asyncio.sleep(self.ttl / 3)
