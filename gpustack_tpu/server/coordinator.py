"""HA coordinator: epoch-fenced leader election + change-log propagation.

Reference parity (gpustack/server/coordinator/base.py:94 Coordinator ABC;
local.py:17 LocalCoordinator; distributed impls ship as plugins,
server/server.py:1166-1194; lost leadership exits the process,
server/server.py:1296-1304).

Single-server deployments use LocalCoordinator (always leader, in-process
bus only). LeaseCoordinator implements multi-server HA over the shared
DB with three mechanisms:

- **TTL-lease election with fencing epochs**: one ``leadership`` row
  holds (holder, expires_at, epoch); the conditional upsert
  (orm/sql.py ``lease_upsert`` — per-dialect spellings) steals only an
  expired lease and bumps the monotonic ``epoch`` on every acquisition.
  Leader-only writers stamp their writes with the acquired epoch
  (orm/fencing.py), so a deposed-but-not-yet-exited leader's queued
  write rejects atomically instead of clobbering its successor's state.
- **Injectable fatal path**: losing a held lease is fatal (reference
  semantics — leader-only tasks must never split-brain); the default
  ``os._exit(1)`` is an injectable ``fatal_hook`` so the in-process
  chaos harness can assert the fatal path without dying with it.
- **Change-log propagation**: every Record write commits an entry into
  the shared ``change_log`` table INSIDE its own transaction
  (orm/changelog.py — a SIGKILL'd server loses zero committed events;
  the old in-memory outbox survives only as a migration shim for
  non-transactional bindings); every server tails the others' entries
  each replication cycle and re-fetches the touched rows, republishing
  full events on its local bus. Follower watch fan-out stays O(events)
  instead of the old RESYNC-every-TTL/3 forced re-list (O(tables) at
  scale), and the leader finally *hears* writes that landed through a
  follower's API.

Election observability: ``election_tap_hook`` (module-level, harness
style like worker_request.rpc_fault_hook) receives every
acquired/renewed/lost/released event losslessly — the chaos harness
builds its at-most-one-leader invariant from it.
"""

from __future__ import annotations

import abc
import asyncio
import json
import logging
import os
import time
from collections import deque
from typing import Awaitable, Callable, Deque, List, Optional, Tuple

from gpustack_tpu.server.bus import Event, EventType

logger = logging.getLogger(__name__)

# Lossless election-event tap (chaos harness): called synchronously with
# {ts, identity, event, epoch, expires_at, ttl} for every election
# transition. Module-level injectable, same idiom as
# worker_request.rpc_fault_hook.
election_tap_hook: Optional[Callable[[dict], None]] = None


def _os_exit_fatal(coordinator: "LeaseCoordinator") -> None:
    """Production fatal path: a leader that lost its lease must die
    before its leader-only tasks can split-brain (reference
    server/server.py:1296-1304)."""
    os._exit(1)


# replaceable process-wide default for newly constructed coordinators
# (the chaos harness swaps it BEFORE booting servers, so even the very
# first election cycle is covered); an explicit ``fatal_hook`` argument
# always wins
default_fatal_hook: Callable[["LeaseCoordinator"], None] = _os_exit_fatal

# tail batch bound: more pending entries than this in one cycle degrades
# to a RESYNC (re-list) instead of a fetch storm
TAIL_BATCH = 1000

# the never-replicated kinds live with the transactional append logic
# (orm/changelog.py); re-exported here for existing importers
from gpustack_tpu.orm.changelog import (  # noqa: E402
    REPLICATION_SKIP_KINDS,
)


class Coordinator(abc.ABC):
    """Leadership + cross-instance pub/sub contract."""

    #: fencing epoch of the held lease (0 = not leading / non-HA)
    epoch: int = 0
    #: leadership transitions observed by this instance (acquired+lost)
    transitions: int = 0

    @abc.abstractmethod
    async def start(self) -> None:
        """Begin participating (election loops, subscriptions)."""

    @abc.abstractmethod
    async def stop(self) -> None:
        """Stop participating; release leadership if held."""

    @property
    @abc.abstractmethod
    def is_leader(self) -> bool:
        """Whether this instance currently holds leadership."""

    @abc.abstractmethod
    def on_leadership_change(
        self, callback: Callable[[bool], Awaitable[None]]
    ) -> None:
        """Register a callback invoked with the new leadership state."""

    @abc.abstractmethod
    def publish_remote(self, event: Event) -> None:
        """Propagate a bus event to peer server instances (id-only is
        sufficient: receivers re-fetch from the shared DB — reference
        server/bus.py:312-414 ChangeDetector pattern)."""


class LocalCoordinator(Coordinator):
    """Single-server: always leader, no peers."""

    def __init__(self) -> None:
        self._callbacks: List[Callable[[bool], Awaitable[None]]] = []
        self._started = False
        self._late_tasks: set = set()

    async def start(self) -> None:
        self._started = True
        for cb in self._callbacks:
            await cb(True)

    async def stop(self) -> None:
        self._started = False

    @property
    def is_leader(self) -> bool:
        return True

    def on_leadership_change(
        self, callback: Callable[[bool], Awaitable[None]]
    ) -> None:
        self._callbacks.append(callback)
        if self._started:
            # register-after-start still fires: get_running_loop, not
            # the deprecated get_event_loop (which creates a NEW loop
            # when called off-loop and silently never runs the task).
            # The loop holds only a weak reference to tasks — keep a
            # strong one until done or GC can collect it mid-flight
            # and the component never hears on_leadership(True)
            task = asyncio.get_running_loop().create_task(
                callback(True), name="coordinator-late-callback"
            )
            self._late_tasks.add(task)
            task.add_done_callback(self._late_tasks.discard)

    def publish_remote(self, event: Event) -> None:
        pass  # no peers


class LeaseCoordinator(Coordinator):
    """TTL-lease leader election over the shared sqlite/Postgres DB.

    Multi-server HA without external dependencies: one row in a
    ``leadership`` table holds (holder, expires_at, epoch); the leader
    renews at ttl/3, followers try to acquire when the lease lapses.
    Losing a held lease is fatal (reference semantics: os._exit so
    leader-only tasks can't split-brain, server/server.py:1296-1304) —
    via the injectable ``fatal_hook`` so tests can assert the path
    in-process. Every acquisition bumps the monotonic fencing ``epoch``
    consumed by orm/fencing.py.
    """

    def __init__(
        self,
        db,
        identity: str = "",
        ttl: float = 0.0,
        bus=None,
        fatal_hook: Optional[
            Callable[["LeaseCoordinator"], None]
        ] = None,
    ):
        import secrets
        import socket

        self.db = db
        self.bus = bus
        # operational knob: Config.ha_ttl (env GPUSTACK_TPU_HA_TTL);
        # e2e failover tests shrink it to keep wall-clock sane
        self.ttl = ttl or 15.0
        # hostname + random suffix: pids collide across containers (every
        # process is pid 1), which would let a stale leader renew against
        # its successor's row and split-brain
        self.identity = identity or (
            f"{socket.gethostname()}-{os.getpid()}-"
            f"{secrets.token_hex(4)}"
        )
        self.fatal_hook = fatal_hook or default_fatal_hook
        self.epoch = 0
        self.transitions = 0
        self._leader = False
        self._callbacks: List[Callable[[bool], Awaitable[None]]] = []
        self._task: Optional[asyncio.Task] = None
        self._repl_task: Optional[asyncio.Task] = None
        # chaos harness: clearing this stalls the ELECTION loop (a
        # leader whose event loop hung past TTL, emulated) without
        # touching anything else
        self.hang_gate = asyncio.Event()
        self.hang_gate.set()
        # change-log replication: (kind, event_type, id, changes_json)
        self._outbox: Deque[
            Tuple[str, str, int, Optional[str]]
        ] = deque()
        self._outbox_event = asyncio.Event()
        self._last_seen = 0
        self._republishing = False
        self._prune_at = 0.0

    async def start(self) -> None:
        from gpustack_tpu.orm.changelog import change_log_ddl
        from gpustack_tpu.orm.record import PK_CLAUSE

        await self.db.execute(
            "CREATE TABLE IF NOT EXISTS leadership ("
            "id INTEGER PRIMARY KEY CHECK (id = 1), "
            "holder TEXT, expires_at REAL, epoch INTEGER DEFAULT 0)"
        )
        await self.db.execute(
            change_log_ddl(PK_CLAUSE[self.db.dialect])
        )
        # start tailing at the PRESENT: everything already in the DB is
        # covered by the initial list every watch/controller performs
        rows = await self.db.execute(
            "SELECT COALESCE(MAX(id), 0) AS top FROM change_log"
        )
        self._last_seen = int(rows[0]["top"]) if rows else 0
        # from here on, every Record write through this Database
        # appends its change-log entry INSIDE its own transaction
        # (orm/record.py _append_change) — a crashed process loses
        # zero committed events; the bus tap below degrades to a
        # post-commit no-op. Set only after the table exists.
        self.db.changelog_origin = self.identity
        self._task = asyncio.create_task(self._loop(), name="coordinator")
        self._repl_task = asyncio.create_task(
            self._repl_loop(), name="coordinator-repl"
        )

    async def stop(self) -> None:
        # await the cancelled election task BEFORE touching the lease
        # row: cancel() alone leaves a mid-renewal UPDATE in flight
        # that could re-extend the lease AFTER the delete below, making
        # graceful shutdown hand leadership over only after a full TTL
        # instead of immediately
        await self._cancel_tasks()
        # migration-shim flush: with transactional appends the outbox
        # is always empty (every committed write carried its own
        # entry); legacy/non-transactional bindings still drain here
        try:
            await self._flush_outbox()
        except Exception:
            logger.exception("final change-log flush failed")
        if self._leader:
            self._leader = False
            # expire in place, NEVER delete: the epoch column must
            # survive graceful handoffs or the successor's acquisition
            # would reuse epoch 1 and fencing monotonicity breaks
            await self.db.execute(
                "UPDATE leadership SET holder = '', expires_at = 0 "
                "WHERE holder = ?",
                (self.identity,),
            )
            self._emit("released")

    async def halt(self) -> None:
        """Hard stop: tasks die, the lease row is left to EXPIRE (the
        fatal path and the harness's leader-kill both come through
        here — a crashed leader deletes nothing)."""
        await self._cancel_tasks()
        self._leader = False

    async def _cancel_tasks(self) -> None:
        for attr in ("_task", "_repl_task"):
            task = getattr(self, attr)
            setattr(self, attr, None)
            if task:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass

    @property
    def is_leader(self) -> bool:
        return self._leader

    def on_leadership_change(
        self, callback: Callable[[bool], Awaitable[None]]
    ) -> None:
        self._callbacks.append(callback)

    # ---- election ----------------------------------------------------

    def _emit(self, event: str, expires_at: float = 0.0) -> None:
        hook = election_tap_hook
        if hook is None:
            return
        try:
            hook({
                "ts": time.time(),
                "identity": self.identity,
                "event": event,
                "epoch": self.epoch,
                "expires_at": expires_at,
                "ttl": self.ttl,
            })
        except Exception:  # noqa: BLE001 — taps never break elections
            logger.exception("election tap failed")

    def _trace(self, name: str) -> None:
        """leader.acquired / leader.lost land in the server trace ring
        so failovers show up next to the requests they affected."""
        import uuid

        from gpustack_tpu.observability import tracing

        tracing.get_store("server").add({
            "trace_id": uuid.uuid4().hex,
            "span_id": uuid.uuid4().hex[:16],
            "component": "server",
            "name": name,
            "started_at": time.time(),
            "duration_ms": 0.0,
            "outcome": "ok",
            "events": [{
                "name": name,
                "identity": self.identity,
                "epoch": self.epoch,
            }],
        })

    async def _loop(self) -> None:
        while True:
            try:
                # chaos hook: a cleared gate freezes elections (renewal
                # AND acquisition), emulating an event-loop stall
                await self.hang_gate.wait()
                now = time.time()
                if self._leader:
                    if not await self._renew(now):
                        # fatal path taken: in production the process
                        # is already dead (os._exit); with an injected
                        # hook, a deposed leader must not linger in the
                        # election and steal leadership right back
                        return
                elif not await self._try_acquire(now):
                    return  # acquisition callbacks failed → fatal
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("coordinator iteration failed")
            await asyncio.sleep(self.ttl / 3)

    async def _renew(self, now: float) -> bool:
        # renew-then-verify instead of UPDATE..RETURNING: the
        # container's sqlite (3.34) predates RETURNING (3.35+). The
        # renewal UPDATE is atomic; the follow-up SELECT can only
        # disagree if the lease was ALREADY lost — exactly the case
        # that must be fatal.
        expires = now + self.ttl
        await self.db.execute(
            "UPDATE leadership SET expires_at = ? "
            "WHERE id = 1 AND holder = ?",
            (expires, self.identity),
        )
        rows = await self.db.execute(
            "SELECT holder, epoch FROM leadership WHERE id = 1"
        )
        if not rows or rows[0]["holder"] != self.identity:
            # lease lost while held: fatal, never split-brain. Queued
            # writes from still-running leader tasks are already
            # rejected by the epoch fence regardless of when this
            # branch notices.
            logger.error(
                "leadership lease lost (held epoch %d); invoking "
                "fatal hook", self.epoch,
            )
            self._leader = False
            self.transitions += 1
            self._emit("lost")
            self._trace("leader.lost")
            self.fatal_hook(self)
            return False
        self._emit("renewed", expires_at=expires)
        return True

    async def _try_acquire(self, now: float) -> bool:
        # atomic conditional upsert (steal only an expired lease, bump
        # the fencing epoch), then read back who holds it — a fresh
        # lease cannot be stolen between the two statements
        expires = now + self.ttl
        await self.db.execute(
            self.db.lease_upsert(),
            self.db.lease_upsert_params(self.identity, expires, now),
        )
        rows = await self.db.execute(
            "SELECT holder, epoch, expires_at FROM leadership "
            "WHERE id = 1"
        )
        if rows and rows[0]["holder"] == self.identity:
            self.epoch = int(rows[0]["epoch"] or 0)
            self._leader = True
            self.transitions += 1
            logger.info(
                "acquired leadership (epoch %d)", self.epoch
            )
            self._emit(
                "acquired", expires_at=float(rows[0]["expires_at"])
            )
            self._trace("leader.acquired")
            try:
                for cb in self._callbacks:
                    await cb(True)
            except asyncio.CancelledError:
                raise
            except Exception:
                # a leader whose leader-only tasks never started must
                # NOT squat on the lease renewing forever — release it
                # and take the fatal path so a healthy peer (or this
                # process's restart) can actually lead
                logger.exception(
                    "leadership callbacks failed; releasing lease "
                    "and invoking fatal hook"
                )
                self._leader = False
                self.transitions += 1
                self._emit("lost")
                self._trace("leader.lost")
                try:
                    await self.db.execute(
                        "UPDATE leadership SET holder = '', "
                        "expires_at = 0 WHERE holder = ?",
                        (self.identity,),
                    )
                except Exception:
                    logger.exception(
                        "could not release the lease; it will expire"
                    )
                self.fatal_hook(self)
                return False
        return True

    # ---- change-log replication --------------------------------------

    def publish_remote(self, event: Event) -> None:
        """Post-commit bus tap. With transactional change-log appends
        active (``db.changelog_origin`` set in :meth:`start`), every
        Record write already committed its own entry — this tap is a
        no-op and the in-memory outbox below survives only as a
        migration shim for bindings without transactional logging
        (e.g. a plugin coordinator delegating here before start)."""
        if getattr(self.db, "changelog_origin", ""):
            return  # entry committed WITH the write; nothing to lose
        if self._republishing:
            return  # never re-log events we just tailed from a peer
        if event.type not in (
            EventType.CREATED, EventType.UPDATED, EventType.DELETED
        ) or not event.kind or event.kind == "*":
            return
        if event.kind in REPLICATION_SKIP_KINDS:
            return
        # carry the changed-field diff (already jsonable — Record.update
        # builds it with _jsonable old/new pairs): peers' changes-gated
        # consumers (route targets, breaker resets, worker-lost edges)
        # must see WHICH fields moved, not just that something did
        changes = None
        if event.changes:
            try:
                changes = json.dumps(event.changes)
            except (TypeError, ValueError):
                changes = None
        self._outbox.append(
            (event.kind, event.type.value, event.id, changes)
        )
        self._outbox_event.set()

    async def _repl_loop(self) -> None:
        interval = max(0.05, self.ttl / 6)
        while True:
            try:
                await self._flush_outbox()
                await self._tail_changes()
                await self._maybe_prune()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("coordinator replication failed")
            try:
                await asyncio.wait_for(
                    self._outbox_event.wait(), timeout=interval
                )
            except asyncio.TimeoutError:
                pass
            self._outbox_event.clear()

    async def _flush_outbox(self) -> None:
        if not self._outbox:
            return
        batch: List[Tuple[str, str, int, Optional[str]]] = []
        while self._outbox:
            batch.append(self._outbox.popleft())
        now = time.time()
        origin = self.identity

        def go(conn):
            try:
                conn.executemany(
                    "INSERT INTO change_log "
                    "(origin, kind, record_id, event_type, changes, "
                    "created_at) VALUES (?, ?, ?, ?, ?, ?)",
                    [
                        (origin, kind, rid, etype, changes, now)
                        for kind, etype, rid, changes in batch
                    ],
                )
                conn.commit()
            except BaseException:
                # never leave a half-inserted batch in an open txn — a
                # later unrelated commit would land it AND the retry,
                # duplicating entries
                conn.rollback()
                raise

        try:
            await self.db.run(go)
        except BaseException:
            # transient insert failure (lock contention, shutdown
            # races): these events have no other path to peers — put
            # them back at the FRONT so order survives the retry
            self._outbox.extendleft(reversed(batch))
            self._outbox_event.set()
            raise

    async def _tail_changes(self) -> None:
        """Republish peers' writes onto the local bus: id-only entries
        in, re-fetched full events out — O(events), not O(tables)."""
        if self.bus is None:
            return
        rows = await self.db.execute(
            "SELECT id, origin, kind, record_id, event_type, changes "
            "FROM change_log WHERE id > ? ORDER BY id "
            f"LIMIT {TAIL_BATCH}",
            (self._last_seen,),
        )
        if not rows:
            return
        batch_top = int(rows[-1]["id"])
        if len(rows) >= TAIL_BATCH:
            # flood: one re-list beats a thousand fetches
            self._last_seen = batch_top
            rows2 = await self.db.execute(
                "SELECT COALESCE(MAX(id), 0) AS top FROM change_log"
            )
            if rows2:
                self._last_seen = max(
                    self._last_seen, int(rows2[0]["top"])
                )
            self.bus.publish(Event(kind="*", type=EventType.RESYNC))
            return
        if self._last_seen and int(rows[0]["id"]) > self._last_seen + 1:
            # front gap: entries between our cursor and the oldest
            # surviving row were PRUNED while this tailer lagged (or a
            # rolled-back insert left an id hole — a false positive
            # costs one harmless re-list). The skipped events are
            # unrecoverable, so degrade to RESYNC for local watchers
            # and dirty-set consumers.
            self._last_seen = batch_top
            self.bus.publish(Event(kind="*", type=EventType.RESYNC))
            return
        # one event PER ENTRY, each carrying its own changed-field
        # diff: changes-gated consumers (route targets, breaker
        # resets, worker-lost edges) need every transition, and the
        # per-subscriber queues already coalesce runs of UPDATED with
        # correct change merging (bus.py). Document re-fetches are
        # batched PER KIND per flushed batch (Record.get_many): at
        # high peer write rates a 1000-entry batch over three kinds
        # costs three IN queries, not a thousand point reads.
        from gpustack_tpu.orm.record import registered_records

        registry = registered_records()
        need: dict = {}          # kind -> set of ids to re-fetch
        for row in rows:
            if row["origin"] == self.identity:
                continue
            if row["event_type"] == EventType.DELETED.value:
                continue
            if registry.get(row["kind"]) is not None:
                need.setdefault(row["kind"], set()).add(
                    int(row["record_id"])
                )
        docs: dict = {}          # (kind, id) -> json doc | None
        for kind, ids in need.items():
            fetched = await registry[kind].get_many(ids)
            for rid in ids:
                obj = fetched.get(rid)
                docs[(kind, rid)] = (
                    None if obj is None
                    else obj.model_dump(mode="json")
                )
        events: List[Event] = []
        for row in rows:
            if row["origin"] == self.identity:
                continue
            kind = row["kind"]
            rid = int(row["record_id"])
            etype = row["event_type"]
            changes = None
            if row["changes"]:
                try:
                    changes = json.loads(row["changes"])
                except ValueError:
                    changes = None
            if etype == EventType.DELETED.value:
                events.append(Event(
                    kind=kind, type=EventType.DELETED, id=rid,
                    remote=True,
                ))
                continue
            doc = docs.get((kind, rid))
            if doc is None:
                continue  # unknown kind, or deleted since (its
                #           DELETED entry follows in this same batch)
            events.append(Event(
                kind=kind,
                type=EventType(etype),
                id=rid,
                data=doc,
                changes=changes,
                remote=True,
            ))
        if not events:
            self._last_seen = batch_top
            return
        self._republishing = True
        try:
            for event in events:
                self.bus.publish(event)
        finally:
            self._republishing = False
        # advance the cursor only AFTER the batch fully republished:
        # a re-fetch/publish failure re-tails the same rows next cycle
        # (re-fetched republishes are upsert-shaped, so duplicates are
        # harmless) instead of silently dropping peers' events
        self._last_seen = batch_top

    async def _maybe_prune(self) -> None:
        """Leader-only, occasional: the change log is a propagation
        buffer, not history — entries older than every live peer's tail
        position (bounded by a generous multiple of the TTL) go."""
        now = time.time()
        if not self._leader or now < self._prune_at:
            return
        self._prune_at = now + max(10.0, self.ttl * 2)
        keep = max(60.0, self.ttl * 20)
        await self.db.execute(
            "DELETE FROM change_log WHERE created_at < ?",
            (now - keep,),
        )
